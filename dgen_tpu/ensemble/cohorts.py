"""Dynamic agent populations on the alive-mask data plane.

JAX programs cannot grow arrays mid-horizon, so "new construction
enters in 2032" becomes: the cohort rows exist in the fixed-capacity
table from year 0 — placed, partitioned, clustered, and quantized with
everyone else — but carry ``mask = 0`` until their entry year, when a
tiny jitted mask update flips them alive. PR 13's quarantine proof is
what makes this free: masked rows contribute exact zeros to every
reduction, so the compiled year-step program is literally the same
program before and after entry (the mask is a traced operand, never a
shape). This is the padded-table + alive-mask pattern ABMax
(PAPERS.md) uses for birth/death in JAX ABMs, applied to dGen's
fixed-horizon sweep.

Entry scheduling is one f32 row vector ``entry_year`` aligned with the
PLACED table (use :func:`align_entry` to push an input-table-order
vector through ``Simulation.host_row_origin``):

* ``0.0`` — alive from the start (every pre-existing row);
* calendar year (e.g. ``2032.0``) — flips alive when the model year
  reaches it;
* :data:`COHORT_NEVER` — never alive (padding / quarantined rows).

Electrification / EV load growth rides the existing year-indexed
``load_growth`` trajectory rather than mutating profile banks:
:func:`electrified_load_growth` compounds an extra annual growth rate
into the [Y, R, S] multiplier, which ``apply_year`` already gathers
per agent — no new compiled program, no bank copies.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

#: Entry-year sentinel for rows that never become alive (padding and
#: quarantined rows). Far above any calendar year yet exactly
#: representable in f32, so ``entry_year <= year`` is a clean compare.
COHORT_NEVER = 9.0e9


@dataclasses.dataclass(frozen=True)
class CohortSchedule:
    """Host-side description of a cohort entry plan: ``entry_year[i]``
    is the calendar year input-table row i becomes alive (0.0 =
    alive-at-start, COHORT_NEVER = never)."""

    entry_year: np.ndarray  # [N_input] f32, input-table row order

    def __post_init__(self) -> None:
        e = np.asarray(self.entry_year, dtype=np.float32)
        object.__setattr__(self, "entry_year", e)
        if e.ndim != 1:
            raise ValueError(f"entry_year must be 1-D, got shape {e.shape}")

    @property
    def n_cohort_rows(self) -> int:
        e = self.entry_year
        return int(np.sum((e > 0.0) & (e < COHORT_NEVER)))

    def counts_by_year(self) -> Dict[int, int]:
        """{calendar year: rows entering} for logging / world.json."""
        e = self.entry_year
        sel = (e > 0.0) & (e < COHORT_NEVER)
        ys, cs = np.unique(e[sel].astype(np.int64), return_counts=True)
        return {int(y): int(c) for y, c in zip(ys, cs)}


@jax.jit
def cohort_alive_mask(
    mask_pot: jax.Array, entry_year: jax.Array, year_f: jax.Array
) -> jax.Array:
    """[N] alive mask for model year ``year_f`` (f32 0-d): the
    potential mask gated by ``entry_year <= year``. This is the whole
    per-year population dynamics program — registered in the prog-audit
    registry (entry ``cohort_mask_update``) so its fingerprint is
    pinned like every other compiled program in the system."""
    return mask_pot * (entry_year <= year_f).astype(mask_pot.dtype)


def alive_mask_np(
    mask_pot: np.ndarray, entry_year: np.ndarray, year: float
) -> np.ndarray:
    """NumPy oracle for :func:`cohort_alive_mask` (tests)."""
    return np.asarray(mask_pot, np.float32) * (
        np.asarray(entry_year, np.float32) <= np.float32(year)
    ).astype(np.float32)


def potential_mask(
    base_mask: np.ndarray, entry_year: np.ndarray
) -> np.ndarray:
    """[N] f32 potential-population mask: base-alive rows PLUS every
    cohort row that will ever enter. The ensemble driver hands
    ``Simulation`` a table carrying THIS mask so placement decisions
    (state partitioning, tariff clustering, net-billing flags, chunk
    padding) are made once over the full potential population —
    conservative and numerically exact, since pre-entry rows are
    re-masked to zero each year by :func:`cohort_alive_mask`."""
    base = np.asarray(base_mask, np.float32)
    e = np.asarray(entry_year, np.float32)
    will_enter = ((e > 0.0) & (e < COHORT_NEVER)).astype(np.float32)
    return np.maximum(base, will_enter)


def align_entry(
    entry_input: np.ndarray, host_row_origin: np.ndarray
) -> np.ndarray:
    """Push an input-table-order entry vector through the composed
    placement permutation (``Simulation.host_row_origin``): placed rows
    inherit their origin row's entry year; rows with origin -1
    (per-shard / chunk padding) get :data:`COHORT_NEVER`."""
    origin = np.asarray(host_row_origin, np.int64)
    entry = np.asarray(entry_input, np.float32)
    out = np.full(origin.shape, COHORT_NEVER, dtype=np.float32)
    sel = origin >= 0
    out[sel] = entry[origin[sel]]
    return out


def electrified_load_growth(
    load_growth: np.ndarray,
    years: Sequence[int],
    annual_rate: float,
    start_year: int | None = None,
    sectors: Sequence[int] | None = None,
) -> jnp.ndarray:
    """[Y, R, S] load-growth multiplier with electrification / EV
    uptake compounded on top: from ``start_year`` (default: the first
    model year) each subsequent year multiplies demand by
    ``(1 + annual_rate)``. ``sectors`` restricts the transform (e.g.
    ``(0,)`` = residential EV charging); default applies to all.

    Pure input transform — ``apply_year`` gathers it like any other
    trajectory, so dynamic demand costs zero new compiled programs.
    """
    lg = np.array(load_growth, dtype=np.float32, copy=True)
    ys = np.asarray(list(years), dtype=np.int64)
    y0 = int(start_year) if start_year is not None else int(ys[0])
    exponent = np.maximum(ys - y0, 0).astype(np.float32)
    factor = (1.0 + float(annual_rate)) ** exponent      # [Y]
    s_sel = (
        np.asarray(list(sectors), np.int64)
        if sectors is not None
        else np.arange(lg.shape[2])
    )
    lg[:, :, s_sel] *= factor[:, None, None]
    return jnp.asarray(lg)
