"""On-device ensemble reductions: per-member aggregates + per-year
quantiles, so host traffic stays O(quantiles) per year — never O(E x N)
agent rows.

The contract with the driver: for each model year, the [E, N] (or, in
loop mode, [N]) :class:`YearOutputs` leaves are reduced ON DEVICE to
per-member national/state aggregates (:func:`member_aggregates` — the
same mask-weighted sums as ``SimResults.summary``), and in vmap mode
the member axis is further collapsed to quantiles on device
(:func:`year_quantiles`), so the per-year fetch is a handful of [Q]
vectors. Loop mode fetches one scalar block per (member, year) and
quantiles on the host at the end — both paths use linear-interpolation
quantiles (``jnp.quantile`` == ``np.quantile`` default), which the
small-E NumPy-reference test pins.

Per-state aggregates use ``jax.ops.segment_sum`` over ``state_idx``
(vmapped over members) — NOT a one-hot matmul, which at 10M agents x
51 states would materialize a 2 TB intermediate.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: metric name -> YearOutputs field behind it. The four headline
#: curves, matching ``SimResults.summary`` exactly.
METRIC_FIELDS: Dict[str, str] = {
    "adopters": "number_of_adopters",
    "system_kw_cum": "system_kw_cum",
    "batt_kwh_cum": "batt_kwh_cum",
    "new_adopters": "new_adopters",
}

#: metrics also reduced per state (kept to the two the NEM cap and
#: state policy questions need; each costs [E, n_states] on device)
STATE_METRICS: Tuple[str, ...] = ("adopters", "system_kw_cum")

DEFAULT_QUANTILES: Tuple[float, ...] = (0.1, 0.5, 0.9)


@partial(jax.jit, static_argnames=("n_states",))
def member_aggregates(outs, mask, state_idx, *, n_states: int):
    """(national, state) aggregate dicts for one model year.

    ``outs`` leaves may be [N] (loop mode: one member) or [E, N] (vmap
    mode: the whole ensemble); ``mask``/``state_idx`` are [N], shared —
    members never disagree about who is alive. Returns national sums
    shaped [] / [E] and state sums [n_states] / [E, n_states].
    """
    mask = mask.astype(jnp.float32)

    def seg(x):
        return jax.ops.segment_sum(
            x * mask, state_idx, num_segments=n_states
        )

    national = {}
    state = {}
    for name, field in METRIC_FIELDS.items():
        leaf = getattr(outs, field)
        if leaf.ndim == 2:
            national[name] = jnp.sum(leaf * mask[None, :], axis=1)
        else:
            national[name] = jnp.sum(leaf * mask)
        if name in STATE_METRICS:
            state[name] = jax.vmap(seg)(leaf) if leaf.ndim == 2 else seg(leaf)
    return national, state


@jax.jit
def year_quantiles(agg, qs: jax.Array):
    """Collapse the leading member axis of every aggregate leaf to
    quantiles ``qs`` on device: [E] -> [Q], [E, n_states] ->
    [Q, n_states] (linear interpolation, numpy-default semantics)."""
    return jax.tree.map(lambda a: jnp.quantile(a, qs, axis=0), agg)


def quantiles_np(curves: np.ndarray, qs: Sequence[float]) -> np.ndarray:
    """NumPy reference: ``curves`` [E, ...] -> [Q, ...] (tests pin the
    device path against this at small E)."""
    return np.quantile(
        np.asarray(curves, np.float64), np.asarray(qs), axis=0
    ).astype(np.float32)


@dataclasses.dataclass(frozen=True)
class EnsembleStats:
    """The ensemble's answer: per-year quantile bands of the headline
    adoption curves, national and per state.

    ``national[metric]`` is [Y, Q]; ``state[metric]`` is
    [Y, Q, n_states]; ``quantiles`` orders the Q axis.
    """

    years: np.ndarray                   # [Y] calendar years, int64
    quantiles: Tuple[float, ...]
    n_members: int
    national: Dict[str, np.ndarray]
    state: Dict[str, np.ndarray]

    def band(self, metric: str = "adopters") -> Dict[str, np.ndarray]:
        """{"p10": [Y], ...} for one national metric — the headline
        "10th-90th percentile adoption band" accessor."""
        arr = self.national[metric]
        return {
            f"p{round(q * 100):02d}": arr[:, i]
            for i, q in enumerate(self.quantiles)
        }

    def to_json(self) -> Dict[str, object]:
        return {
            "years": [int(y) for y in np.asarray(self.years)],
            "quantiles": [float(q) for q in self.quantiles],
            "n_members": int(self.n_members),
            "national": {
                k: np.asarray(v, np.float64).tolist()
                for k, v in self.national.items()
            },
            "state": {
                k: np.asarray(v, np.float64).tolist()
                for k, v in self.state.items()
            },
        }

    @classmethod
    def from_json(cls, d: Dict[str, object]) -> "EnsembleStats":
        return cls(
            years=np.asarray(d["years"], np.int64),
            quantiles=tuple(float(q) for q in d["quantiles"]),
            n_members=int(d["n_members"]),
            national={
                k: np.asarray(v, np.float32)
                for k, v in d.get("national", {}).items()
            },
            state={
                k: np.asarray(v, np.float32)
                for k, v in d.get("state", {}).items()
            },
        )

    def frame(self):
        """Long-form pandas frame (one row per year x quantile, one
        column per national metric) for parquet export."""
        import pandas as pd

        years = np.asarray(self.years)
        rows = {
            "year": np.repeat(years, len(self.quantiles)),
            "quantile": np.tile(np.asarray(self.quantiles), len(years)),
        }
        for k, v in self.national.items():
            rows[k] = np.asarray(v, np.float64).reshape(-1)
        return pd.DataFrame(rows)


def stats_from_year_blocks(
    years: Sequence[int],
    quantiles: Sequence[float],
    n_members: int,
    blocks: Dict[int, Dict[str, Dict[str, np.ndarray]]],
) -> EnsembleStats:
    """Assemble :class:`EnsembleStats` from vmap-mode per-year quantile
    fetches: ``blocks[year_idx] = {"national": {m: [Q]}, "state":
    {m: [Q, n_states]}}``. Missing years raise — a resume that skipped
    a year is a bug, not a gap to interpolate."""
    years = np.asarray(list(years), np.int64)
    missing = [i for i in range(len(years)) if i not in blocks]
    if missing:
        raise ValueError(f"missing ensemble stats for year indices {missing}")
    national = {
        m: np.stack([np.asarray(blocks[i]["national"][m]) for i in range(len(years))])
        for m in METRIC_FIELDS
    }
    state = {
        m: np.stack([np.asarray(blocks[i]["state"][m]) for i in range(len(years))])
        for m in STATE_METRICS
    }
    return EnsembleStats(
        years=years,
        quantiles=tuple(float(q) for q in quantiles),
        n_members=int(n_members),
        national=national,
        state=state,
    )


def stats_from_member_aggregates(
    years: Sequence[int],
    quantiles: Sequence[float],
    national_curves: Dict[str, np.ndarray],
    state_curves: Dict[str, np.ndarray],
) -> EnsembleStats:
    """Assemble :class:`EnsembleStats` from loop-mode per-member
    fetches: ``national_curves[m]`` is [E, Y], ``state_curves[m]`` is
    [E, Y, n_states]; quantiles taken on host with the same linear
    interpolation the device path uses."""
    qs = tuple(float(q) for q in quantiles)
    some = next(iter(national_curves.values()))
    n_members = int(np.asarray(some).shape[0])
    national = {
        # [E, Y] -> [Q, Y] -> [Y, Q]
        m: quantiles_np(v, qs).transpose(1, 0)
        for m, v in national_curves.items()
    }
    state = {
        # [E, Y, n_st] -> [Q, Y, n_st] -> [Y, Q, n_st]
        m: quantiles_np(v, qs).transpose(1, 0, 2)
        for m, v in state_curves.items()
    }
    return EnsembleStats(
        years=np.asarray(list(years), np.int64),
        quantiles=qs,
        n_members=n_members,
        national=national,
        state=state,
    )
