"""The ensemble driver: E Monte-Carlo members in one program against
one placed table, with dynamic cohort populations.

Rides the sweep engine's machinery end to end: ``plan_sweep`` budgets
the member axis through its ``n_members`` term (members batch exactly
like scenarios — one [E, N] carry row-set, one shared copy of the
multi-GB banks), and execution is the same vmap/loop duality:

* **vmap mode** — :func:`ensemble_year_step` vmaps ``year_step_impl``
  over the member axis of (inputs, carry) with table/banks closed over
  UNMAPPED; when cohorts are scheduled the shared alive mask is
  computed ONCE inside the program (members never disagree about who
  exists) and fused ahead of the vmap;
* **loop mode** — member-major over the ONE compiled single-member
  executable (``with_inputs`` siblings) when E doesn't fit the HBM
  model; member 1..E-1 must compile NOTHING (cross-member
  RetraceGuard). ``E == 1`` is FORCED onto this path so that a
  zero-width-draw ensemble is byte-identical to ``Simulation.run`` —
  the member loop then drives the base Simulation itself, stepping the
  very same compiled program with the very same operands.

Per-year statistics reduce on device (:mod:`dgen_tpu.ensemble.stats`):
vmap mode fetches [Q]-sized quantile blocks, loop mode one scalar
block per (member, year) — host traffic is O(quantiles), never
O(E x N). Checkpoint/resume is (member, year)-grained: loop mode lays
out ``mem=<m>/`` subdirectories (:func:`dgen_tpu.io.checkpoint.
member_dir`), vmap mode saves the stacked [E, N] carry like a vmapped
sweep group, and the partial statistics ride the checkpoint directory
as a JSON sidecar so a resumed run's quantiles cover the full horizon.
"""

from __future__ import annotations

import dataclasses
import os
from functools import partial
from typing import Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from dgen_tpu.config import RunConfig, ScenarioConfig
from dgen_tpu.ensemble import stats as estats
from dgen_tpu.ensemble.cohorts import (
    CohortSchedule,
    align_entry,
    cohort_alive_mask,
    potential_mask,
)
from dgen_tpu.ensemble.draws import DrawSpec, draw_members
from dgen_tpu.models.scenario import ScenarioInputs, stack_scenarios
from dgen_tpu.models.simulation import (
    YEAR_STEP_STATIC_ARGNAMES,
    SimCarry,
    SimResults,
    Simulation,
    YearOutputs,
    year_step_impl,
)
from dgen_tpu.resilience.atomic import atomic_write_json
from dgen_tpu.sweep.driver import bank_nbytes
from dgen_tpu.sweep.plan import MODE_LOOP, MODE_VMAP, SweepPlan, plan_sweep
from dgen_tpu.sweep.results import SweepResults
from dgen_tpu.utils import timing
from dgen_tpu.utils.logging import get_logger

logger = get_logger()

#: env knobs (documented in docs/userguide.md): default member count
#: and draw seed when the constructor arguments are omitted
ENV_MEMBERS = "DGEN_TPU_ENSEMBLE"
ENV_SEED = "DGEN_TPU_ENSEMBLE_SEED"

#: stats sidecar in the checkpoint directory — partial per-year
#: aggregates persisted incrementally so (member, year) resume can
#: still produce full-horizon quantiles
STATS_FILE = "ensemble_stats.json"

#: vmap mode's stacked-carry checkpoint subdirectory key (the analogue
#: of a sweep group's ``scn=<group>/``)
_VMAP_CKPT_KEY = "members"


@partial(
    jax.jit,
    static_argnames=YEAR_STEP_STATIC_ARGNAMES,
    donate_argnames=("carry",),
)
def ensemble_year_step(
    table,
    profiles,
    tariffs,
    inputs_e,           # ScenarioInputs with [E, ...] leaves
    entry_year,         # [N] f32 cohort entry years, or None
    year_f,             # 0-d f32 calendar year, or None
    carry,              # SimCarry with [E, N] leaves
    year_idx,
    *,
    n_periods: int,
    econ_years: int,
    sizing_iters: int,
    first_year: bool,
    with_hourly: bool,
    storage_enabled: bool,
    year_step_len: float,
    sizing_impl: str = "auto",
    rate_switch: bool = False,
    mesh=None,
    agent_chunk: int = 0,
    net_billing: bool = True,
    daylight=None,
    pack_once: bool = False,
    soft_tau=None,
    anchor: bool = True,
    cluster=None,
    cluster_banks=None,
    cluster_tidx=None,
):
    """One model year for E ensemble members as a single device
    program: ``year_step_impl`` vmapped over the member axis of
    (inputs, carry), table and banks closed over UNMAPPED — the member
    analogue of ``sweep_year_step``, plus the cohort data plane: when
    ``entry_year`` is given, the shared alive mask
    ``mask * (entry_year <= year)`` is computed ONCE ahead of the vmap
    (members share one population, so aliveness is member-invariant).
    ``year_f`` is a traced 0-d f32 — the year value changes every step
    without retracing, exactly like ``year_idx``."""
    if entry_year is not None:
        table = dataclasses.replace(
            table,
            mask=table.mask * (entry_year <= year_f).astype(table.mask.dtype),
        )

    def one(inputs, c):
        return year_step_impl(
            table, profiles, tariffs, inputs, c, year_idx,
            n_periods=n_periods, econ_years=econ_years,
            sizing_iters=sizing_iters, first_year=first_year,
            with_hourly=with_hourly, storage_enabled=storage_enabled,
            year_step_len=year_step_len, sizing_impl=sizing_impl,
            rate_switch=rate_switch, mesh=mesh, agent_chunk=agent_chunk,
            net_billing=net_billing, daylight=daylight,
            pack_once=pack_once, soft_tau=soft_tau, anchor=anchor,
            cluster=cluster, cluster_banks=cluster_banks,
            cluster_tidx=cluster_tidx,
        )

    return jax.vmap(one)(inputs_e, carry)


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    return int(raw) if raw else default


class EnsembleSimulation:
    """Run an E-member Monte-Carlo ensemble over one shared population
    (the ensemble analogue of ``SweepSimulation``).

    Parameters
    ----------
    table, profiles, tariffs : the shared population and banks, placed
        once through Simulation's placement path.
    inputs : the BASE ScenarioInputs; member m perturbs it per
        ``draws`` with the restart-stable key ``fold_in(seed, m)``.
    scenario : ScenarioConfig.
    n_members : ensemble width E (default: env ``DGEN_TPU_ENSEMBLE``,
        else 1).
    seed : draw seed (default: env ``DGEN_TPU_ENSEMBLE_SEED``, else 0).
    draws : DrawSpec; the default zero-width spec perturbs nothing —
        members are then literal copies of the base (the byte-parity
        configuration).
    entry_year : optional cohort schedule (CohortSchedule or [N] f32
        in INPUT-table row order): 0 = alive at start, calendar year =
        cohort entry, COHORT_NEVER = never. The driver hands Simulation
        the potential-population mask so placement sees every row that
        will ever exist, then re-derives aliveness per year.
    quantiles : per-year quantile levels (default p10/p50/p90).
    max_vmap_members : forwarded to the planner's vmap width cap.
    Other parameters match Simulation.
    """

    def __init__(
        self,
        table,
        profiles,
        tariffs,
        inputs: ScenarioInputs,
        scenario: ScenarioConfig,
        run_config: Optional[RunConfig] = None,
        *,
        n_members: Optional[int] = None,
        seed: Optional[int] = None,
        draws: Optional[DrawSpec] = None,
        entry_year: Union[CohortSchedule, np.ndarray, None] = None,
        mesh=None,
        with_hourly: bool = False,
        econ_years: int = 25,
        quantiles: Sequence[float] = estats.DEFAULT_QUANTILES,
        max_vmap_members: Optional[int] = None,
        plan: Optional[SweepPlan] = None,
    ) -> None:
        self.n_members = (
            int(n_members) if n_members is not None
            else _env_int(ENV_MEMBERS, 1)
        )
        if self.n_members < 1:
            raise ValueError(f"n_members must be >= 1, got {self.n_members}")
        self.seed = int(seed) if seed is not None else _env_int(ENV_SEED, 0)
        self.draws = draws if draws is not None else DrawSpec()
        self.inputs = inputs
        self.scenario = scenario
        self.run_config = run_config or RunConfig()
        self.mesh = mesh
        self.with_hourly = with_hourly
        self.quantiles = tuple(float(q) for q in quantiles)
        self.labels = [f"mem{m:03d}" for m in range(self.n_members)]

        if isinstance(entry_year, CohortSchedule):
            entry_input = entry_year.entry_year
        elif entry_year is not None:
            entry_input = np.asarray(entry_year, np.float32)
        else:
            entry_input = None
        if entry_input is not None:
            if len(entry_input) != table.n_agents:
                raise ValueError(
                    f"entry_year covers {len(entry_input)} rows but the "
                    f"table has {table.n_agents}"
                )
            # placement must see the POTENTIAL population: every row
            # that will ever be alive participates in partitioning,
            # clustering, static-flag proofs and chunk padding (all
            # conservative over a superset); per-year aliveness is then
            # re-derived from entry_year on the data plane
            table = dataclasses.replace(
                table,
                mask=jnp.asarray(
                    potential_mask(np.asarray(table.mask), entry_input)
                ),
            )
        self._entry_input = entry_input

        #: member m's ScenarioInputs — pure function of (base, seed, m);
        #: with a zero-width DrawSpec every element IS the base object
        self.members: List[ScenarioInputs] = draw_members(
            inputs, self.draws, self.n_members, self.seed
        )

        years = list(scenario.model_years)
        self.plan = plan if plan is not None else plan_sweep(
            [inputs], years,
            table=table, tariffs=tariffs,
            with_hourly=with_hourly, econ_years=econ_years,
            sizing_iters=self.run_config.sizing_iters,
            bank_bf16=self.run_config.bf16_banks,
            bank_quant=self.run_config.quant_banks,
            mesh=mesh,
            max_vmap_scenarios=max_vmap_members,
            cluster=self.run_config.cluster_tariffs,
            agent_pad_multiple=self.run_config.agent_pad_multiple,
            n_members=self.n_members,
        )

        rc = self.run_config
        if self.plan.agent_chunk is not None and rc.agent_chunk is None:
            rc = dataclasses.replace(rc, agent_chunk=self.plan.agent_chunk)
        self.base = Simulation(
            table, profiles, tariffs, inputs, scenario, rc,
            mesh=mesh, with_hourly=with_hourly, econ_years=econ_years,
        )
        self.years = self.base.years
        self.bank_bytes_shared = bank_nbytes(self.base.profiles)

        group = self.plan.groups[0]
        self.net_billing = group.net_billing
        # E=1 is pinned to the member-major loop: the single member
        # then steps the base Simulation's own compiled program with
        # its own operands — byte-identical to Simulation.run, which a
        # vmapped E=1 program (different executable) could not promise
        self.mode = MODE_LOOP if self.n_members == 1 else group.mode

        # cohort operands on device, aligned with the PLACED row order
        # (host_row_origin composes partition/chunk/cluster gathers)
        if entry_input is not None:
            aligned = align_entry(entry_input, self.base.host_row_origin)
            entry_dev = jnp.asarray(aligned)
            mask_pot = self.base.table.mask
            if self.base._shard is not None:
                entry_dev = self.base._put(entry_dev, self.base._shard)
            self._entry_dev = entry_dev
            self._mask_pot_dev = mask_pot
        else:
            self._entry_dev = None
            self._mask_pot_dev = None

        logger.info(
            "ensemble: E=%d seed=%d draws=%s cohorts=%s -> %s mode "
            "(net_billing=%s, agent_chunk=%s)",
            self.n_members, self.seed,
            "zero" if self.draws.is_zero else "on",
            "none" if entry_input is None else
            f"{int(np.sum((entry_input > 0) & (entry_input < 1e9)))} rows",
            self.mode, self.net_billing, self.plan.agent_chunk,
        )

    # -- stats sidecar --------------------------------------------------

    def _stats_path(self, checkpoint_dir: str) -> str:
        return os.path.join(checkpoint_dir, STATS_FILE)

    def _load_stats_state(self, checkpoint_dir: Optional[str],
                          mode: str) -> Optional[dict]:
        if not checkpoint_dir:
            return None
        path = self._stats_path(checkpoint_dir)
        if not os.path.exists(path):
            return None
        import json

        with open(path) as f:
            state = json.load(f)
        if (
            state.get("mode") != mode
            or int(state.get("n_members", -1)) != self.n_members
            or list(state.get("quantiles", [])) != list(self.quantiles)
        ):
            logger.warning(
                "ensemble stats sidecar %s does not match this run "
                "(mode/members/quantiles); ignoring it", path,
            )
            return None
        return state

    # -- loop mode ------------------------------------------------------

    def _run_loop(self, collect: bool, checkpoint_dir: Optional[str],
                  resume: bool):
        from dgen_tpu.io import checkpoint as ckpt

        E = self.n_members
        years = self.years
        n_states = self.base.table.n_states
        state_idx = self.base.table.state_idx
        rc = self.run_config
        agent_fields = [
            f.name for f in dataclasses.fields(YearOutputs)
            if f.name != "state_hourly_net_mw"
        ]

        nat_curves = {
            m: np.full((E, len(years)), np.nan, np.float64)
            for m in estats.METRIC_FIELDS
        }
        st_curves = {
            m: np.full((E, len(years), n_states), np.nan, np.float64)
            for m in estats.STATE_METRICS
        }
        if resume:
            state = self._load_stats_state(checkpoint_dir, "loop")
            if state is not None:
                for m, v in state.get("national", {}).items():
                    nat_curves[m][:] = np.asarray(v, np.float64)
                for m, v in state.get("state", {}).items():
                    st_curves[m][:] = np.asarray(v, np.float64)

        def persist() -> None:
            if not checkpoint_dir:
                return
            atomic_write_json(self._stats_path(checkpoint_dir), {
                "mode": "loop",
                "n_members": E,
                "quantiles": list(self.quantiles),
                "national": {m: v.tolist() for m, v in nat_curves.items()},
                "state": {m: v.tolist() for m, v in st_curves.items()},
            })

        results: List[SimResults] = []
        cross_guard = None
        try:
            for mi in range(E):
                member = self.members[mi]
                if member is self.inputs and self._entry_dev is None:
                    # zero-width draws, no cohorts: the member IS the
                    # base — drive the base Simulation itself, so the
                    # E=1 ensemble is byte-identical to Simulation.run
                    sib = self.base
                else:
                    sib = self.base.with_inputs(
                        member, net_billing=self.net_billing,
                        timing_ctx=self.labels[mi],
                    )
                mdir = (
                    ckpt.member_dir(checkpoint_dir, mi)
                    if checkpoint_dir else None
                )
                start_idx = 0
                carry = sib.init_carry()
                if resume and mdir:
                    last = ckpt.latest_year(mdir)
                    if last is not None and last not in years:
                        raise ValueError(
                            f"checkpointed year {last} of member {mi} is "
                            f"not on the year grid {years}; refusing to "
                            "resume"
                        )
                    if last is not None:
                        _, carry = ckpt.restore_year(
                            mdir, self.base.table.n_agents, last,
                            sharding=self.base._shard,
                        )
                        start_idx = years.index(last) + 1
                        logger.info(
                            "ensemble member %d: resuming after year %d",
                            mi, last,
                        )
                writer = ckpt.Writer(mdir) if mdir else None
                collected: Dict[str, list] = {k: [] for k in agent_fields}
                hourly: List[np.ndarray] = []
                steady_guard = None
                try:
                    for yi, year in enumerate(years):
                        if yi < start_idx:
                            continue
                        if (
                            rc.guard_retrace and steady_guard is None
                            and cross_guard is None
                            and yi - start_idx >= 2
                        ):
                            from dgen_tpu.lint.guard import RetraceGuard

                            steady_guard = RetraceGuard(
                                context="ensemble member steady state"
                            ).start()
                        if self._entry_dev is not None:
                            alive = cohort_alive_mask(
                                self._mask_pot_dev, self._entry_dev,
                                jnp.asarray(float(year), jnp.float32),
                            )
                            sib.table = dataclasses.replace(
                                sib.table, mask=alive
                            )
                        else:
                            alive = sib.table.mask
                        with timing.timer(
                            "ensemble_year_step", ctx=self.labels[mi]
                        ):
                            carry, outs = sib.step(
                                carry, yi, first_year=(yi == 0)
                            )
                        nat, st = estats.member_aggregates(
                            outs, alive, state_idx, n_states=n_states
                        )
                        # a scalar block per (member, year) — the
                        # O(quantiles) contract, not a bulk D2H copy
                        host = jax.device_get(  # dgenlint: disable=L9
                            {"nat": nat, "st": st}
                        )
                        for k, v in host["nat"].items():
                            nat_curves[k][mi, yi] = float(v)
                        for k, v in host["st"].items():
                            st_curves[k][mi, yi] = np.asarray(v)
                        if collect:
                            fetch = {
                                k: getattr(outs, k) for k in agent_fields
                            }
                            if self.with_hourly:
                                fetch["_hourly"] = outs.state_hourly_net_mw
                            h = jax.device_get(fetch)  # dgenlint: disable=L9
                            for k in agent_fields:
                                collected[k].append(h[k])
                            if self.with_hourly:
                                hourly.append(h["_hourly"])
                        if writer is not None:
                            writer.save(year, carry)
                            persist()
                        if steady_guard is not None:
                            steady_guard.check(f"year {year}")
                        if cross_guard is not None:
                            cross_guard.check(
                                f"member {mi} year {year}"
                            )
                finally:
                    if steady_guard is not None:
                        steady_guard.stop()
                    if writer is not None:
                        writer.close()
                run_years = years[start_idx:]
                agent = (
                    {k: np.stack(v) for k, v in collected.items()}
                    if collect and collected[agent_fields[0]] else {}
                )
                results.append(SimResults(
                    years=list(run_years),
                    agent=agent,
                    state_hourly_net_mw=(
                        np.stack(hourly) if hourly else None
                    ),
                ))
                if (
                    rc.guard_retrace and cross_guard is None
                    and mi == 0 and E > 1
                ):
                    # member 0 compiled the program set; every later
                    # member must compile NOTHING
                    from dgen_tpu.lint.guard import RetraceGuard

                    cross_guard = RetraceGuard(
                        context="ensemble cross-member"
                    ).start()
        finally:
            if cross_guard is not None:
                cross_guard.stop()
        persist()

        if any(np.isnan(v).any() for v in nat_curves.values()):
            logger.warning(
                "ensemble stats are incomplete (resumed without a "
                "stats sidecar?) — quantiles will carry NaNs"
            )
        stats = estats.stats_from_member_aggregates(
            years, self.quantiles, nat_curves, st_curves
        )
        return results, stats

    # -- vmap mode ------------------------------------------------------

    def _init_stacked_carry(self) -> SimCarry:
        n = self.base.table.n_agents
        zeros = SimCarry.zeros(n)
        return jax.tree.map(
            lambda x: jnp.zeros((self.n_members,) + x.shape, x.dtype),
            zeros,
        )

    def _run_vmap(self, collect: bool, checkpoint_dir: Optional[str],
                  resume: bool):
        from dgen_tpu.io import checkpoint as ckpt

        E = self.n_members
        years = self.years
        rc = self.run_config
        n_states = self.base.table.n_states
        state_idx = self.base.table.state_idx
        inputs_e = stack_scenarios(self.members).inputs
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(self.mesh, P())
            inputs_e = jax.tree.map(
                lambda x: self.base._put(x, repl), inputs_e
            )

        kwargs = self.base.step_kwargs(first_year=True)
        kwargs["net_billing"] = self.net_billing
        # the planner routes >1-device meshes to loop mode; a 1-device
        # mesh adds nothing inside the vmapped body (same as sweeps)
        kwargs["mesh"] = None
        if kwargs.get("cluster") is not None:
            kwargs["cluster"] = kwargs["cluster"].pin_net_billing(
                self.net_billing
            )
        kwargs.update(self.base.step_operands())

        carry = self._init_stacked_carry()
        start_idx = 0
        writer = None
        if resume:
            if not checkpoint_dir:
                raise ValueError("resume=True requires checkpoint_dir")
            last = ckpt.latest_year(checkpoint_dir, scenario=_VMAP_CKPT_KEY)
            if last is not None and last not in years:
                raise ValueError(
                    f"checkpointed year {last} is not on the year grid "
                    f"{years}; refusing to resume"
                )
            if last is not None:
                _, carry = ckpt.restore_year(
                    checkpoint_dir, self.base.table.n_agents, last,
                    scenario=_VMAP_CKPT_KEY, n_scenarios=E,
                )
                start_idx = years.index(last) + 1
                logger.info(
                    "ensemble (vmap): resuming after year %d", last
                )
        if checkpoint_dir is not None:
            writer = ckpt.Writer(checkpoint_dir, scenario=_VMAP_CKPT_KEY)

        blocks: Dict[int, dict] = {}
        if resume:
            state = self._load_stats_state(checkpoint_dir, "vmap")
            if state is not None:
                blocks = {
                    int(k): {
                        "national": {
                            m: np.asarray(a, np.float32)
                            for m, a in v["national"].items()
                        },
                        "state": {
                            m: np.asarray(a, np.float32)
                            for m, a in v["state"].items()
                        },
                    }
                    for k, v in state.get("blocks", {}).items()
                }

        def persist() -> None:
            if not checkpoint_dir:
                return
            atomic_write_json(self._stats_path(checkpoint_dir), {
                "mode": "vmap",
                "n_members": E,
                "quantiles": list(self.quantiles),
                "blocks": {
                    str(k): {
                        "national": {
                            m: np.asarray(a).tolist()
                            for m, a in v["national"].items()
                        },
                        "state": {
                            m: np.asarray(a).tolist()
                            for m, a in v["state"].items()
                        },
                    }
                    for k, v in blocks.items()
                },
            })

        qs_dev = jnp.asarray(self.quantiles, jnp.float32)
        agent_fields = [
            f.name for f in dataclasses.fields(YearOutputs)
            if f.name != "state_hourly_net_mw"
        ]
        collected: Dict[str, list] = {k: [] for k in agent_fields}
        hourly: List[np.ndarray] = []

        guard = None
        try:
            for yi, year in enumerate(years):
                if yi < start_idx:
                    continue
                if (
                    rc.guard_retrace and guard is None
                    and yi - start_idx >= 2
                ):
                    from dgen_tpu.lint.guard import RetraceGuard

                    guard = RetraceGuard(
                        context="ensemble vmap steady state"
                    ).start()
                kwargs["first_year"] = (yi == 0)
                year_f = (
                    jnp.asarray(float(year), jnp.float32)
                    if self._entry_dev is not None else None
                )
                with timing.timer("ensemble_year_step", ctx="vmap"):
                    carry, outs = ensemble_year_step(
                        self.base.table, self.base.profiles,
                        self.base.tariffs, inputs_e,
                        self._entry_dev, year_f, carry,
                        jnp.asarray(yi, dtype=jnp.int32), **kwargs,
                    )
                alive = (
                    cohort_alive_mask(
                        self._mask_pot_dev, self._entry_dev, year_f
                    )
                    if self._entry_dev is not None
                    else self.base.table.mask
                )
                nat, st = estats.member_aggregates(
                    outs, alive, state_idx, n_states=n_states
                )
                q_nat = estats.year_quantiles(nat, qs_dev)
                q_st = estats.year_quantiles(st, qs_dev)
                # the whole per-year host fetch: a handful of [Q] /
                # [Q, n_states] blocks, O(quantiles) not O(E x N)
                host = jax.device_get(  # dgenlint: disable=L9
                    {"national": q_nat, "state": q_st}
                )
                blocks[yi] = host
                if collect:
                    fetch = {k: getattr(outs, k) for k in agent_fields}
                    if self.with_hourly:
                        fetch["_hourly"] = outs.state_hourly_net_mw
                    h = jax.device_get(fetch)  # dgenlint: disable=L9
                    for k in agent_fields:
                        collected[k].append(h[k])
                    if self.with_hourly:
                        hourly.append(h["_hourly"])
                if writer is not None:
                    writer.save(year, carry)
                    persist()
                if guard is not None:
                    guard.check(f"year {year}")
        finally:
            if guard is not None:
                guard.stop()
            if writer is not None:
                writer.close()
        persist()

        run_years = years[start_idx:]
        results: List[SimResults] = []
        for m in range(E):
            agent = (
                {k: np.stack([v[m] for v in vs])
                 for k, vs in collected.items()}
                if collect and collected[agent_fields[0]] else {}
            )
            results.append(SimResults(
                years=list(run_years),
                agent=agent,
                state_hourly_net_mw=(
                    np.stack([h[m] for h in hourly]) if hourly else None
                ),
            ))
        stats = estats.stats_from_year_blocks(
            years, self.quantiles, E, blocks
        )
        return results, stats

    # -- the ensemble ---------------------------------------------------

    def run(
        self,
        collect: bool = False,
        checkpoint_dir: Optional[str] = None,
        resume: bool = False,
    ) -> SweepResults:
        """Run every member of every model year; returns
        :class:`SweepResults` whose ``quantiles`` block carries the
        per-year p10/p50/p90 bands (:class:`EnsembleStats`).

        ``collect`` defaults to False — the ensemble's contract is
        quantile bands with O(quantiles) host traffic; flip it on for
        per-member agent-level outputs (tests, small worlds).

        ``checkpoint_dir`` lays out (member, year)-grained resume:
        per-member ``mem=<m>/`` subdirectories in loop mode, one
        stacked ``scn=members/`` in vmap mode, plus the incremental
        stats sidecar so a resumed run still reports the full horizon.
        """
        if self.mode == MODE_VMAP:
            results, stats = self._run_vmap(collect, checkpoint_dir, resume)
        else:
            results, stats = self._run_loop(collect, checkpoint_dir, resume)
        rep_q = getattr(self.base, "quarantine_report", None)
        return SweepResults(
            labels=list(self.labels),
            baseline=0,
            runs=results,
            plan=self.plan,
            bank_bytes_shared=self.bank_bytes_shared,
            host_mask=self.base.host_mask,
            host_agent_id=self.base.host_agent_id,
            quarantine=(
                rep_q.summary()
                if rep_q is not None and not rep_q.is_clean else None
            ),
            quantiles=stats,
        )
