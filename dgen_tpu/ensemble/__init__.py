"""dgen_tpu.ensemble — stochastic Monte-Carlo ensembles + dynamic
agent populations over one placed table (ISSUE 20).

The reference answers policy questions with one deterministic
trajectory; decision-makers need adoption *bands* under diffusion and
price uncertainty, over populations that change mid-horizon (new
construction, electrification load growth). This package runs E
seed-deterministic ensemble members in ONE compiled program against
one placed agent table and one HBM-resident copy of the profile
banks:

* :mod:`~dgen_tpu.ensemble.draws` — per-member stochastic axes (Bass
  p/q, retail/wholesale price paths, tech-cost trajectories) as pure
  functions of ``(base ScenarioInputs, member key)`` built from
  ``jax.random.fold_in`` — restart-stable and identical across
  loop/vmap execution modes;
* :mod:`~dgen_tpu.ensemble.cohorts` — cohort entry on the alive-mask
  data plane: future-construction rows sit pre-placed and masked in
  the fixed-capacity table, and a per-year jitted mask update flips
  them alive at their entry year (masked rows contribute exact zeros
  — the PR 13 quarantine proof — so the compiled programs never move);
* :mod:`~dgen_tpu.ensemble.stats` — on-device per-member reductions +
  per-year p10/p50/p90 quantiles, so host traffic stays O(quantiles)
  per year instead of O(E x N) agent rows;
* :mod:`~dgen_tpu.ensemble.driver` — :class:`EnsembleSimulation`,
  riding the sweep engine's vmap/loop duality and ``plan_sweep``'s
  mesh-global HBM byte model (``n_members`` term): vmap mode batches
  the member axis in one program, loop mode reuses ONE compiled
  executable member-major when E doesn't fit, and E=1 with zero-width
  draws is byte-identical to :meth:`Simulation.run`.

See docs/ensemble.md.
"""

from dgen_tpu.ensemble.cohorts import (  # noqa: F401
    COHORT_NEVER,
    CohortSchedule,
    cohort_alive_mask,
)
from dgen_tpu.ensemble.draws import (  # noqa: F401
    DEFAULT_DRAWS,
    DrawSpec,
    draw_members,
    member_key,
)
from dgen_tpu.ensemble.driver import (  # noqa: F401
    EnsembleSimulation,
    ensemble_year_step,
)
from dgen_tpu.ensemble.stats import EnsembleStats  # noqa: F401
