"""Per-member stochastic axes for Monte-Carlo ensembles.

Every ensemble member m is a pure function of ``(base ScenarioInputs,
member key)`` where the key is ``jax.random.fold_in(PRNGKey(seed), m)``
— no sequential RNG state, so member 7 draws the same trajectories
whether it runs first, last, vmapped alongside 63 siblings, or alone
after a checkpoint restart (the restart-stability contract the tests
pin). Within a member, each stochastic axis folds in its own constant,
so adding an axis never reshuffles the draws of existing ones.

Axes (all mean-preserving so the ensemble median tracks the base case):

* **Bass diffusion** — per-group lognormal perturbations of ``bass_p``
  / ``bass_q``, the reference's most-cited calibration uncertainty;
* **retail price path** — a [Y] geometric random walk on
  ``elec_price_multiplier`` (year 0 pinned at the observed base year),
  with ``elec_price_escalator`` re-derived from the shocked multiplier
  via :func:`~dgen_tpu.models.scenario.escalator_from_multipliers` so
  the two stay mutually consistent the way the reference computes them;
* **wholesale price path** — an independent [Y] walk on
  ``wholesale_multiplier`` (shared across regions: wholesale shocks
  are systemic, not regional);
* **tech cost** — one lognormal scale per technology applied to every
  coupled capex field (pv standalone + combined; battery $/kWh, $/kW,
  and combined) so PV-vs-storage relative economics shift coherently.

``nem_cap_kw`` is NEVER drawn: it feeds the net-billing static flag
(models.flags), and perturbing it could flip a compiled-program shape
decision between members that must share one executable.

A zero-width spec returns the base :class:`ScenarioInputs` OBJECT
(identity, not a copy) — the hook that makes the E=1 ensemble
byte-identical to ``Simulation.run``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dgen_tpu.models.scenario import (
    ScenarioInputs,
    escalator_from_multipliers,
)

# fold_in constants, one per stochastic axis: member key -> axis key.
# Frozen — reordering or renumbering changes every committed draw.
AXIS_BASS = 0
AXIS_RETAIL = 1
AXIS_WHOLESALE = 2
AXIS_TECH = 3


@dataclasses.dataclass(frozen=True)
class DrawSpec:
    """Standard deviations of the per-member stochastic axes (all in
    log space; 0.0 disables an axis exactly — it consumes no RNG and
    perturbs nothing)."""

    bass_p_sd: float = 0.0      # per-group lognormal on bass_p
    bass_q_sd: float = 0.0      # per-group lognormal on bass_q
    retail_sd: float = 0.0      # per-year retail price walk step
    wholesale_sd: float = 0.0   # per-year wholesale price walk step
    pv_capex_sd: float = 0.0    # one lognormal scale per member
    batt_capex_sd: float = 0.0  # one lognormal scale per member

    def __post_init__(self) -> None:
        for f in dataclasses.fields(self):
            v = float(getattr(self, f.name))
            if not np.isfinite(v) or v < 0.0:
                raise ValueError(f"DrawSpec.{f.name} must be >= 0, got {v}")

    @property
    def is_zero(self) -> bool:
        """True when every axis is disabled — the byte-parity contract:
        :func:`draw_member` returns the base inputs object unchanged."""
        return all(
            float(getattr(self, f.name)) == 0.0
            for f in dataclasses.fields(self)
        )

    def to_json(self) -> Dict[str, float]:
        return {
            f.name: float(getattr(self, f.name))
            for f in dataclasses.fields(self)
        }

    @classmethod
    def from_json(cls, d: Dict[str, float]) -> "DrawSpec":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: float(v) for k, v in d.items() if k in known})


#: Calibrated-magnitude default: ~±20% Bass p, ~±15% q (the spread of
#: the reference's state-level pq calibrations), 1%/yr retail and
#: 3%/yr wholesale walk steps, ±5% technology cost levels.
DEFAULT_DRAWS = DrawSpec(
    bass_p_sd=0.20,
    bass_q_sd=0.15,
    retail_sd=0.01,
    wholesale_sd=0.03,
    pv_capex_sd=0.05,
    batt_capex_sd=0.05,
)


def member_key(seed: int, member: int) -> jax.Array:
    """Restart-stable key for ensemble member ``member``."""
    return jax.random.fold_in(jax.random.PRNGKey(int(seed)), int(member))


def _lognormal(key: jax.Array, sd: float, shape) -> np.ndarray:
    """Mean-preserving lognormal factors: E[exp(sd*z - sd^2/2)] = 1."""
    z = np.asarray(jax.random.normal(key, shape, dtype=jnp.float32))
    return np.exp(sd * z - 0.5 * sd * sd).astype(np.float32)


def _walk(key: jax.Array, sd: float, n_years: int) -> np.ndarray:
    """[Y] geometric random-walk factors, year 0 pinned at 1.0 (the
    base year is observed, not uncertain). Median-preserving
    (exp of a zero-mean walk); the p50 band therefore tracks the base
    trajectory, which is what the quantile tests assert."""
    z = np.asarray(
        jax.random.normal(key, (n_years - 1,), dtype=jnp.float32)
    )
    steps = np.concatenate([[0.0], np.cumsum(sd * z)])
    return np.exp(steps).astype(np.float32)


def draw_member(
    base: ScenarioInputs, spec: DrawSpec, key: jax.Array
) -> ScenarioInputs:
    """One ensemble member's :class:`ScenarioInputs`, drawn from
    ``key`` (host-side: the perturbed trajectories are tiny O(Y x G)
    arrays, and the escalator re-derivation is numpy).

    Zero-width spec => returns ``base`` itself (object identity), so
    downstream byte-parity holds with no float round-trip at all.
    """
    if spec.is_zero:
        return base

    years_np = np.asarray(base.years)
    n_years = int(years_np.shape[0])
    repl: Dict[str, jax.Array] = {}

    if spec.bass_p_sd > 0.0 or spec.bass_q_sd > 0.0:
        k = jax.random.fold_in(key, AXIS_BASS)
        kp, kq = jax.random.split(k)
        g = base.bass_p.shape
        if spec.bass_p_sd > 0.0:
            repl["bass_p"] = jnp.asarray(
                np.asarray(base.bass_p) * _lognormal(kp, spec.bass_p_sd, g)
            )
        if spec.bass_q_sd > 0.0:
            repl["bass_q"] = jnp.asarray(
                np.asarray(base.bass_q) * _lognormal(kq, spec.bass_q_sd, g)
            )

    if spec.retail_sd > 0.0 and n_years > 1:
        k = jax.random.fold_in(key, AXIS_RETAIL)
        walk = _walk(k, spec.retail_sd, n_years)          # [Y]
        mult = np.asarray(base.elec_price_multiplier) * walk[:, None, None]
        repl["elec_price_multiplier"] = jnp.asarray(mult)
        # keep the forward-CAGR escalator consistent with the shocked
        # path — the reference derives one from the other, never both
        repl["elec_price_escalator"] = jnp.asarray(
            escalator_from_multipliers(mult, years_np.astype(np.int64))
        )

    if spec.wholesale_sd > 0.0 and n_years > 1:
        k = jax.random.fold_in(key, AXIS_WHOLESALE)
        walk = _walk(k, spec.wholesale_sd, n_years)       # [Y]
        repl["wholesale_multiplier"] = jnp.asarray(
            np.asarray(base.wholesale_multiplier) * walk[:, None]
        )

    if spec.pv_capex_sd > 0.0 or spec.batt_capex_sd > 0.0:
        k = jax.random.fold_in(key, AXIS_TECH)
        kpv, kb = jax.random.split(k)
        if spec.pv_capex_sd > 0.0:
            s = float(_lognormal(kpv, spec.pv_capex_sd, ()))
            for f in ("pv_capex_per_kw", "pv_capex_per_kw_combined"):
                repl[f] = jnp.asarray(np.asarray(getattr(base, f)) * s)
        if spec.batt_capex_sd > 0.0:
            s = float(_lognormal(kb, spec.batt_capex_sd, ()))
            for f in (
                "batt_capex_per_kwh",
                "batt_capex_per_kw",
                "batt_capex_per_kwh_combined",
            ):
                repl[f] = jnp.asarray(np.asarray(getattr(base, f)) * s)

    return dataclasses.replace(base, **repl)


def draw_members(
    base: ScenarioInputs,
    spec: DrawSpec,
    n_members: int,
    seed: int,
) -> List[ScenarioInputs]:
    """All E members' inputs. Member m depends only on ``(seed, m)`` —
    the list is stable under reordering, truncation, and restart."""
    if n_members < 1:
        raise ValueError(f"n_members must be >= 1, got {n_members}")
    return [
        draw_member(base, spec, member_key(seed, m))
        for m in range(int(n_members))
    ]
