"""CLI: ``python -m dgen_tpu.ensemble`` — run a seed-vmapped
Monte-Carlo ensemble over one synthetic population in a single process.

    python -m dgen_tpu.ensemble --agents 512 --members 8 \\
        --end-year 2025 --cohort-rows 32 --cohort-year 2018

prints the per-year p10/p50/p90 national adoption band as JSON.
``--check-parity`` additionally runs the E=1 zero-width-draw ensemble
next to a plain ``Simulation.run`` and asserts byte equality — the
check.sh smoke gate. Real populations go through the programmatic API
(:class:`dgen_tpu.ensemble.EnsembleSimulation`) with worlds from
``models.synth`` / ``io.package``.
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dgen_tpu.ensemble",
        description="stochastic Monte-Carlo ensemble on one population",
    )
    ap.add_argument("--agents", type=int, default=512)
    ap.add_argument("--states", nargs="*", default=["DE", "CA", "TX"])
    ap.add_argument("--start-year", type=int, default=2014)
    ap.add_argument("--end-year", type=int, default=2020)
    ap.add_argument("--members", type=int, default=None,
                    help="ensemble width E (default: env "
                         "DGEN_TPU_ENSEMBLE, else 4)")
    ap.add_argument("--seed", type=int, default=None,
                    help="draw seed (default: env "
                         "DGEN_TPU_ENSEMBLE_SEED, else 0)")
    ap.add_argument("--zero-draws", action="store_true",
                    help="zero-width DrawSpec — members are literal "
                         "copies of the base scenario")
    ap.add_argument("--cohort-rows", type=int, default=0,
                    help="reschedule this many (tail) rows as a future "
                         "construction cohort")
    ap.add_argument("--cohort-year", type=int, default=2017,
                    help="calendar entry year of the cohort rows")
    ap.add_argument("--sizing-iters", type=int, default=8)
    ap.add_argument("--check-parity", action="store_true",
                    help="assert the E=1 zero-draw ensemble is "
                         "byte-identical to Simulation.run (smoke gate)")
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--run-dir", default=None,
                    help="write the quantile block (ensemble.json) here")
    args = ap.parse_args(argv)

    from dgen_tpu.config import RunConfig, ScenarioConfig
    from dgen_tpu.ensemble import (
        DEFAULT_DRAWS,
        DrawSpec,
        EnsembleSimulation,
    )
    from dgen_tpu.ensemble.driver import ENV_MEMBERS
    from dgen_tpu.io import synth
    from dgen_tpu.models import scenario as scen
    from dgen_tpu.models.simulation import Simulation
    from dgen_tpu.utils import compilecache

    compilecache.enable()

    cfg = ScenarioConfig(
        name="ensemble", start_year=args.start_year,
        end_year=args.end_year, anchor_years=(),
    )
    pop = synth.generate_population(
        args.agents, states=list(args.states), seed=7,
    )
    inputs = scen.uniform_inputs(
        cfg, n_groups=pop.table.n_groups, n_regions=pop.n_regions,
    )
    rc = RunConfig(sizing_iters=args.sizing_iters)

    parity = None
    if args.check_parity:
        ref = Simulation(
            pop.table, pop.profiles, pop.tariffs, inputs, cfg, rc,
        ).run(collect=True)
        r1 = EnsembleSimulation(
            pop.table, pop.profiles, pop.tariffs, inputs, cfg, rc,
            n_members=1, draws=DrawSpec(),
        ).run(collect=True)[0]
        parity = list(ref.years) == list(r1.years) and all(
            np.array_equal(np.asarray(ref.agent[k]),
                           np.asarray(r1.agent[k]))
            for k in ref.agent
        )
        if not parity:
            print("PARITY FAILED: E=1 zero-draw ensemble diverges from "
                  "Simulation.run")
            return 1

    entry = None
    if args.cohort_rows > 0:
        # reschedule the TAIL of the alive rows as a cohort: same
        # world, a slice of it now enters at --cohort-year instead of
        # being alive from the start
        entry = np.zeros(pop.table.n_agents, np.float32)
        alive = np.flatnonzero(np.asarray(pop.table.mask) > 0)
        entry[alive[-min(args.cohort_rows, len(alive)):]] = float(
            args.cohort_year)

    n_members = (
        args.members if args.members is not None
        else int(os.environ.get(ENV_MEMBERS, "").strip() or 4)
    )
    draws = DrawSpec() if args.zero_draws else DEFAULT_DRAWS
    t0 = time.time()
    ens = EnsembleSimulation(
        pop.table, pop.profiles, pop.tariffs, inputs, cfg, rc,
        n_members=n_members, seed=args.seed, draws=draws,
        entry_year=entry,
    )
    results = ens.run(
        checkpoint_dir=args.checkpoint_dir, resume=args.resume,
    )
    wall = time.time() - t0
    stats = results.quantiles

    if args.run_dir:
        from dgen_tpu.resilience.atomic import atomic_write_json

        os.makedirs(args.run_dir, exist_ok=True)
        atomic_write_json(
            os.path.join(args.run_dir, "ensemble.json"), stats.to_json(),
        )

    print(json.dumps({
        "members": ens.n_members,
        "agents": args.agents,
        "years": [int(y) for y in np.asarray(stats.years)],
        "mode": ens.mode,
        "seed": ens.seed,
        "draws": "zero" if draws.is_zero else "default",
        "cohort_rows": int(args.cohort_rows),
        "quantiles": [float(q) for q in stats.quantiles],
        "adopters_band": {
            k: [round(float(x), 3) for x in v]
            for k, v in stats.band("adopters").items()
        },
        "parity": parity,
        "wall_s": round(wall, 2),
        "per_member_wall_s": round(wall / ens.n_members, 3),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
