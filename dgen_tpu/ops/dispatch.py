"""Behind-the-meter battery dispatch: the TPU replacement for the SSC
``Battery`` module run (reference financial_functions.py:164
``batt.execute()``).

The reference configures SSC for rule-based behind-the-meter dispatch:
charge only from PV surplus (no grid charging), hourly updates
(reference batt_dispatch_helpers.py:59 ``configure_retail_rate_dispatch``
with ``batt_dispatch_choice = 0``). SSC's internal dispatch is a large
stateful C++ machine; matching it trace-for-trace is a non-goal — the
framework targets *economic equivalence* (SURVEY.md §7 hard parts):
greedy self-consumption with SOC/power/efficiency limits, which is what
choice-0 peak-shaving dispatch converges to for a load-following BTM
battery.

Implemented as an 8760-step ``lax.scan`` (the SOC recurrence is
inherently sequential) with a partially-unrolled body so XLA amortizes
loop overhead; everything else in the model vectorizes around it via
``jax.vmap`` over agents.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

# Reference sizing ratios (financial_functions.py:140-147): battery
# energy = PV kW / 0.8 (kWh), power = energy / 2 (kW).
PV_TO_BATT_RATIO = 0.8
BATT_CAPACITY_TO_POWER_RATIO = 2.0
# Reference SOC settings (financial_functions.py:138,151).
SOC_MIN_FRAC = 0.10
SOC_INIT_FRAC = 0.30
# Default round-trip efficiency when no trajectory is supplied (~0.92,
# typical Li-ion AC-coupled); the scenario's batt_tech trajectory
# (reference batt_tech_performance CSVs, applied per year at
# agent_mutation/elec.py:319) overrides this per agent-year.
DEFAULT_RT_EFF = 0.9216


def batt_size_from_pv(system_kw: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(batt_kw, batt_kwh) at the reference's fixed PV ratio."""
    batt_kwh = system_kw / PV_TO_BATT_RATIO
    batt_kw = batt_kwh / BATT_CAPACITY_TO_POWER_RATIO
    return batt_kw, batt_kwh


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DispatchResult:
    system_out: jax.Array   # [8760] net system output at the meter (kWh/h)
    soc: jax.Array          # [8760] state of charge (kWh) after each hour
    charge: jax.Array       # [8760] PV -> battery
    discharge: jax.Array    # [8760] battery -> load


@partial(jax.jit, static_argnames=("unroll",))
def dispatch_battery(
    load: jax.Array,
    gen: jax.Array,
    batt_kw: jax.Array,
    batt_kwh: jax.Array,
    rt_eff: jax.Array | float = DEFAULT_RT_EFF,
    unroll: int = 24,
) -> DispatchResult:
    """Greedy self-consumption dispatch over one year.

    Per hour: charge from PV surplus only (up to power / headroom
    limits), discharge to unmet load only (up to power / available
    energy); surplus beyond charging exports, deficit beyond discharge
    imports. ``system_out = gen - charge + discharge`` is what the bill
    engine sees as the system's net meter contribution, mirroring how the
    reference hands the battery-modified ``SystemOutput.gen`` to
    Utilityrate5 (financial_functions.py:195).

    ``rt_eff``: round-trip efficiency, split evenly into one-way charge
    and discharge efficiencies (sqrt); year-dependent via the scenario's
    batt_tech trajectory.
    """
    soc_min = batt_kwh * SOC_MIN_FRAC
    soc0 = batt_kwh * SOC_INIT_FRAC
    eta = jnp.sqrt(jnp.asarray(rt_eff, dtype=jnp.float32))

    def step(soc, inputs):
        ld, g = inputs
        surplus = jnp.maximum(g - ld, 0.0)
        deficit = jnp.maximum(ld - g, 0.0)
        charge = jnp.minimum(
            jnp.minimum(surplus, batt_kw),
            jnp.maximum(batt_kwh - soc, 0.0) / eta,
        )
        discharge = jnp.minimum(
            jnp.minimum(deficit, batt_kw),
            jnp.maximum(soc - soc_min, 0.0) * eta,
        )
        new_soc = soc + charge * eta - discharge / eta
        return new_soc, (new_soc, charge, discharge)

    _, (soc, charge, discharge) = jax.lax.scan(
        step, soc0, (load, gen), unroll=unroll
    )
    system_out = gen - charge + discharge
    return DispatchResult(system_out=system_out, soc=soc, charge=charge, discharge=discharge)
