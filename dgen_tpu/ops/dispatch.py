"""Behind-the-meter battery dispatch: the TPU replacement for the SSC
``Battery`` module run (reference financial_functions.py:164
``batt.execute()``).

The reference configures SSC for rule-based behind-the-meter dispatch:
charge only from PV surplus (no grid charging), hourly updates
(reference batt_dispatch_helpers.py:59 ``configure_retail_rate_dispatch``
with ``batt_dispatch_choice = 0``). SSC's internal dispatch is a large
stateful C++ machine; matching it trace-for-trace is a non-goal — the
framework targets *economic equivalence* (SURVEY.md §7 hard parts):
greedy self-consumption with SOC/power/efficiency limits, which is what
choice-0 peak-shaving dispatch converges to for a load-following BTM
battery.

Implemented as an 8760-step ``lax.scan`` (partially unrolled);
everything else in the model vectorizes around it via ``jax.vmap``
over agents.  The scan is lane-parallel across the whole agent batch,
so its measured cost is near the loop-overhead floor: 0.12 s per call
at 8192 agents (~14 us/step) on v5e, ~1.0 s inside a 65k all-sector
year step (~25% of that step's device time).

**Round-5 negative result — the parallel-prefix formulation is
slower.**  The SOC recurrence is EXACTLY a saturating accumulator
(with the invariants ``soc_min <= soc <= kwh`` the charge/discharge
limits collapse to ``soc_t = clamp(soc_{t-1} + a_t, soc_min, kwh)``
with SOC-independent ``a_t``), and add-then-clamp maps compose, so
``lax.associative_scan`` solves the year in ~14 vectorized sweeps —
``impl="pscan"``, parity-pinned in tests/test_dispatch.py.  Measured
on v5e it LOSES: 0.68 s vs 0.12 s at 8192 agents (the sweeps
materialize [N, 8760] tuple intermediates and go HBM-bound where the
scan keeps one [N] carry in VMEM), and its program blows up the
remote AOT compile helper at the 17792-row national chunk.  Kept as
an option + proof, not the default.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

# Reference sizing ratios (financial_functions.py:140-147): battery
# energy = PV kW / 0.8 (kWh), power = energy / 2 (kW).
PV_TO_BATT_RATIO = 0.8
BATT_CAPACITY_TO_POWER_RATIO = 2.0
# Reference SOC settings (financial_functions.py:138,151).
SOC_MIN_FRAC = 0.10
SOC_INIT_FRAC = 0.30
# Default round-trip efficiency when no trajectory is supplied (~0.92,
# typical Li-ion AC-coupled); the scenario's batt_tech trajectory
# (reference batt_tech_performance CSVs, applied per year at
# agent_mutation/elec.py:319) overrides this per agent-year.
DEFAULT_RT_EFF = 0.9216


def batt_size_from_pv(system_kw: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(batt_kw, batt_kwh) at the reference's fixed PV ratio."""
    batt_kwh = system_kw / PV_TO_BATT_RATIO
    batt_kw = batt_kwh / BATT_CAPACITY_TO_POWER_RATIO
    return batt_kw, batt_kwh


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DispatchResult:
    system_out: jax.Array   # [8760] net system output at the meter (kWh/h)
    soc: jax.Array          # [8760] state of charge (kWh) after each hour
    charge: jax.Array       # [8760] PV -> battery
    discharge: jax.Array    # [8760] battery -> load


def _compose_clamp(f, g):
    """Composition of add-then-clamp maps, f applied FIRST:
    ``(g o f)(x) = clamp(x + af + ag, lo', hi')``.  The standard
    saturating-prefix identity; associative, which is what lets the
    SOC recurrence run as a parallel prefix."""
    af, lf, hf = f
    ag, lg, hg = g
    a = af + ag
    hi = jnp.minimum(hg, jnp.maximum(lg, hf + ag))
    lo = jnp.minimum(hi, jnp.maximum(lg, lf + ag))
    return a, lo, hi


@partial(jax.jit, static_argnames=("unroll", "impl"))
def dispatch_battery(
    load: jax.Array,
    gen: jax.Array,
    batt_kw: jax.Array,
    batt_kwh: jax.Array,
    rt_eff: jax.Array | float = DEFAULT_RT_EFF,
    unroll: int = 24,
    impl: str = "scan",
) -> DispatchResult:
    """Greedy self-consumption dispatch over one year.

    Per hour: charge from PV surplus only (up to power / headroom
    limits), discharge to unmet load only (up to power / available
    energy); surplus beyond charging exports, deficit beyond discharge
    imports. ``system_out = gen - charge + discharge`` is what the bill
    engine sees as the system's net meter contribution, mirroring how the
    reference hands the battery-modified ``SystemOutput.gen`` to
    Utilityrate5 (financial_functions.py:195).

    ``rt_eff``: round-trip efficiency, split evenly into one-way charge
    and discharge efficiencies (sqrt); year-dependent via the scenario's
    batt_tech trajectory.

    ``impl``: "scan" (default) is the sequential 8760-step
    formulation — measured faster on TPU; "pscan" solves the SOC
    recurrence as a saturating-accumulator parallel prefix (see the
    module docstring's negative result).
    """
    soc_min = batt_kwh * SOC_MIN_FRAC
    soc0 = batt_kwh * SOC_INIT_FRAC
    eta = jnp.sqrt(jnp.asarray(rt_eff, dtype=jnp.float32))

    if impl not in ("scan", "pscan"):
        raise ValueError(f"unknown dispatch impl {impl!r}")
    if impl == "pscan":
        surplus = jnp.maximum(gen - load, 0.0)
        deficit = jnp.maximum(load - gen, 0.0)
        a = (jnp.minimum(surplus, batt_kw) * eta
             - jnp.minimum(deficit, batt_kw) / eta)
        lo = jnp.full_like(a, soc_min)
        hi = jnp.full_like(a, batt_kwh)
        # composed tuple at t = f_t o ... o f_1: its offset is the plain
        # prefix sum and its (lo, hi) the collapsed clamp window, so
        # soc_t = clamp(soc0 + A_t, L_t, H_t)
        a_p, lo_p, hi_p = jax.lax.associative_scan(
            _compose_clamp, (a, lo, hi), axis=-1
        )
        soc = jnp.clip(soc0 + a_p, lo_p, hi_p)
        dsoc = jnp.diff(soc, prepend=jnp.reshape(soc0, (1,)))
        charge = jnp.maximum(dsoc, 0.0) / eta
        discharge = jnp.maximum(-dsoc, 0.0) * eta
    else:
        def step(soc, inputs):
            ld, g = inputs
            surplus = jnp.maximum(g - ld, 0.0)
            deficit = jnp.maximum(ld - g, 0.0)
            charge = jnp.minimum(
                jnp.minimum(surplus, batt_kw),
                jnp.maximum(batt_kwh - soc, 0.0) / eta,
            )
            discharge = jnp.minimum(
                jnp.minimum(deficit, batt_kw),
                jnp.maximum(soc - soc_min, 0.0) * eta,
            )
            new_soc = soc + charge * eta - discharge / eta
            return new_soc, (new_soc, charge, discharge)

        _, (soc, charge, discharge) = jax.lax.scan(
            step, soc0, (load, gen), unroll=unroll
        )
    system_out = gen - charge + discharge
    return DispatchResult(system_out=system_out, soc=soc, charge=charge, discharge=discharge)
