"""NPV-optimal PV sizing search + the fused per-agent economics kernel.

This is the hot loop of the whole framework: the reference sizes each
agent with ``scipy.optimize.minimize_scalar(bounded)`` over PV kW, each
objective call running three PySAM C++ modules (reference
financial_functions.py:440-447, SURVEY.md §3.2), one agent at a time in
a process pool. Here the objective — bill engine + cashflow (+ battery
dispatch for the forward run) — is a pure JAX function and the optimizer
is a fixed-iteration golden-section search, so the entire agent table
sizes as ONE vmapped kernel on device.

Fixed-iteration golden section vs the reference's adaptive Brent-style
search: 14 iterations shrink the bracket by phi^-14 ~ 1.2e-3 of its
width, comfortably inside the reference's ``xatol = max(2 kW,
(hi-lo)*1e-3)`` tolerance (financial_functions.py:444), with a
compile-time-static trip count (no data-dependent control flow under
jit).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp

from dgen_tpu.ops import bill as bill_ops
from dgen_tpu.ops import cashflow as cf_ops
from dgen_tpu.ops import dispatch as dispatch_ops
from dgen_tpu.ops.bill import AgentTariff

INV_EFF = 0.96  # inverter efficiency (reference financial_functions.py:113)
GOLDEN = 0.6180339887498949  # 1/phi

# Sizing bracket relative to the load-implied max system size
# (reference financial_functions.py:440-443).
SIZE_LO_FRAC = 0.8
SIZE_HI_FRAC = 1.25


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class AgentEconInputs:
    """Everything one agent's economics evaluation needs, as dense leaves.

    Built by the year step from the agent table + banks; vmap over the
    leading axis for the whole population.
    """

    load: jax.Array            # [8760] hourly consumption (kWh/h)
    gen_per_kw: jax.Array      # [8760] PV DC output per kW_dc
    ts_sell: jax.Array         # [8760] $/kWh time-series sell rate
    tariff: AgentTariff
    fin: cf_ops.FinanceParams
    inc: cf_ops.IncentiveParams
    load_kwh_per_customer: jax.Array
    elec_price_escalator: jax.Array
    pv_degradation: jax.Array
    system_capex_per_kw: jax.Array
    system_capex_per_kw_combined: jax.Array
    batt_capex_per_kwh_combined: jax.Array
    cap_cost_multiplier: jax.Array
    value_of_resiliency_usd: jax.Array
    one_time_charge: jax.Array


def _npv_given_system_out(
    env: AgentEconInputs,
    system_kw: jax.Array,
    system_out: jax.Array,
    installed_cost: jax.Array,
    vor: jax.Array,
    n_periods: int,
    n_years: int,
):
    """Shared tail of the objective: bills -> energy value -> cashflow."""
    bills_w, bills_wo = bill_ops.bill_series(
        env.load, system_out, env.tariff, env.ts_sell,
        env.fin.inflation_rate, env.elec_price_escalator, env.pv_degradation,
        n_periods=n_periods, n_years=n_years,
    )
    # Value of resiliency is added to every year's energy value for the
    # with-battery case (reference financial_functions.py:220,274-275).
    energy_value = (bills_wo - bills_w) + vor
    annual_kwh = jnp.sum(system_out)
    out = cf_ops.cashflow(
        energy_value, installed_cost, env.fin, n_years,
        system_kw=system_kw, annual_kwh=annual_kwh,
        degradation=env.pv_degradation, inc=env.inc,
    )
    out["energy_value"] = energy_value
    out["bills_w"] = bills_w
    out["bills_wo"] = bills_wo
    return out


def pv_only_npv(
    kw: jax.Array, env: AgentEconInputs, n_periods: int, n_years: int
) -> jax.Array:
    """Objective for the sizing search (PV only, no battery)."""
    gen = env.gen_per_kw * kw * INV_EFF
    cost = env.system_capex_per_kw * kw * env.cap_cost_multiplier + env.one_time_charge
    out = _npv_given_system_out(
        env, kw, gen, cost, jnp.zeros(()), n_periods, n_years
    )
    return out["npv"]


def golden_section_max(
    f: Callable[[jax.Array], jax.Array],
    lo: jax.Array,
    hi: jax.Array,
    n_iters: int,
) -> jax.Array:
    """Maximize a unimodal scalar function on [lo, hi].

    Static trip count; returns the bracket midpoint after ``n_iters``
    interval reductions. (The reference minimizes -NPV; we maximize NPV.)
    """
    a, b = lo, hi
    c = b - (b - a) * GOLDEN
    d = a + (b - a) * GOLDEN
    fc = f(c)
    fd = f(d)

    def body(_, state):
        a, b, c, d, fc, fd = state
        # keep the half containing the larger value
        take_left = fc > fd
        a2 = jnp.where(take_left, a, c)
        b2 = jnp.where(take_left, d, b)
        c2 = b2 - (b2 - a2) * GOLDEN
        d2 = a2 + (b2 - a2) * GOLDEN
        # Golden-ratio identity: the surviving interior point IS one of
        # the new ones (take_left -> d2 == c, else c2 == d), so only one
        # fresh evaluation is needed per iteration.
        x_new = jnp.where(take_left, c2, d2)
        fx = f(x_new)
        fc2 = jnp.where(take_left, fx, fd)
        fd2 = jnp.where(take_left, fc, fx)
        return a2, b2, c2, d2, fc2, fd2

    a, b, c, d, fc, fd = jax.lax.fori_loop(
        0, n_iters, body, (a, b, c, d, fc, fd)
    )
    return 0.5 * (a + b)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SizingResult:
    """Per-agent sized economics (fields mirror what the reference writes
    back onto the agent row, financial_functions.py:522-565)."""

    system_kw: jax.Array
    npv: jax.Array
    payback_period: jax.Array
    cash_flow: jax.Array                  # [Y+1]
    naep: jax.Array
    annual_energy_production_kwh: jax.Array
    capacity_factor: jax.Array
    first_year_bill_with_system: jax.Array
    first_year_bill_without_system: jax.Array
    batt_kw: jax.Array
    batt_kwh: jax.Array
    first_year_bill_with_batt: jax.Array
    energy_value_pv_only: jax.Array       # [Y]
    energy_value_pv_batt: jax.Array       # [Y]
    baseline_net_hourly: jax.Array        # [8760]
    adopter_net_hourly_pvonly: jax.Array  # [8760]
    adopter_net_hourly_with_batt: jax.Array  # [8760]


@partial(jax.jit, static_argnames=("n_periods", "n_years", "n_iters", "keep_hourly"))
def size_one_agent(
    env: AgentEconInputs,
    n_periods: int,
    n_years: int,
    n_iters: int = 14,
    keep_hourly: bool = True,
) -> SizingResult:
    """Full sizing pipeline for one agent (vmap for the table).

    1. Golden-section search for NPV-optimal PV kW, no battery
       (reference financial_functions.py:445).
    2. PV-only outputs at kW*.
    3. One forward run with a battery at the fixed PV ratio
       (reference financial_functions.py:479).
    """
    naep = jnp.sum(env.gen_per_kw)
    max_system = env.load_kwh_per_customer / jnp.maximum(naep, 1e-9)
    lo = max_system * SIZE_LO_FRAC
    hi = max_system * SIZE_HI_FRAC

    obj = lambda kw: pv_only_npv(kw, env, n_periods, n_years)
    kw_star = golden_section_max(obj, lo, hi, n_iters)

    # --- PV-only outputs at kW* ---
    gen_n = env.gen_per_kw * kw_star * INV_EFF
    cost_n = (
        env.system_capex_per_kw * kw_star * env.cap_cost_multiplier
        + env.one_time_charge
    )
    out_n = _npv_given_system_out(
        env, kw_star, gen_n, cost_n, jnp.zeros(()), n_periods, n_years
    )
    payback = cf_ops.payback_period(out_n["cf"])

    # --- Forward run with battery at fixed ratio ---
    batt_kw, batt_kwh = dispatch_ops.batt_size_from_pv(kw_star)
    dr = dispatch_ops.dispatch_battery(env.load, gen_n, batt_kw, batt_kwh)
    # Battery capex enters the cost basis at 0.7x for the ITC treatment
    # (reference financial_functions.py:219).
    batt_cost = env.batt_capex_per_kwh_combined * batt_kwh * 0.7
    cost_w = (
        env.system_capex_per_kw_combined * kw_star + batt_cost
    ) * env.cap_cost_multiplier + env.one_time_charge
    out_w = _npv_given_system_out(
        env, kw_star, dr.system_out, cost_w, env.value_of_resiliency_usd,
        n_periods, n_years,
    )

    annual_kwh = jnp.sum(gen_n)
    naep_final = annual_kwh / jnp.maximum(kw_star, 1e-9)

    if keep_hourly:
        baseline_net = env.load
        net_pvonly = jnp.maximum(env.load - gen_n, 0.0)
        net_with_batt = jnp.maximum(env.load - dr.system_out, 0.0)
    else:
        empty = jnp.zeros((0,), dtype=env.load.dtype)
        baseline_net = net_pvonly = net_with_batt = empty

    return SizingResult(
        system_kw=kw_star,
        npv=out_n["npv"],
        payback_period=payback,
        cash_flow=out_n["cf"],
        naep=naep_final,
        annual_energy_production_kwh=annual_kwh,
        capacity_factor=naep_final / 8760.0,
        first_year_bill_with_system=out_n["bills_w"][0],
        first_year_bill_without_system=out_n["bills_wo"][0],
        batt_kw=batt_kw,
        batt_kwh=batt_kwh,
        first_year_bill_with_batt=out_w["bills_w"][0],
        energy_value_pv_only=out_n["energy_value"],
        energy_value_pv_batt=out_w["energy_value"],
        baseline_net_hourly=baseline_net,
        adopter_net_hourly_pvonly=net_pvonly,
        adopter_net_hourly_with_batt=net_with_batt,
    )


def size_agents(
    envs: AgentEconInputs,
    n_periods: int,
    n_years: int,
    n_iters: int = 14,
    keep_hourly: bool = True,
) -> SizingResult:
    """Vmapped sizing over the whole agent table (leading axis)."""
    fn = partial(
        size_one_agent,
        n_periods=n_periods,
        n_years=n_years,
        n_iters=n_iters,
        keep_hourly=keep_hourly,
    )
    return jax.vmap(fn)(envs)
