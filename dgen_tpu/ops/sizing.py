"""NPV-optimal PV sizing search + the fused per-agent economics kernel.

This is the hot loop of the whole framework: the reference sizes each
agent with ``scipy.optimize.minimize_scalar(bounded)`` over PV kW, each
objective call running three PySAM C++ modules (reference
financial_functions.py:440-447, SURVEY.md §3.2), one agent at a time in
a process pool. Here the objective — bill engine + cashflow (+ battery
dispatch for the forward run) — is a pure JAX function and the optimizer
is a fixed-iteration golden-section search, so the entire agent table
sizes as ONE vmapped kernel on device.

Fixed-iteration golden section vs the reference's adaptive Brent-style
search: 14 iterations shrink the bracket by phi^-14 ~ 1.2e-3 of its
width, comfortably inside the reference's ``xatol = max(2 kW,
(hi-lo)*1e-3)`` tolerance (financial_functions.py:444), with a
compile-time-static trip count (no data-dependent control flow under
jit).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp

from dgen_tpu.ops import bill as bill_ops
from dgen_tpu.ops import billpallas
from dgen_tpu.ops import cashflow as cf_ops
from dgen_tpu.ops import dispatch as dispatch_ops
from dgen_tpu.ops.bill import AgentTariff

INV_EFF = 0.96  # inverter efficiency (reference financial_functions.py:113)
GOLDEN = 0.6180339887498949  # 1/phi

# Sizing bracket relative to the load-implied max system size
# (reference financial_functions.py:440-443).
SIZE_LO_FRAC = 0.8
SIZE_HI_FRAC = 1.25


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class AgentEconInputs:
    """Everything one agent's economics evaluation needs, as dense leaves.

    Built by the year step from the agent table + banks; vmap over the
    leading axis for the whole population.
    """

    load: jax.Array            # [8760] hourly consumption (kWh/h)
    gen_per_kw: jax.Array      # [8760] PV DC output per kW_dc
    ts_sell: jax.Array         # [8760] $/kWh time-series sell rate
    tariff: AgentTariff
    #: post-adoption (DG-rate-switched) tariff used for WITH-system
    #: bills (reference apply_rate_switch, agent_mutation/elec.py:838);
    #: None = no switch, with-system bills use ``tariff``
    tariff_w: "AgentTariff | None"
    fin: cf_ops.FinanceParams
    inc: cf_ops.IncentiveParams
    load_kwh_per_customer: jax.Array
    elec_price_escalator: jax.Array
    pv_degradation: jax.Array
    system_capex_per_kw: jax.Array
    system_capex_per_kw_combined: jax.Array
    batt_capex_per_kwh_combined: jax.Array
    cap_cost_multiplier: jax.Array
    value_of_resiliency_usd: jax.Array
    #: one-time interconnection charge, applied only when the DG-rate
    #: switch takes effect (reference elec.py:857-860)
    one_time_charge: jax.Array
    #: upper bound on the sizing bracket while NEM is active (the
    #: per-agent nem_system_kw_limit, reference elec.py:92-119); 1e30
    #: where NEM is off or unlimited. None -> filled with 1e30 by
    #: :func:`size_agents`.
    nem_kw_cap: jax.Array = None
    #: DG-rate switch window: with-system bills price on ``tariff_w``
    #: only where kw in [switch_min_kw, switch_max_kw) (reference
    #: apply_rate_switch, elec.py:844-845); switch_min_kw=1e30 disables.
    #: None -> always-switch when tariff_w is given (filled by
    #: :func:`size_agents`).
    switch_min_kw: jax.Array = None
    switch_max_kw: jax.Array = None
    #: battery round-trip efficiency for the forward dispatch run
    #: (year-dependent batt_tech trajectory, reference elec.py:319);
    #: None -> the dispatch default
    batt_rt_eff: jax.Array = None
    #: int8 quantized banks (RunConfig.quant_banks): when set, ``load``
    #: and ``gen_per_kw`` carry int8 codes and these [N] f32 factors
    #: dequantize them (real load = load_scale * load; the per-agent
    #: load multiplier is already folded in). None = unquantized.
    load_scale: jax.Array = None
    gen_scale: jax.Array = None


def net_hourly_profiles(
    load: jax.Array, gen: jax.Array, system_out: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """(baseline, pv_only, with_batt) net grid-consumption profiles —
    the single definition shared by the sizing keep_hourly outputs and
    the driver's chunked rematerialization pass (reference
    attachment_rate_functions.py:177-201 mixes exactly these three)."""
    return (
        load,
        jnp.maximum(load - gen, 0.0),
        jnp.maximum(load - system_out, 0.0),
    )


def _switch_active(env: AgentEconInputs, kw: jax.Array) -> jax.Array:
    """Whether the DG-rate switch applies at system size ``kw``
    (reference apply_rate_switch, elec.py:844-845). Broadcasts the
    per-agent window over a trailing candidate axis if present.

    A ``None`` window is the legacy always-on behavior (switch and
    one-time charge apply at every size)."""
    mn, mx = env.switch_min_kw, env.switch_max_kw
    if mn is None:
        return jnp.ones_like(kw, dtype=bool)
    if kw.ndim == mn.ndim + 1:
        mn, mx = mn[..., None], mx[..., None]
    return (kw >= mn) & (kw < mx)


def _switch_weight(
    env: AgentEconInputs, kw: jax.Array, soft_tau: float | None
) -> jax.Array:
    """Float [0, 1] DG-rate-switch indicator at system size ``kw``.

    ``soft_tau=None`` is the exact hard window cast to f32. Under soft
    it is a straight-through gate pair (grad.smooth.ste_gate): forward
    still evaluates the window test (closed at the upper edge — a
    measure-zero difference from the hard strict ``<``), backward
    carries a sigmoid bump of width ``soft_tau`` kW so the switch
    boundary is visible to gradients instead of a dead zero."""
    mn, mx = env.switch_min_kw, env.switch_max_kw
    if mn is None:
        return jnp.ones_like(kw)
    if kw.ndim == mn.ndim + 1:
        mn, mx = mn[..., None], mx[..., None]
    if soft_tau is None:
        return ((kw >= mn) & (kw < mx)).astype(kw.dtype)
    from dgen_tpu.grad.smooth import ste_gate

    return ste_gate(kw - mn, soft_tau) * ste_gate(mx - kw, soft_tau)


def _npv_given_system_out(
    env: AgentEconInputs,
    system_kw: jax.Array,
    system_out: jax.Array,
    installed_cost: jax.Array,
    vor: jax.Array,
    n_periods: int,
    n_years: int,
):
    """Shared tail of the objective: bills -> energy value -> cashflow.

    The with-system tariff is size-conditioned: ``tariff_w`` applies
    only where the DG-rate switch window contains ``system_kw``.
    """
    if env.tariff_w is None:
        tw = env.tariff
    else:
        sw = _switch_active(env, system_kw)
        tw = jax.tree.map(
            lambda a, b: jnp.where(sw, a, b), env.tariff_w, env.tariff
        )
    bills_w, bills_wo = bill_ops.bill_series(
        env.load, system_out, tw, env.ts_sell,
        env.fin.inflation_rate, env.elec_price_escalator, env.pv_degradation,
        n_periods=n_periods, n_years=n_years,
        tariff_wo=None if env.tariff_w is None else env.tariff,
    )
    # Value of resiliency is added to every year's energy value for the
    # with-battery case (reference financial_functions.py:220,274-275).
    energy_value = (bills_wo - bills_w) + vor
    annual_kwh = jnp.sum(system_out)
    out = cf_ops.cashflow(
        energy_value, installed_cost, env.fin, n_years,
        system_kw=system_kw, annual_kwh=annual_kwh,
        degradation=env.pv_degradation, inc=env.inc,
    )
    out["energy_value"] = energy_value
    out["bills_w"] = bills_w
    out["bills_wo"] = bills_wo
    return out


def pv_only_npv(
    kw: jax.Array, env: AgentEconInputs, n_periods: int, n_years: int
) -> jax.Array:
    """Objective for the sizing search (PV only, no battery)."""
    gen = env.gen_per_kw * kw * INV_EFF
    otc = jnp.where(_switch_active(env, kw), env.one_time_charge, 0.0)
    cost = env.system_capex_per_kw * kw * env.cap_cost_multiplier + otc
    out = _npv_given_system_out(
        env, kw, gen, cost, jnp.zeros(()), n_periods, n_years
    )
    return out["npv"]


def golden_section_max(
    f: Callable[[jax.Array], jax.Array],
    lo: jax.Array,
    hi: jax.Array,
    n_iters: int,
) -> jax.Array:
    """Maximize a unimodal scalar function on [lo, hi].

    Static trip count; returns the bracket midpoint after ``n_iters``
    interval reductions. (The reference minimizes -NPV; we maximize NPV.)
    """
    a, b = lo, hi
    c = b - (b - a) * GOLDEN
    d = a + (b - a) * GOLDEN
    fc = f(c)
    fd = f(d)

    def body(_, state):
        a, b, c, d, fc, fd = state
        # keep the half containing the larger value
        take_left = fc > fd
        a2 = jnp.where(take_left, a, c)
        b2 = jnp.where(take_left, d, b)
        c2 = b2 - (b2 - a2) * GOLDEN
        d2 = a2 + (b2 - a2) * GOLDEN
        # Golden-ratio identity: the surviving interior point IS one of
        # the new ones (take_left -> d2 == c, else c2 == d), so only one
        # fresh evaluation is needed per iteration.
        x_new = jnp.where(take_left, c2, d2)
        fx = f(x_new)
        fc2 = jnp.where(take_left, fx, fd)
        fd2 = jnp.where(take_left, fc, fx)
        return a2, b2, c2, d2, fc2, fd2

    a, b, c, d, fc, fd = jax.lax.fori_loop(
        0, n_iters, body, (a, b, c, d, fc, fd)
    )
    return 0.5 * (a + b)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SizingResult:
    """Per-agent sized economics (fields mirror what the reference writes
    back onto the agent row, financial_functions.py:522-565)."""

    system_kw: jax.Array
    npv: jax.Array
    payback_period: jax.Array
    cash_flow: jax.Array                  # [Y+1]
    naep: jax.Array
    annual_energy_production_kwh: jax.Array
    capacity_factor: jax.Array
    first_year_bill_with_system: jax.Array
    first_year_bill_without_system: jax.Array
    batt_kw: jax.Array
    batt_kwh: jax.Array
    first_year_bill_with_batt: jax.Array
    energy_value_pv_only: jax.Array       # [Y]
    energy_value_pv_batt: jax.Array       # [Y]
    baseline_net_hourly: jax.Array        # [8760]
    adopter_net_hourly_pvonly: jax.Array  # [8760]
    adopter_net_hourly_with_batt: jax.Array  # [8760]


@partial(jax.jit, static_argnames=("n_periods", "n_years", "n_iters", "keep_hourly"))
def size_one_agent(
    env: AgentEconInputs,
    n_periods: int,
    n_years: int,
    n_iters: int = 14,
    keep_hourly: bool = True,
) -> SizingResult:
    """Full sizing pipeline for one agent — the direct hourly path,
    kept as the cross-check oracle for :func:`size_agents`' fast path.

    1. Golden-section search for NPV-optimal PV kW, no battery
       (reference financial_functions.py:445).
    2. PV-only outputs at kW*.
    3. One forward run with a battery at the fixed PV ratio
       (reference financial_functions.py:479).
    """
    naep = jnp.sum(env.gen_per_kw.astype(jnp.float32))
    max_system = env.load_kwh_per_customer / jnp.maximum(naep, 1e-9)
    lo = max_system * SIZE_LO_FRAC
    hi = max_system * SIZE_HI_FRAC
    # NEM system-size limit caps the bracket while NEM is active
    # (reference nem_system_kw_limit, elec.py:92-119)
    if env.nem_kw_cap is not None:
        hi = jnp.minimum(hi, env.nem_kw_cap)
        lo = jnp.minimum(lo, hi)

    obj = lambda kw: pv_only_npv(kw, env, n_periods, n_years)
    kw_star = golden_section_max(obj, lo, hi, n_iters)

    # --- PV-only outputs at kW* ---
    gen_n = env.gen_per_kw * kw_star * INV_EFF
    otc_star = jnp.where(_switch_active(env, kw_star), env.one_time_charge, 0.0)
    cost_n = (
        env.system_capex_per_kw * kw_star * env.cap_cost_multiplier
        + otc_star
    )
    out_n = _npv_given_system_out(
        env, kw_star, gen_n, cost_n, jnp.zeros(()), n_periods, n_years
    )
    payback = cf_ops.payback_period(out_n["cf"])

    # --- Forward run with battery at fixed ratio ---
    batt_kw, batt_kwh = dispatch_ops.batt_size_from_pv(kw_star)
    rt_eff = (
        dispatch_ops.DEFAULT_RT_EFF if env.batt_rt_eff is None
        else env.batt_rt_eff
    )
    # f32 dispatch even under bf16 banks (same rule as the fast path:
    # the SOC recursion compounds rounding over 8760 steps)
    load_f32 = env.load.astype(jnp.float32)
    dr = dispatch_ops.dispatch_battery(
        load_f32, gen_n, batt_kw, batt_kwh, rt_eff
    )
    # Battery capex enters the cost basis at 0.7x for the ITC treatment
    # (reference financial_functions.py:219).
    batt_cost = env.batt_capex_per_kwh_combined * batt_kwh * 0.7
    cost_w = (
        env.system_capex_per_kw_combined * kw_star + batt_cost
    ) * env.cap_cost_multiplier + otc_star
    out_w = _npv_given_system_out(
        env, kw_star, dr.system_out, cost_w, env.value_of_resiliency_usd,
        n_periods, n_years,
    )

    annual_kwh = jnp.sum(gen_n)
    naep_final = annual_kwh / jnp.maximum(kw_star, 1e-9)

    if keep_hourly:
        baseline_net, net_pvonly, net_with_batt = net_hourly_profiles(
            load_f32, gen_n, dr.system_out
        )
    else:
        empty = jnp.zeros((0,), dtype=jnp.float32)
        baseline_net = net_pvonly = net_with_batt = empty

    return SizingResult(
        system_kw=kw_star,
        npv=out_n["npv"],
        payback_period=payback,
        cash_flow=out_n["cf"],
        naep=naep_final,
        annual_energy_production_kwh=annual_kwh,
        capacity_factor=naep_final / 8760.0,
        first_year_bill_with_system=out_n["bills_w"][0],
        first_year_bill_without_system=out_n["bills_wo"][0],
        batt_kw=batt_kw,
        batt_kwh=batt_kwh,
        first_year_bill_with_batt=out_w["bills_w"][0],
        energy_value_pv_only=out_n["energy_value"],
        energy_value_pv_batt=out_w["energy_value"],
        baseline_net_hourly=baseline_net,
        adopter_net_hourly_pvonly=net_pvonly,
        adopter_net_hourly_with_batt=net_with_batt,
    )


@partial(
    jax.jit,
    static_argnames=("n_periods", "n_years", "n_iters", "keep_hourly", "impl",
                     "mesh", "net_billing", "daylight", "pack_once",
                     "soft_tau"),
)
def _size_agents_fast(
    envs: AgentEconInputs,
    n_periods: int,
    n_years: int,
    n_iters: int,
    keep_hourly: bool,
    impl: str,
    mesh=None,
    net_billing: bool = True,
    daylight=None,
    pack_once: bool = False,
    soft_tau: float | None = None,
) -> SizingResult:
    """Table-level sizing via two refining candidate-grid rounds.

    Each round evaluates ``n_iters`` candidate sizes for every agent in
    ONE bucket-sums kernel call by packing (candidate, year) pairs into
    the matmul row axis (ops.billpallas docstring, fact 3); round 2
    re-grids around round 1's winner, so the size resolution is
    ``(hi-lo) * 2 / n_iters**2`` — e.g. 16 candidates -> 0.8% of the
    bracket, well inside the reference's ``xatol = max(2 kW,
    1e-3 * width)`` (financial_functions.py:444). NEM bills inside the
    rounds use the linear identity (zero hourly work); net-billing uses
    the single-matmul import kernel.
    """
    n = envs.load.shape[0]
    f32 = jnp.float32
    k = max(int(n_iters), 4)

    # the smooth twin prices on the plain f32 full-hour path only:
    # quantized codes round-trip through hard thresholds, and the
    # compacted/packed layouts' night-sum split assumes the hard relu's
    # exact zeros (config.RunConfig.soft_boundaries rejects these
    # upstream; this guard covers direct callers)
    if soft_tau is not None and (
        envs.load_scale is not None or daylight is not None or pack_once
    ):
        raise ValueError(
            "soft_tau requires plain f32 full-hour banks (no "
            "quant_banks / daylight_compact / pack_once)"
        )

    # the stream engine pipelines uniform (agent-block x month-segment)
    # blocks; a compacted layout is padded to its longest month once,
    # here, so the pack and every engine call agree on the lane map
    if impl == "pallas_stream" and daylight is not None:
        daylight = daylight.uniform()

    # int8 quantized banks (RunConfig.quant_banks): the candidate
    # kernels consume the int8 codes directly (the per-agent scales
    # fold into the candidate grid, billpallas._quant_fold); the
    # precision floors below — linear_sums, the battery SOC recursion,
    # naep, keep_hourly profiles — price DEQUANTIZED f32 streams, the
    # same rule bf16 banks follow
    quant = envs.load_scale is not None
    if quant:
        gen_scale_eff = envs.gen_scale * INV_EFF                  # [N]
        gen_shape = envs.gen_per_kw                               # codes
        gen_f32 = envs.gen_per_kw.astype(f32) * gen_scale_eff[:, None]
        load_f32 = envs.load.astype(f32) * envs.load_scale[:, None]
        naep = jnp.sum(envs.gen_per_kw.astype(f32), axis=1) * envs.gen_scale
    else:
        gen_scale_eff = None
        gen_shape = envs.gen_per_kw * INV_EFF                     # [N, H]
        gen_f32 = gen_shape.astype(f32)
        # f32 dispatch/profile floor even under bf16 banks (the SOC
        # recursion compounds rounding over 8760 steps)
        load_f32 = envs.load.astype(f32)
        # f32 accumulation even under bf16 profile banks (8760-term sum)
        naep = jnp.sum(envs.gen_per_kw.astype(f32), axis=1)       # [N]

    max_system = envs.load_kwh_per_customer / jnp.maximum(naep, 1e-9)
    lo = max_system * SIZE_LO_FRAC
    hi = max_system * SIZE_HI_FRAC
    # NEM system-size limit caps the sizing bracket while NEM is active
    # (reference nem_system_kw_limit, elec.py:92-119)
    hi = jnp.minimum(hi, envs.nem_kw_cap)
    lo = jnp.minimum(lo, hi)

    n_buckets = 12 * n_periods
    # with-system bills price on the DG-rate-switched tariff_w only for
    # candidates inside the per-agent switch window; the counterfactual
    # stays on the original tariff (reference apply_rate_switch,
    # agent_mutation/elec.py:838-845)
    has_switch = envs.tariff_w is not None
    tw = envs.tariff if envs.tariff_w is None else envs.tariff_w
    bucket = billpallas.hourly_bucket_ids(tw.hour_period, n_periods)
    sell = billpallas.sell_rate_hourly(tw, envs.ts_sell)

    yr = jnp.arange(n_years, dtype=f32)[None, :]                  # [1, Y]
    pf = (
        (1.0 + envs.fin.inflation_rate[:, None])
        * (1.0 + envs.elec_price_escalator[:, None])
    ) ** yr                                                       # [N, Y]
    df = (1.0 - envs.pv_degradation[:, None]) ** yr               # [N, Y]

    # once per call: the linear bill structure (NEM + export credit)
    # on the with-system tariff (dequantized f32 floor under quant)
    lin_load = load_f32 if quant else envs.load
    lin_gen = gen_f32 if quant else gen_shape
    lin = billpallas.linear_sums(
        lin_load, lin_gen, sell, tw.hour_period, n_periods
    )

    # no-system bills: scale 0 through the linear path on the ORIGINAL
    # tariff — no kernel call
    zeros1 = jnp.zeros((n, 1), f32)
    if envs.tariff_w is None:
        lin_wo, sell_wo = lin, sell
    else:
        sell_wo = billpallas.sell_rate_hourly(envs.tariff, envs.ts_sell)
        lin_wo = billpallas.linear_sums(
            lin_load, lin_gen, sell_wo, envs.tariff.hour_period, n_periods
        )
    imp0 = lin_wo[0][:, None, :]       # imports at s=0 == S_load buckets
    bills_wo = billpallas.bills_linear_nb(
        lin_wo, imp0, lin_wo[2][:, None], zeros1, envs.tariff, n_periods,
        soft_tau,
    )[:, 0:1] * pf                                                # [N, Y]

    cashflow_v = jax.vmap(
        lambda ev, cost, fin, kw, kwh, deg, inc: cf_ops.cashflow(
            ev, cost, fin, n_years, system_kw=kw, annual_kwh=kwh,
            degradation=deg, inc=inc,
        )
    )

    def econ(bills_w, kw, installed_cost, vor, annual_kwh):
        energy_value = (bills_wo - bills_w) + vor[:, None]
        out = cashflow_v(
            energy_value, installed_cost, envs.fin, kw, annual_kwh,
            envs.pv_degradation, envs.inc,
        )
        out["energy_value"] = energy_value
        out["bills_w"] = bills_w
        return out

    def pv_cost(kw):
        # kw: [N] or [N, K]; per-agent cost params broadcast over K.
        # The one-time (interconnection) charge applies only where the
        # DG-rate switch takes effect (reference elec.py:857-860).
        unsq = (lambda x: x[:, None]) if kw.ndim == 2 else (lambda x: x)
        if soft_tau is None:
            otc = jnp.where(
                _switch_active(envs, kw), unsq(envs.one_time_charge), 0.0
            )
        else:
            # STE gate: forward identical, backward sees the boundary
            otc = _switch_weight(envs, kw, soft_tau) * unsq(
                envs.one_time_charge
            )
        return (
            unsq(envs.system_capex_per_kw) * kw * unsq(envs.cap_cost_multiplier)
            + otc
        )

    bucket_wo = (
        billpallas.hourly_bucket_ids(envs.tariff.hour_period, n_periods)
        if has_switch else bucket
    )

    # pack-once (RunConfig.pack_once): ONE repack gather (+ one night-
    # sums pass under a daylight layout) feeds both refine rounds —
    # and, below, the battery forward run when the layouts line up —
    # instead of each engine call re-gathering the [N, 8760] streams.
    # Skipped for all-NEM programs (no candidate kernel runs at all).
    packed = None
    if pack_once and net_billing:
        packed = billpallas.pack_streams(
            envs.load, gen_shape, sell, bucket, n_buckets,
            layout=daylight,
            sell_b=sell_wo if has_switch else None,
            bucket_b=bucket_wo if has_switch else None,
        )
    kq = dict(load_scale=envs.load_scale,
              gen_scale=gen_scale_eff) if quant else {}

    def candidate_bills(scales):
        """[N, R] packed (candidate, year) scales -> with-system annual
        bills on a given tariff structure; evaluated on the switched
        tariff and, when a switch window exists, also on the original.

        ``net_billing=False`` (the driver's static all-NEM detection):
        every bill is the pure linear identity — the two dominant
        bucket-sums kernel calls per search round are skipped entirely.
        """
        if not net_billing:
            bills_sw = billpallas.bills_linear_nem(
                lin, scales, tw, n_periods, soft_tau)
            if not has_switch:
                return bills_sw, None
            return bills_sw, billpallas.bills_linear_nem(
                lin_wo, scales, envs.tariff, n_periods, soft_tau)
        none_if_packed = lambda a: None if packed is not None else a
        if not has_switch:
            imports, imp_sell = billpallas.import_sums(
                none_if_packed(envs.load), none_if_packed(gen_shape),
                none_if_packed(sell), none_if_packed(bucket), scales,
                n_buckets, impl, mesh=mesh, layout=daylight,
                packed=packed, soft_tau=soft_tau, **kq,
            )
            return billpallas.bills_linear_nb(
                lin, imports, imp_sell, scales, tw, n_periods, soft_tau
            ), None
        # switch populations price every candidate on BOTH tariffs over
        # the same relu(net) grid — one fused kernel call (the net build
        # dominates; see billpallas.import_sums_pair)
        imports, imp_sell, imports_o, imp_sell_o = (
            billpallas.import_sums_pair(
                none_if_packed(envs.load), none_if_packed(gen_shape),
                none_if_packed(sell), none_if_packed(bucket),
                none_if_packed(sell_wo), none_if_packed(bucket_wo),
                scales, n_buckets, impl, mesh=mesh, layout=daylight,
                packed=packed, soft_tau=soft_tau, **kq,
            )
        )
        bills_sw = billpallas.bills_linear_nb(
            lin, imports, imp_sell, scales, tw, n_periods, soft_tau
        )
        bills_o = billpallas.bills_linear_nb(
            lin_wo, imports_o, imp_sell_o, scales, envs.tariff, n_periods,
            soft_tau,
        )
        return bills_sw, bills_o

    def eval_grid(kw_grid):
        """kw_grid [N, K] -> economics of every candidate.

        One kernel call with R = K * Y packed scale rows (two calls for
        switch populations: the candidate's tariff depends on its size).
        """
        scales = (kw_grid[:, :, None] * df[:, None, :]).reshape(n, k * n_years)
        bills_sw, bills_o = candidate_bills(scales)
        if has_switch:
            if soft_tau is None:
                in_w = _switch_active(envs, kw_grid)              # [N, K]
                sel = jnp.repeat(in_w, n_years, axis=1)           # [N, K*Y]
                bills = jnp.where(sel, bills_sw, bills_o)
            else:
                # STE-weighted blend: forward matches the hard select,
                # backward carries the window boundary
                w = jnp.repeat(
                    _switch_weight(envs, kw_grid, soft_tau), n_years, axis=1
                )
                bills = w * bills_sw + (1.0 - w) * bills_o
        else:
            bills = bills_sw
        bills = bills.reshape(n, k, n_years) * pf[:, None, :]     # [N, K, Y]

        rep = lambda x: jnp.repeat(x, k, axis=0)
        ev = (bills_wo[:, None, :] - bills).reshape(n * k, n_years)
        kw_f = kw_grid.reshape(n * k)
        out = cashflow_v(
            ev, pv_cost(kw_grid).reshape(n * k),
            jax.tree.map(rep, envs.fin), kw_f,
            kw_f * INV_EFF * jnp.repeat(naep, k),
            jnp.repeat(envs.pv_degradation, k),
            jax.tree.map(rep, envs.inc),
        )
        npv = out["npv"].reshape(n, k)
        return npv, bills

    def grid(lo_, hi_):
        t = jnp.linspace(0.0, 1.0, k, dtype=f32)[None, :]
        return lo_[:, None] + (hi_ - lo_)[:, None] * t            # [N, K]

    # round 1: coarse grid over the reference bracket
    g1 = grid(lo, hi)
    npv1, _ = eval_grid(g1)
    i1 = jnp.argmax(npv1, axis=1)
    take = lambda a, i: jnp.take_along_axis(a, i[:, None], axis=1)[:, 0]
    lo2 = take(g1, jnp.maximum(i1 - 1, 0))
    hi2 = take(g1, jnp.minimum(i1 + 1, k - 1))

    # round 2: refined grid around the round-1 winner
    g2 = grid(lo2, hi2)
    npv2, bills2 = eval_grid(g2)
    i2 = jnp.argmax(npv2, axis=1)
    kw_star = take(g2, i2)

    # --- PV-only outputs at kW* (select the winning candidate) ---
    gen_n = gen_f32 * kw_star[:, None]
    bills_w_n = jnp.take_along_axis(
        bills2, i2[:, None, None], axis=1
    )[:, 0, :]                                                    # [N, Y]
    out_n = econ(bills_w_n, kw_star, pv_cost(kw_star), jnp.zeros(n, f32),
                 kw_star * INV_EFF * naep)
    payback = jax.vmap(
        partial(cf_ops.payback_period, soft=soft_tau is not None)
    )(out_n["cf"])

    # --- Forward run with battery at fixed ratio ---
    batt_kw, batt_kwh = dispatch_ops.batt_size_from_pv(kw_star)
    rt_eff = (
        jnp.full(n, dispatch_ops.DEFAULT_RT_EFF, f32)
        if envs.batt_rt_eff is None else envs.batt_rt_eff
    )
    # f32 dispatch even under bf16/int8 banks: the SOC recursion
    # compounds rounding over 8760 steps (load_f32 dequantized above)
    dr = jax.vmap(dispatch_ops.dispatch_battery)(
        load_f32, gen_n, batt_kw, batt_kwh, rt_eff
    )
    batt_cost = envs.batt_capex_per_kwh_combined * batt_kwh * 0.7
    sw_star = _switch_active(envs, kw_star)                       # [N]
    otc_star = jnp.where(sw_star, envs.one_time_charge, 0.0)
    cost_w = (
        envs.system_capex_per_kw_combined * kw_star + batt_cost
    ) * envs.cap_cost_multiplier + otc_star
    # the with-battery tariff follows the switch decision at kW*
    if has_switch:
        tariff_star = jax.tree.map(
            lambda a, b: jnp.where(
                sw_star.reshape((-1,) + (1,) * (a.ndim - 1)), a, b
            ),
            tw, envs.tariff,
        )
        bucket_star = jnp.where(sw_star[:, None], bucket, bucket_wo)
        sell_star = jnp.where(sw_star[:, None], sell, sell_wo)
    else:
        tariff_star, bucket_star, sell_star = tw, bucket, sell
    # battery-modified output is not a scale of gen_shape; use the full
    # bucket-sums kernel with per-year degradation scales. Quantized
    # runs price the battery on the DEQUANTIZED f32 load (one call per
    # year; the SOC output is f32 anyway). The pack-once bundle is
    # reusable here only when its load/sell/period match this call —
    # full-hour lanes (no daylight compaction: a discharging battery
    # breaks the night-zero premise), one tariff structure, no quant.
    batt_load = load_f32 if quant else envs.load
    batt_packed = (
        packed if (packed is not None and daylight is None
                   and not has_switch and not quant) else None
    )
    s_b, i_b, c_b = billpallas.bucket_sums(
        None if batt_packed is not None else batt_load,
        dr.system_out,
        None if batt_packed is not None else sell_star,
        None if batt_packed is not None else bucket_star,
        df, n_buckets, impl, mesh=mesh, packed=batt_packed,
        soft_tau=soft_tau,
    )
    bills_w_b = billpallas.bills_from_sums(
        s_b, i_b, c_b, tariff_star, n_periods, soft_tau
    ) * pf
    out_w = econ(bills_w_b, kw_star, cost_w, envs.value_of_resiliency_usd,
                 jnp.sum(dr.system_out, axis=1))

    annual_kwh = jnp.sum(gen_n, axis=1)
    naep_final = annual_kwh / jnp.maximum(kw_star, 1e-9)

    if keep_hourly:
        baseline_net, net_pvonly, net_with_batt = net_hourly_profiles(
            load_f32, gen_n, dr.system_out
        )
    else:
        empty = jnp.zeros((n, 0), dtype=f32)
        baseline_net = net_pvonly = net_with_batt = empty

    bills_wo_y1 = bills_wo[:, 0]
    return SizingResult(
        system_kw=kw_star,
        npv=out_n["npv"],
        payback_period=payback,
        cash_flow=out_n["cf"],
        naep=naep_final,
        annual_energy_production_kwh=annual_kwh,
        capacity_factor=naep_final / 8760.0,
        first_year_bill_with_system=out_n["bills_w"][:, 0],
        first_year_bill_without_system=bills_wo_y1,
        batt_kw=batt_kw,
        batt_kwh=batt_kwh,
        first_year_bill_with_batt=out_w["bills_w"][:, 0],
        energy_value_pv_only=out_n["energy_value"],
        energy_value_pv_batt=out_w["energy_value"],
        baseline_net_hourly=baseline_net,
        adopter_net_hourly_pvonly=net_pvonly,
        adopter_net_hourly_with_batt=net_with_batt,
    )


def _fill_env_defaults(envs: AgentEconInputs) -> AgentEconInputs:
    """Fill the legacy ``None`` sentinels with their dense encodings:
    unlimited NEM bracket (1e30) and an always-on switch window when a
    ``tariff_w`` was supplied (switch_min_kw=0) / never-on otherwise."""
    if (envs.nem_kw_cap is not None and envs.switch_min_kw is not None
            and envs.switch_max_kw is not None):
        return envs
    n = envs.load.shape[0]
    big = jnp.full(n, 1e30, jnp.float32)
    return dataclasses.replace(
        envs,
        nem_kw_cap=big if envs.nem_kw_cap is None else envs.nem_kw_cap,
        switch_min_kw=(
            (jnp.zeros(n, jnp.float32) if envs.tariff_w is not None else big)
            if envs.switch_min_kw is None else envs.switch_min_kw
        ),
        switch_max_kw=(
            big if envs.switch_max_kw is None else envs.switch_max_kw
        ),
    )


def size_agents(
    envs: AgentEconInputs,
    n_periods: int,
    n_years: int,
    n_iters: int = 14,
    keep_hourly: bool = True,
    fast: bool = True,
    impl: str = "auto",
    mesh=None,
    net_billing: bool = True,
    daylight=None,
    pack_once: bool = False,
    soft_tau: float | None = None,
) -> SizingResult:
    """Sizing over the whole agent table (leading axis).

    ``fast=True`` (default) runs the table-level bucket-sums path — the
    Pallas kernel on TPU, its XLA equivalent elsewhere (``impl``
    overrides). ``fast=False`` vmaps the direct per-agent hourly kernel
    (the oracle; ~100x more HBM traffic). ``mesh``: a >1-device Mesh
    runs the bucket-sums engine per-shard over the agent axis
    (shard_map), keeping the Pallas kernel live under real multi-chip
    sharding. ``net_billing=False`` asserts (statically) that no agent
    prices on a net-billing tariff, so search-round bills reduce to the
    linear NEM identity and skip the hourly kernel — the driver derives
    this from the tariffs the population actually references.
    ``daylight``: optional :class:`billpallas.DaylightLayout` — the
    search-round import kernels run over the compacted daylight lanes
    only (night sums added back; the battery forward run always prices
    full-hour, since a discharging battery breaks the night-zero
    premise). ``pack_once``: gather the month-positional candidate
    streams once per call (:class:`billpallas.PackedStreams`) instead
    of once per engine call — the refine rounds (and, where the
    layouts line up, the battery run) then read pre-packed lanes.
    ``soft_tau``: the differentiable smooth-boundary twin
    (:mod:`dgen_tpu.grad`) — soft import/export splits, tier clips and
    STE switch gates inside the search objective; fast path only.
    """
    if envs.load_scale is not None and not fast:
        raise ValueError(
            "int8 quantized banks (AgentEconInputs.load_scale) are a "
            "fast-path representation; the per-agent oracle prices "
            "full-precision streams — dequantize or use fast=True"
        )
    if soft_tau is not None and not fast:
        raise ValueError(
            "soft_tau is a fast-path knob; the per-agent oracle stays "
            "the exact hard reference (use fast=True, or "
            "make_npv_objective for a differentiable per-size objective)"
        )
    envs = _fill_env_defaults(envs)
    if fast:
        return _size_agents_fast(
            envs, n_periods=n_periods, n_years=n_years, n_iters=n_iters,
            keep_hourly=keep_hourly, impl=impl, mesh=mesh,
            net_billing=net_billing, daylight=daylight,
            pack_once=pack_once, soft_tau=soft_tau,
        )
    fn = partial(
        size_one_agent,
        n_periods=n_periods,
        n_years=n_years,
        n_iters=n_iters,
        keep_hourly=keep_hourly,
    )
    return jax.vmap(fn)(envs)


def make_npv_objective(
    envs: AgentEconInputs,
    n_periods: int,
    n_years: int,
    *,
    net_billing: bool = True,
    soft_tau: float | None = None,
    impl: str = "xla",
):
    """Build the batched differentiable sizing objective for
    :mod:`dgen_tpu.grad.newton`.

    Returns ``(npv_fn, lo, hi)``: ``npv_fn(kw)`` maps per-agent system
    sizes ``[N]`` (or a candidate grid ``[N, K]``) to NPV of the same
    shape. The per-agent prologue — linear bill structure, no-system
    bills, price/degradation factors — is computed ONCE here and closed
    over, so each objective evaluation costs what one refine-round
    column of :func:`_size_agents_fast` does: a single import-sums
    kernel call (none at all for an all-NEM population). One
    ``jax.value_and_grad(npv_fn)`` step therefore replaces a whole
    16-candidate search round.

    With ``soft_tau`` set, every boundary inside the objective — the
    hourly import/export split, tier-cap clips, the DG-rate-switch
    window (straight-through gate) — is the smooth surrogate from
    :mod:`dgen_tpu.grad.smooth`, so ``jax.grad`` sees a usable
    derivative everywhere. With ``soft_tau=None`` the surface is the
    same piecewise-smooth objective the grid search evaluates
    (differentiable a.e., kinked at the boundaries).

    Quantized / daylight-compacted / pre-packed bank representations
    are not supported: build plain f32 envs
    (``RunConfig.soft_boundaries`` enforces this upstream).
    """
    if envs.load_scale is not None:
        raise ValueError(
            "make_npv_objective prices full-precision streams; "
            "dequantize the banks first (quant_banks is incompatible "
            "with the differentiable objective)"
        )
    envs = _fill_env_defaults(envs)
    n = envs.load.shape[0]
    f32 = jnp.float32

    naep = jnp.sum(envs.gen_per_kw.astype(f32), axis=1)           # [N]
    max_system = envs.load_kwh_per_customer / jnp.maximum(naep, 1e-9)
    lo = max_system * SIZE_LO_FRAC
    hi = jnp.minimum(max_system * SIZE_HI_FRAC, envs.nem_kw_cap)
    lo = jnp.minimum(lo, hi)

    n_buckets = 12 * n_periods
    has_switch = envs.tariff_w is not None
    tw = envs.tariff if envs.tariff_w is None else envs.tariff_w
    bucket = billpallas.hourly_bucket_ids(tw.hour_period, n_periods)
    sell = billpallas.sell_rate_hourly(tw, envs.ts_sell)
    gen_shape = envs.gen_per_kw * INV_EFF

    yr = jnp.arange(n_years, dtype=f32)[None, :]                  # [1, Y]
    pf = (
        (1.0 + envs.fin.inflation_rate[:, None])
        * (1.0 + envs.elec_price_escalator[:, None])
    ) ** yr                                                       # [N, Y]
    df = (1.0 - envs.pv_degradation[:, None]) ** yr               # [N, Y]

    lin = billpallas.linear_sums(
        envs.load, gen_shape, sell, tw.hour_period, n_periods
    )
    zeros1 = jnp.zeros((n, 1), f32)
    if envs.tariff_w is None:
        lin_wo, sell_wo, bucket_wo = lin, sell, bucket
    else:
        sell_wo = billpallas.sell_rate_hourly(envs.tariff, envs.ts_sell)
        lin_wo = billpallas.linear_sums(
            envs.load, gen_shape, sell_wo, envs.tariff.hour_period, n_periods
        )
        bucket_wo = billpallas.hourly_bucket_ids(
            envs.tariff.hour_period, n_periods
        )
    imp0 = lin_wo[0][:, None, :]
    bills_wo = billpallas.bills_linear_nb(
        lin_wo, imp0, lin_wo[2][:, None], zeros1, envs.tariff, n_periods,
        soft_tau,
    )[:, 0:1] * pf                                                # [N, Y]

    cashflow_v = jax.vmap(
        lambda ev, cost, fin, kw, kwh, deg, inc: cf_ops.cashflow(
            ev, cost, fin, n_years, system_kw=kw, annual_kwh=kwh,
            degradation=deg, inc=inc,
        )
    )

    def npv_fn(kw: jax.Array) -> jax.Array:
        squeeze = kw.ndim == 1
        kw2 = kw[:, None] if squeeze else kw                      # [N, K]
        kk = kw2.shape[1]
        scales = (kw2[:, :, None] * df[:, None, :]).reshape(n, kk * n_years)
        if not net_billing:
            bills_sw = billpallas.bills_linear_nem(
                lin, scales, tw, n_periods, soft_tau)
            bills_o = (
                billpallas.bills_linear_nem(
                    lin_wo, scales, envs.tariff, n_periods, soft_tau)
                if has_switch else None
            )
        elif not has_switch:
            imports, imp_sell = billpallas.import_sums(
                envs.load, gen_shape, sell, bucket, scales, n_buckets,
                impl, soft_tau=soft_tau,
            )
            bills_sw = billpallas.bills_linear_nb(
                lin, imports, imp_sell, scales, tw, n_periods, soft_tau
            )
            bills_o = None
        else:
            imports, imp_sell, imports_o, imp_sell_o = (
                billpallas.import_sums_pair(
                    envs.load, gen_shape, sell, bucket, sell_wo, bucket_wo,
                    scales, n_buckets, impl, soft_tau=soft_tau,
                )
            )
            bills_sw = billpallas.bills_linear_nb(
                lin, imports, imp_sell, scales, tw, n_periods, soft_tau
            )
            bills_o = billpallas.bills_linear_nb(
                lin_wo, imports_o, imp_sell_o, scales, envs.tariff,
                n_periods, soft_tau,
            )
        if has_switch:
            w = jnp.repeat(
                _switch_weight(envs, kw2, soft_tau), n_years, axis=1
            )
            bills = w * bills_sw + (1.0 - w) * bills_o
        else:
            bills = bills_sw
        bills = bills.reshape(n, kk, n_years) * pf[:, None, :]    # [N, K, Y]

        ev = (bills_wo[:, None, :] - bills).reshape(n * kk, n_years)
        kw_f = kw2.reshape(n * kk)
        rep1 = lambda x: jnp.repeat(x, kk)
        otc = _switch_weight(envs, kw2, soft_tau).reshape(n * kk) * rep1(
            envs.one_time_charge
        )
        cost = (
            rep1(envs.system_capex_per_kw) * kw_f
            * rep1(envs.cap_cost_multiplier) + otc
        )
        rep = lambda x: jnp.repeat(x, kk, axis=0)
        out = cashflow_v(
            ev, cost, jax.tree.map(rep, envs.fin), kw_f,
            kw_f * INV_EFF * rep1(naep), rep1(envs.pv_degradation),
            jax.tree.map(rep, envs.inc),
        )
        npv = out["npv"].reshape(n, kk)
        return npv[:, 0] if squeeze else npv

    return npv_fn, lo, hi
