"""Pallas TPU kernels for the bill engine's hour-axis reductions.

The sizing hot loop needs, for every agent and a batch of R net-load
scales (search candidates x analysis years), reductions over the
8760-hour axis of ``net = load - s * gen_shape``:

  * signed (month x TOU-period) sums      -> net-metering bills
  * positive-part (import) bucket sums    -> net-billing import charges
  * sell-rate-weighted sums               -> net-billing export credit

Three structural facts make this cheap on a TPU:

1. **Signed sums are linear in s**: ``signed(s) = S_load - s * S_gen``,
   so net-metering bills need NO hourly work per candidate — just two
   precomputed bucket-sum vectors per agent (:func:`linear_sums`).
2. **Export credit is linear given the import sums**: with
   ``exp = relu(net) - net`` (elementwise identity),
   ``credit(s) = imp_sell(s) - (S_load_sell - s * S_gen_sell)`` — so the
   nonlinear kernel only ever computes ONE matmul: ``relu(net) @ M``.
3. **The hour->bucket map is structural, not data**: bucket =
   month * P + period, the calendar month map is shared by every agent,
   and P (TOU periods) is tiny. With a month-padded hour layout the
   month becomes POSITIONAL and bucket sums reduce to P-1 masked row
   reductions per month block — no per-agent one-hot materialization
   and no matmul at all (see ``_kernel_month``; the round-3 one-hot+MXU
   engine is kept as ``impl="pallas_dot"`` — its iota/compare/select M
   build measured 54% of device time, tools/kernel_microbench.py).

HBM traffic per sizing-objective evaluation is O(N * 8760) — the
straightforward XLA formulation (dgen_tpu.ops.bill.bill_series)
materializes O(N * Y * 8760), the measured v5e bottleneck; the
reference re-runs its C++ rate engine per (agent, candidate)
(financial_functions.py:270).

The pure-XLA twins (``impl="xla"``) keep CPU tests and
virtually-sharded runs working; parity is asserted in
tests/test_billpallas.py.

**The 89.5 ms/call month kernel is a measured VPU-compute floor, not a
scheduling artifact** (round-5 negative results, all at the 8k x 250
microbench point, tools/kernel_microbench.py):

  * exact piecewise-linear / sorted-hinge formulation (imports(s) is
    piecewise linear in s; candidate-bin histogram + suffix sums,
    O(H log R + B*R) arithmetic): 27,205 ms in XLA — 300x SLOWER.
    TPUs have no vectorized VMEM gather, so searchsorted/scatter
    serialize; any vectorized evaluation touches R x H lanes anyway,
    at which point the direct relu pass is optimal.
  * prebuilt-mask narrow MXU dot (VPU does only fma+relu, all masked
    reductions as [r,768]x[768,P+1] dots): 98.8 ms — the narrow dot
    costs more than the VPU masked reductions it replaces.
  * rank-1 MXU net build ([r,2]x[2,768] so the VPU does ONLY relu):
    149.0 ms — a K=2 contraction wastes the systolic array and stalls
    the VPU/MXU pipeline; with Precision.HIGHEST (3-pass f32): 652 ms.

  The kernel's 38.6G lane-ops of fma+relu at the v5e VPU's ~1G
  lane-op/s/lane-group rate bound the call at ~75-80 ms; 89.5 ms is
  ~97% of that bound with the masked reductions riding along.

**Past the floor: remove lanes, don't reschedule them.** The 38.6G
lane-op count assumes every candidate touches the full hour axis, but
rooftop-solar generation is structurally zero at night: wherever
``gen == 0``, ``relu(load - s*gen) == relu(load)`` for EVERY candidate
``s``, so roughly half the hours contribute candidate-INDEPENDENT
sums. The daylight-compacted layout (:func:`daylight_layout`) exploits
this: the union daylight mask per calendar month (over the whole
generation bank) defines per-month compacted segments (each padded to
a 128-lane multiple), the nonlinear kernels run only over those lanes,
and the night hours' bucket sums — signed, import, and sell-weighted,
all linear in nothing — are precomputed once per call
(:func:`_night_sums`) and added back. On the synthetic diurnal banks
the compacted layout is 4608 lanes vs the 9216 full month-padded
lanes: 2.0x fewer lane-ops against a ~97%-of-floor kernel
(tools/kernel_microbench.py ``compact``; real solar banks land at
1.5-2x depending on the longest summer month). Config-gated
(``RunConfig.daylight_compact``); the full-hour path stays the
default-on parity oracle.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dgen_tpu.ops.bill import tiered_charge
from dgen_tpu.ops.tariff import HOURS, MONTHS, NET_BILLING, hour_month_map
from dgen_tpu.parallel.mesh import agent_spec

H_PAD = 8832          # 8760 rounded up to 69 * 128 lanes
B_PAD = 128           # bucket axis = MXU-friendly output width
SELL_COL = B_PAD - 1  # column of M carrying the hourly sell rate
PAD_BUCKET = B_PAD - 2  # bucket id for padding hours (values are 0 anyway)

#: month-padded hour layout: month m occupies lanes [m*768, m*768+len_m)
#: (768 = 6 * 128 lanes >= 744, the longest month), zero-filled beyond —
#: makes the hour->month map POSITIONAL so the kernel needs no month
#: comparisons at all (see _kernel_month)
MONTH_SLOT = 768
H_MONTHS = 12 * MONTH_SLOT

_HOUR_MONTH = hour_month_map()


#: full-hour month segment lengths (every month gets the 768-lane slot)
FULL_SEG_LENS = (MONTH_SLOT,) * MONTHS


def _month_layout() -> tuple[np.ndarray, np.ndarray]:
    """(gather idx [H_MONTHS] int32, valid [H_MONTHS] f32) for the
    month-padded repack; cached numpy (no backend touch at import)."""
    hm = np.asarray(_HOUR_MONTH)
    idx = np.zeros(H_MONTHS, np.int32)
    valid = np.zeros(H_MONTHS, np.float32)
    for m in range(MONTHS):
        hrs = np.nonzero(hm == m)[0]
        idx[m * MONTH_SLOT:m * MONTH_SLOT + len(hrs)] = hrs
        valid[m * MONTH_SLOT:m * MONTH_SLOT + len(hrs)] = 1.0
    return idx, valid


_MONTH_IDX, _MONTH_VALID = _month_layout()


@dataclasses.dataclass(frozen=True, eq=False)
class DaylightLayout:
    """Compacted month-padded hour layout for the candidate kernels.

    Built host-side once per scenario (:func:`daylight_layout`) from
    the generation shape bank: month m's DAYLIGHT hours (union over the
    whole bank — any agent's gen can be nonzero there) occupy the
    static lane segment ``[offs[m], offs[m] + seg_lens[m])``, padded to
    a 128-lane multiple and zero-filled beyond. Night hours never enter
    the kernels: ``relu(load - s*gen) == relu(load)`` wherever
    ``gen == 0``, so their bucket sums are candidate-independent and
    are added back from :func:`_night_sums`.

    Deliberately NOT a pytree: the hour maps are HOST numpy constants
    (hashable — the object rides ``static_argnames`` like the layout
    tuple it is), so the engines fold them into the executable exactly
    like the full-hour ``_MONTH_IDX`` — a traced index operand would
    instead lower the repack to the pathologically slow TPU runtime
    gather the bill engine goes out of its way to avoid (see
    ``bill.select_by_period``).
    """

    idx: np.ndarray    # [sum(seg_lens)] int32 gather into the 8760 axis
    valid: np.ndarray  # [sum(seg_lens)] f32, 1 = real daylight lane
    night: np.ndarray  # [8760] f32, 1 = structurally-zero-gen hour
    seg_lens: tuple

    def __post_init__(self):
        for a in (self.idx, self.valid, self.night):
            a.setflags(write=False)
        object.__setattr__(
            self, "_key",
            (self.seg_lens, self.idx.tobytes(), self.night.tobytes()),
        )

    def __eq__(self, other) -> bool:
        return isinstance(other, DaylightLayout) and self._key == other._key

    def __hash__(self) -> int:
        return hash(self._key)

    @property
    def n_lanes(self) -> int:
        return int(sum(self.seg_lens))

    def uniform(self) -> "DaylightLayout":
        """This layout with every month padded to the LONGEST month's
        segment length — the uniform-block form the segment-streaming
        engine needs (:func:`_sums_pallas_stream` pipelines one
        fixed-shape (agent-block x month-segment) grid; variable
        ``seg_lens`` would change the block shape per grid step).
        Costs ``12 * max(seg_lens) - n_lanes`` extra zero lanes; still
        compacted whenever any month is shorter than the longest."""
        seg = max(self.seg_lens)
        if all(s == seg for s in self.seg_lens):
            return self
        idx = np.zeros(MONTHS * seg, np.int32)
        valid = np.zeros(MONTHS * seg, np.float32)
        off = 0
        for m, ln in enumerate(self.seg_lens):
            cnt = int(np.sum(self.valid[off:off + ln]))
            idx[m * seg:m * seg + cnt] = self.idx[off:off + cnt]
            valid[m * seg:m * seg + cnt] = 1.0
            off += ln
        return DaylightLayout(
            idx=idx, valid=valid, night=self.night.copy(),
            seg_lens=(seg,) * MONTHS,
        )


def daylight_layout(gen_bank: np.ndarray) -> Optional[DaylightLayout]:
    """Union-daylight compacted layout from a [*, 8760] generation
    bank (host numpy; no backend touch). Returns None when compaction
    cannot drop at least one 128-lane block from any month (a bank
    with no structurally-zero hours)."""
    day = np.any(np.asarray(gen_bank) > 0.0, axis=0)
    if day.shape != (HOURS,):
        raise ValueError(f"gen bank must have a trailing {HOURS} axis")
    hm = np.asarray(_HOUR_MONTH)
    seg_lens = []
    for m in range(MONTHS):
        count = int(np.sum(day[hm == m]))
        seg_lens.append(max(128, -(-count // 128) * 128))
    if sum(seg_lens) >= H_MONTHS:
        return None
    n_lanes = sum(seg_lens)
    idx = np.zeros(n_lanes, np.int32)
    valid = np.zeros(n_lanes, np.float32)
    off = 0
    for m, seg in enumerate(seg_lens):
        hrs = np.nonzero((hm == m) & day)[0]
        idx[off:off + len(hrs)] = hrs
        valid[off:off + len(hrs)] = 1.0
        off += seg
    return DaylightLayout(
        idx=idx,
        valid=valid,
        night=(~day).astype(np.float32),
        seg_lens=tuple(seg_lens),
    )


def _seg_offsets(seg_lens) -> tuple:
    offs = []
    off = 0
    for s in seg_lens:
        offs.append(off)
        off += s
    return tuple(offs)


def _sums_out_dtype(load, gen, sell=None):
    """Engine output dtype rule: bf16 banks in -> bf16 bucket sums out.

    The [N, R, B_PAD] candidate sums are the other O(N*R) HBM term of
    the streaming chunk (comparable to the hour streams at national
    scale), and monthly-kWh sums at bank precision add the same ~0.4%
    relative rounding the bf16 inputs already carry — accumulation
    stays f32 in VMEM; only the stored result is bank-precision. The
    battery forward pass mixes a f32 dispatch trace into ``gen`` and
    therefore keeps f32 sums automatically.

    int8 quantized banks alone keep f32 sums (the codes carry no
    storage dtype to mirror); composed with bf16 banks (``sell`` at
    bf16 — the recommended national-scale setting) the sums store
    bf16, by the same bank-precision argument.
    """
    if load.dtype == jnp.bfloat16 and gen.dtype == jnp.bfloat16:
        return jnp.bfloat16
    if (load.dtype == jnp.int8 and sell is not None
            and sell.dtype == jnp.bfloat16):
        return jnp.bfloat16
    return jnp.float32


def _night_sums(load, sell, bucket_id, night, n_periods, with_signed):
    """Candidate-independent night bucket sums in the kernel's
    [N, B_PAD] output layout: wherever ``gen == 0``,
    ``relu(load - s*gen) == relu(load)`` and the signed net is just
    ``load`` — for EVERY candidate scale. Computed once per engine
    call (O(N*H), pure XLA) and broadcast-added over the candidate
    axis; returns (imports, signed-or-None)."""
    from dgen_tpu.ops.bill import monthly_period_sums

    n = load.shape[0]
    nb = MONTHS * n_periods
    hour_period = (bucket_id % n_periods).astype(jnp.int32)
    sell_f = sell.astype(jnp.float32)

    def bucketize(x):  # [N, H] -> [N, nb] month-major
        mp = jax.vmap(
            lambda row, hp: monthly_period_sums(row, hp, n_periods)
        )(x, hour_period)
        return mp.reshape(n, nb)

    def pack(x):  # [N, H] night stream -> [N, B_PAD] layout row
        out = jnp.zeros((n, B_PAD), jnp.float32)
        out = out.at[:, :nb].set(bucketize(x))
        return out.at[:, SELL_COL].set(jnp.sum(x * sell_f, axis=1))

    load_n = load.astype(jnp.float32) * night[None, :]
    imp = pack(jnp.maximum(load_n, 0.0))
    if not with_signed:
        return imp, None
    return imp, pack(load_n)


def _kernel(scales_ref, load_ref, gen_ref, sell_ref, bucket_ref,
            *out_refs, r_pad, h_chunk, with_signed, bf16):
    """One agent per program: [r_pad, B_PAD] bucket sums.

    Outputs: (imports,) or (imports, signed) when ``with_signed``.

    ``bf16`` is inert on this stack, kept for API stability: the
    runtime compiles with ``--xla_allow_excess_precision=true``, which
    (a) lets Mosaic elide the f32->bf16->f32 casts and (b) already runs
    the f32 dot at the MXU's native bf16 input precision — measured
    round 3: the bf16 variant is bit-identical to f32 and the same
    speed, and a genuinely-bf16-operand variant (bf16 HBM inputs, no
    elidable casts) was also no faster, confirming the contraction is
    not the bottleneck (the per-program cost is VPU one-hot/net work
    serialized with the dot).
    """
    scales = scales_ref[0, 0, :]                           # [r_pad]
    acc_i = jnp.zeros((r_pad, B_PAD), jnp.float32)
    acc_s = jnp.zeros((r_pad, B_PAD), jnp.float32) if with_signed else None
    mm_dtype = jnp.bfloat16 if bf16 else jnp.float32

    for h0 in range(0, H_PAD, h_chunk):
        # upcast on read: inputs may arrive bf16 (bf16 profile banks)
        load = load_ref[0, 0, h0:h0 + h_chunk].astype(jnp.float32)  # [Hc]
        gen = gen_ref[0, 0, h0:h0 + h_chunk].astype(jnp.float32)
        sell = sell_ref[0, 0, h0:h0 + h_chunk].astype(jnp.float32)
        bucket = bucket_ref[0, 0, h0:h0 + h_chunk]

        col = jax.lax.broadcasted_iota(jnp.int32, (h_chunk, B_PAD), 1)
        onehot = (bucket[:, None] == col).astype(mm_dtype)
        m = jnp.where(col == SELL_COL, sell[:, None].astype(mm_dtype), onehot)

        net = load[None, :] - scales[:, None] * gen[None, :]  # [r_pad, Hc]
        acc_i = acc_i + jnp.dot(
            jnp.maximum(net, 0.0).astype(mm_dtype), m,
            preferred_element_type=jnp.float32,
        )
        if with_signed:
            acc_s = acc_s + jnp.dot(
                net.astype(mm_dtype), m, preferred_element_type=jnp.float32
            )

    out_refs[0][0] = acc_i
    if with_signed:
        out_refs[1][0] = acc_s


def _kernel_month(scales_ref, load_ref, gen_ref, sell_ref, period_ref,
                  *out_refs, r_pad, r_chunk, n_periods, with_signed,
                  seg_lens=FULL_SEG_LENS):
    """One agent per program: month-blocked masked reductions.

    The round-3 kernel built a per-agent [H, 128] bucket one-hot in VMEM
    and contracted against it on the MXU; the round-4 trace
    (tools/kernel_microbench.py) showed that iota-compare-select build
    was 54% of device time (92 of 171 ms/call at 8k agents x 250
    scales) while the MXU dot itself was ~6 ms — and that the build
    stays ~80 ms no matter how it is sliced (positional per-month
    builds, B_PAD=64: both no better; lane padding swallows narrow
    widths). This formulation needs NO one-hot and NO matmul:

      * inputs arrive month-padded ([12 * 768] lanes, zero-filled), so
        the hour->month map is positional — 12 static 768-lane slices;
      * within a month, TOU-period sums use n_periods-1 masked row
        reductions, the last period arriving by subtraction from the
        month total (documented f32 cancellation ~3e-4 relative, inside
        the engine's pinned parity tolerance);
      * the sell-weighted sum accumulates across months in the same
        pass.

    Measured 89.5 ms/call vs 171 ms for the dot kernel (same shapes) —
    within ~20% of the irreducible net-build floor (net+relu alone:
    73 ms). Outputs keep the dot kernel's layout ([r_pad, B_PAD],
    bucket cols month-major, sell sums in SELL_COL).

    ``seg_lens`` are the static per-month lane lengths: the full
    layout's (768,)*12 or a :class:`DaylightLayout`'s compacted
    segments (same positional-month contract, just fewer lanes).
    Input refs may be bf16 (bf16 profile banks); the kernel upcasts on
    read and accumulates in f32.
    """
    scales_all = scales_ref[0, 0, :]                        # [r_pad]
    nb = MONTHS * n_periods
    offs = _seg_offsets(seg_lens)

    for r0 in range(0, r_pad, r_chunk):
        scales = scales_all[r0:r0 + r_chunk]
        cols_i = []
        cols_s = []
        sell_i = jnp.zeros((r_chunk,), jnp.float32)
        sell_s = jnp.zeros((r_chunk,), jnp.float32)
        for m in range(MONTHS):
            lo, ln = offs[m], seg_lens[m]
            load = load_ref[0, 0, lo:lo + ln].astype(jnp.float32)
            gen = gen_ref[0, 0, lo:lo + ln].astype(jnp.float32)
            sell = sell_ref[0, 0, lo:lo + ln].astype(jnp.float32)
            period = period_ref[0, 0, lo:lo + ln]

            net = load[None, :] - scales[:, None] * gen[None, :]
            pos = jnp.maximum(net, 0.0)                 # [r_chunk, 768]
            sell_i = sell_i + jnp.sum(pos * sell[None, :], axis=1)
            rem_i = jnp.sum(pos, axis=1)
            if with_signed:
                sell_s = sell_s + jnp.sum(net * sell[None, :], axis=1)
                rem_s = jnp.sum(net, axis=1)
            for p in range(n_periods - 1):
                mask = (period == p).astype(jnp.float32)[None, :]
                s_pm = jnp.sum(pos * mask, axis=1)
                cols_i.append(s_pm)
                rem_i = rem_i - s_pm
                if with_signed:
                    sgn_pm = jnp.sum(net * mask, axis=1)
                    cols_s.append(sgn_pm)
                    rem_s = rem_s - sgn_pm
            cols_i.append(rem_i)
            if with_signed:
                cols_s.append(rem_s)

        fill = jnp.zeros((r_chunk, B_PAD - nb - 1), jnp.float32)
        out_i = jnp.concatenate(
            [jnp.stack(cols_i, axis=1), fill, sell_i[:, None]], axis=1)
        # accumulate f32, store at the output ref's dtype (bf16 under
        # bf16 profile banks — sums at bank precision, half the HBM)
        out_refs[0][0, r0:r0 + r_chunk, :] = out_i.astype(out_refs[0].dtype)
        if with_signed:
            out_s = jnp.concatenate(
                [jnp.stack(cols_s, axis=1), fill, sell_s[:, None]], axis=1)
            out_refs[1][0, r0:r0 + r_chunk, :] = \
                out_s.astype(out_refs[1].dtype)


def _kernel_month_pair(scales_ref, load_ref, gen_ref,
                       sell_a_ref, period_a_ref, sell_b_ref, period_b_ref,
                       out_a_ref, out_b_ref, *, r_pad, r_chunk, n_periods,
                       seg_lens=FULL_SEG_LENS):
    """Imports bucket sums for TWO tariff structures over ONE net grid.

    Rate-switch populations (reference apply_rate_switch,
    agent_mutation/elec.py:838-845) price every candidate on both the
    switched and the original tariff; the two evaluations share
    ``net = load - s * gen`` and its relu — the kernel's dominant cost
    (net+relu ~73 of 89 ms/call) — so fusing them saves ~40% over two
    single-tariff calls. Only the per-period masks and the sell row
    differ; the month total is computed once.
    """
    scales_all = scales_ref[0, 0, :]
    nb = MONTHS * n_periods
    offs = _seg_offsets(seg_lens)

    for r0 in range(0, r_pad, r_chunk):
        scales = scales_all[r0:r0 + r_chunk]
        cols_a = []
        cols_b = []
        sell_acc_a = jnp.zeros((r_chunk,), jnp.float32)
        sell_acc_b = jnp.zeros((r_chunk,), jnp.float32)
        for m in range(MONTHS):
            lo, ln = offs[m], seg_lens[m]
            load = load_ref[0, 0, lo:lo + ln].astype(jnp.float32)
            gen = gen_ref[0, 0, lo:lo + ln].astype(jnp.float32)
            sell_a = sell_a_ref[0, 0, lo:lo + ln].astype(jnp.float32)
            period_a = period_a_ref[0, 0, lo:lo + ln]
            sell_b = sell_b_ref[0, 0, lo:lo + ln].astype(jnp.float32)
            period_b = period_b_ref[0, 0, lo:lo + ln]

            net = load[None, :] - scales[:, None] * gen[None, :]
            pos = jnp.maximum(net, 0.0)                 # shared
            sell_acc_a = sell_acc_a + jnp.sum(pos * sell_a[None, :], axis=1)
            sell_acc_b = sell_acc_b + jnp.sum(pos * sell_b[None, :], axis=1)
            tot = jnp.sum(pos, axis=1)                  # shared month total
            rem_a = tot
            rem_b = tot
            for p in range(n_periods - 1):
                mask_a = (period_a == p).astype(jnp.float32)[None, :]
                s_a = jnp.sum(pos * mask_a, axis=1)
                cols_a.append(s_a)
                rem_a = rem_a - s_a
                mask_b = (period_b == p).astype(jnp.float32)[None, :]
                s_b = jnp.sum(pos * mask_b, axis=1)
                cols_b.append(s_b)
                rem_b = rem_b - s_b
            cols_a.append(rem_a)
            cols_b.append(rem_b)

        fill = jnp.zeros((r_chunk, B_PAD - nb - 1), jnp.float32)
        out_a_ref[0, r0:r0 + r_chunk, :] = jnp.concatenate(
            [jnp.stack(cols_a, axis=1), fill, sell_acc_a[:, None]], axis=1
        ).astype(out_a_ref.dtype)
        out_b_ref[0, r0:r0 + r_chunk, :] = jnp.concatenate(
            [jnp.stack(cols_b, axis=1), fill, sell_acc_b[:, None]], axis=1
        ).astype(out_b_ref.dtype)


def _pick_r_chunk(r_pad: int, with_signed: bool,
                  max_seg: int = MONTH_SLOT) -> int:
    """Largest multiple-of-8 scales chunk whose [r_chunk, max_seg]
    working set (net + pos + masked temporaries; signed keeps both
    live) stays well under the 16 MB VMEM. ``max_seg`` is the longest
    month segment (768 full-hour; less under a DaylightLayout, which
    buys proportionally larger scale chunks)."""
    live = 4 if with_signed else 3
    budget = 10_000_000
    r_chunk = min(r_pad, 1024)
    while r_chunk > 8 and live * 4 * r_chunk * max_seg > budget:
        r_chunk //= 2
    r_chunk = _round8(r_chunk)
    while r_pad % r_chunk:   # chunks must tile the padded scales axis
        r_chunk -= 8
    return r_chunk


def _pad_hours(x: jax.Array, fill=0.0) -> jax.Array:
    n, h = x.shape
    if h == H_PAD:
        return x
    return jnp.pad(x, ((0, 0), (0, H_PAD - h)), constant_values=fill)


def _round8(r: int) -> int:
    return ((r + 7) // 8) * 8


def _pick_h_chunk(r_pad: int, with_signed: bool) -> int:
    """Largest hour chunk whose working set fits VMEM (~16 MB/core).

    Per chunk the kernel holds net [r_pad, hc] f32, M [hc, B_PAD] f32,
    the accumulators and the resident input rows; the signed path keeps
    BOTH net and relu(net) live (each feeds its own dot), doubling the
    r_pad term. Fewer, larger chunks measured ~5-10%% faster at
    r_pad=256 (fewer VPU<->MXU pipeline boundaries); candidates are the
    divisors of H_PAD."""
    budget = 14_000_000  # leave headroom under the 16 MB VMEM
    r_live = (2 if with_signed else 1) * r_pad
    for hc in (8832, 4416, 2208, 1104, 552):
        if 4 * (r_live + B_PAD) * hc <= budget:
            return hc
    return 552


def _month_repack(arrays, idx=None, valid=None):
    """Month-positional repack shared by every pallas engine: gather
    each [N, 8760] array into the month-padded lane layout (zero-filled
    pad lanes — downstream sums see exact zeros) and add the kernel's
    singleton block dim. ``idx``/``valid`` default to the full-hour
    layout; a :class:`DaylightLayout` supplies compacted ones — both
    are HOST numpy constants, so XLA folds the gather (a traced index
    operand would hit the slow TPU runtime-gather path). The layout
    contract lives HERE only; _kernel_month/_kernel_month_pair consume
    it. Float streams keep their dtype (bf16 banks stay bf16 through
    VMEM; 0/1 valid is exact in bf16)."""
    if idx is None:
        idx = _MONTH_IDX
        valid = _MONTH_VALID
    out = []
    for a in arrays:
        if a.dtype == jnp.int32:
            out.append(a[:, idx][:, None, :])   # pad lanes harmless:
            # their VALUES are zeroed in the float streams
        else:
            out.append((a[:, idx] * valid.astype(a.dtype)[None, :])[:, None, :])
    return out


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PackedStreams:
    """Month-positional candidate-kernel inputs, gathered ONCE.

    ``_size_agents_fast`` calls the bucket-sums engines up to three
    times per year (two refine rounds + the battery forward run), and
    every call used to re-gather the ``[N, 8760]`` hour streams into
    the month-padded lane layout before re-reading them from HBM.
    This pytree is that repack done once per :func:`~dgen_tpu.ops.
    sizing.size_agents` call (``RunConfig.pack_once``): the engines
    consume the pre-packed lanes directly and, under a
    :class:`DaylightLayout`, reuse the candidate-independent night
    bucket sums instead of recomputing them per call.

    All leaves are TRACED arrays (``[N, L]`` lanes at bank dtype —
    bf16/int8 banks stay narrow through the pack); the static layout
    contract (which ``seg_lens`` the lanes follow) still rides the
    engines' hashable ``layout`` argument, and
    :func:`_prep_positional` cross-checks the lane count against it.

    ``sell_b``/``period_b``/``night_imp_b``: the second tariff
    structure of a rate-switch population (``import_sums_pair``);
    None otherwise. ``night_imp``/``night_imp_b`` are None for
    full-hour layouts (no night lanes to add back).
    """

    load: jax.Array                          # [N, L]
    gen: jax.Array                           # [N, L]
    sell: jax.Array                          # [N, L]
    period: jax.Array                        # [N, L] int32
    night_imp: Optional[jax.Array] = None    # [N, B_PAD]
    sell_b: Optional[jax.Array] = None
    period_b: Optional[jax.Array] = None
    night_imp_b: Optional[jax.Array] = None

    def tariff_b(self) -> "PackedStreams":
        """View of the SECOND tariff structure as a single-tariff pack
        (the XLA pair fallback prices the two structures in two
        independent passes)."""
        return PackedStreams(
            load=self.load, gen=self.gen, sell=self.sell_b,
            period=self.period_b, night_imp=self.night_imp_b,
        )


def pack_streams(
    load: jax.Array,       # [N, 8760]
    gen: jax.Array,        # [N, 8760]
    sell: jax.Array,       # [N, 8760]
    bucket_id: jax.Array,  # [N, 8760] int32 month-major bucket ids
    n_buckets: int,
    layout: Optional[DaylightLayout] = None,
    sell_b: Optional[jax.Array] = None,
    bucket_b: Optional[jax.Array] = None,
) -> PackedStreams:
    """Build the pack-once stream bundle for the candidate engines.

    ``layout`` must be the SAME static layout later passed to the
    engine calls that consume the pack (None = full-hour month-padded
    lanes). Night import sums are precomputed here for compacted
    layouts — once per pack instead of once per engine call."""
    n_periods = n_buckets // MONTHS
    idx = None if layout is None else layout.idx
    valid = None if layout is None else layout.valid
    period = (bucket_id % n_periods).astype(jnp.int32)
    arrays = [load, gen, sell, period]
    if sell_b is not None:
        period_b = (bucket_b % n_periods).astype(jnp.int32)
        arrays += [sell_b, period_b]
    packed = [a[:, 0, :] for a in _month_repack(arrays, idx, valid)]
    night_imp = night_imp_b = None
    if layout is not None:
        night_imp, _ = _night_sums(
            load, sell, bucket_id, layout.night, n_periods, False)
        if sell_b is not None:
            night_imp_b, _ = _night_sums(
                load, sell_b, bucket_b, layout.night, n_periods, False)
    return PackedStreams(
        load=packed[0], gen=packed[1], sell=packed[2], period=packed[3],
        night_imp=night_imp,
        sell_b=packed[4] if sell_b is not None else None,
        period_b=packed[5] if sell_b is not None else None,
        night_imp_b=night_imp_b,
    )


def _prep_positional(load, gen, sell, bucket_id, n_periods, layout,
                     packed):
    """Shared engine input prep: month-positional [N, L] streams.

    ``packed`` given: consume its lanes (cross-checking the lane count
    against the static layout); a non-None raw ``gen`` alongside a
    pack is the battery forward run's fresh dispatch stream and is
    repacked here (full-hour layouts only — the battery breaks the
    night-zero premise, so callers never combine it with a compacted
    pack). ``packed`` None: gather per call (the legacy path)."""
    segs = FULL_SEG_LENS if layout is None else layout.seg_lens
    h_lanes = sum(segs)
    idx = None if layout is None else layout.idx
    valid = None if layout is None else layout.valid
    if packed is not None:
        if packed.load.shape[-1] != h_lanes:
            raise ValueError(
                f"packed streams carry {packed.load.shape[-1]} lanes "
                f"but the engine layout expects {h_lanes}; build them "
                "with pack_streams(..., layout=<the same layout>)"
            )
        if gen is None:
            gen_p = packed.gen
        else:
            if layout is not None:
                raise ValueError(
                    "a fresh gen stream cannot ride a daylight-"
                    "compacted pack (battery output is nonzero at "
                    "night); price it full-hour"
                )
            (gen3,) = _month_repack((gen,), idx, valid)
            gen_p = gen3[:, 0, :]
        return packed.load, gen_p, packed.sell, packed.period
    period = (bucket_id % n_periods).astype(jnp.int32)
    load_p, gen_p, sell_p, period_p = _month_repack(
        (load, gen, sell, period), idx, valid)
    return (load_p[:, 0, :], gen_p[:, 0, :], sell_p[:, 0, :],
            period_p[:, 0, :])


def _night_for(load, sell, bucket_id, layout, n_periods, with_signed,
               packed):
    """(night_imports, night_signed) to add back, honoring a pack's
    precomputed sums. The signed+compacted+packed combination has no
    caller (bucket_sums never takes a layout) and is rejected."""
    if layout is None:
        return None, None
    if packed is not None:
        if with_signed:
            raise ValueError(
                "packed streams carry import night sums only; the "
                "signed engine must repack (no caller needs this)"
            )
        return packed.night_imp, None
    return _night_sums(load, sell, bucket_id, layout.night, n_periods,
                       with_signed)


def _quant_fold(scales, load_scale, gen_scale):
    """int8 quantized banks: fold the per-agent dequant scales into the
    candidate scale grid so the kernels run UNCHANGED in quantized
    units. With real load = ls*ql and real gen = gs*qg (ql/qg the int8
    codes, upcast on read):

        relu(ls*ql - s*gs*qg) = ls * relu(ql - (s*gs/ls)*qg)

    — every bucket column and the sell-weighted column scale uniformly
    by ``ls`` (sell is never quantized, so its factor rides the same
    ``ls``). Returns (effective scales, per-agent post factor); the
    post factor is applied by :func:`_quant_unfold` AFTER the engine
    (outside shard_map — a cheap [N, R, B] elementwise). ``ls == 0``
    (an identically-zero load row) is floored inside the fold and
    zeroed exactly by the post multiply."""
    if load_scale is None:
        return scales, None
    safe = jnp.maximum(load_scale, jnp.float32(1e-20))
    return scales * (gen_scale / safe)[:, None], load_scale


def _quant_unfold(outs, post):
    if post is None:
        return outs
    return tuple(
        (o.astype(jnp.float32) * post[:, None, None]).astype(o.dtype)
        for o in outs
    )


def _sums_pallas(load, gen, sell, bucket_id, scales, packed=None, *,
                 with_signed, n_periods=None, bf16=False, layout=None):
    """Month-blocked masked-reduction engine (see _kernel_month).

    ``bucket_id`` must be the canonical month-major layout
    (hourly_bucket_ids: month * n_periods + period), from which the
    per-hour TOU period is recovered as ``bucket_id % n_periods``.

    ``layout``: optional :class:`DaylightLayout` (a static host-side
    constant) — the kernel then runs only over the compacted daylight
    lanes and the candidate-independent night bucket sums are added
    back (exact wherever the layout's premise — gen == 0 off-daylight
    — holds, which :func:`daylight_layout` guarantees by construction
    for bank-derived gen).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = scales.shape[0]
    r = scales.shape[1]
    r_pad = _round8(r)
    segs = FULL_SEG_LENS if layout is None else layout.seg_lens
    h_lanes = sum(segs)
    r_chunk = _pick_r_chunk(r_pad, with_signed, max(segs))

    load_2d, gen_2d, sell_2d, period_2d = _prep_positional(
        load, gen, sell, bucket_id, n_periods, layout, packed)
    out_dtype = _sums_out_dtype(load_2d, gen_2d, sell_2d)
    load_p, gen_p, sell_p, period_p = (
        a[:, None, :] for a in (load_2d, gen_2d, sell_2d, period_2d))
    scales_p = jnp.pad(scales, ((0, 0), (0, r_pad - r)))[:, None, :]

    out3 = lambda i: (i, 0, 0)
    n_out = 2 if with_signed else 1
    outs = pl.pallas_call(
        partial(_kernel_month, r_pad=r_pad, r_chunk=r_chunk,
                n_periods=n_periods, with_signed=with_signed,
                seg_lens=segs),
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, 1, r_pad), out3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, h_lanes), out3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, h_lanes), out3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, h_lanes), out3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, h_lanes), out3, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, r_pad, B_PAD), out3, memory_space=pltpu.VMEM)
        ] * n_out,
        out_shape=[
            jax.ShapeDtypeStruct((n, r_pad, B_PAD), out_dtype)
        ] * n_out,
        cost_estimate=pl.CostEstimate(
            flops=(4 + 2 * n_periods) * n_out * n * r_pad * h_lanes,
            bytes_accessed=5 * n * h_lanes * 4,
            transcendentals=0,
        ),
    )(scales_p, load_p, gen_p, sell_p, period_p)
    # imports first to match the dot engine's historical output order
    outs = tuple(o[:, :r] for o in outs)
    if layout is None:
        return outs
    night_i, night_s = _night_for(
        load, sell, bucket_id, layout, n_periods, with_signed, packed)
    add = lambda o, nn: (
        o.astype(jnp.float32) + nn[:, None, :]).astype(out_dtype)
    if with_signed:
        return (add(outs[0], night_i), add(outs[1], night_s))
    return (add(outs[0], night_i),)


def _pick_block_n(n: int, dtype=None) -> int:
    """Agents per stream-engine block. 8 sublanes is the f32 native
    tile; int8 streams prefer 32 (the int8 min sublane tile) when the
    agent count allows. Always a divisor of ``n``."""
    prefs = (32, 16, 8, 4, 2, 1) if dtype == jnp.int8 else (8, 4, 2, 1)
    for b in prefs:
        if n % b == 0:
            return b
    return 1


def _pick_r_chunk_stream(r_pad: int, block_n: int, seg: int,
                         with_signed: bool, n_periods: int) -> int:
    """Largest multiple-of-8 scales chunk whose [block_n, r_chunk, seg]
    working set (net + pos + masked temporaries) fits the stream
    engine's VMEM budget NET of the fixed residents: the
    double-buffered stream blocks, the [block_n, r_pad, B_PAD] output
    block(s), and the [12, P, block_n, r_pad] accumulator scratch —
    all of which stay live across every r-chunk."""
    n_out = 2 if with_signed else 1
    resident = (
        2 * 4 * block_n * seg * 4                       # 2x4 stream bufs
        + n_out * block_n * r_pad * B_PAD * 4           # output block(s)
        + n_out * (MONTHS * n_periods + 1) * block_n * r_pad * 4  # acc
    )
    live = 4 if with_signed else 3
    budget = max(10_000_000 - resident, 1_000_000)
    r_chunk = min(r_pad, 512)
    while r_chunk > 8 and live * 4 * block_n * r_chunk * seg > budget:
        r_chunk //= 2
    r_chunk = _round8(r_chunk)
    while r_pad % r_chunk:
        r_chunk -= 8
    return max(r_chunk, 8)


def _kernel_stream(scales_ref, load_ref, gen_ref, sell_ref, period_ref,
                   *rest, r_pad, r_chunk, n_periods, with_signed,
                   block_n):
    """(agent-block x month-segment) grid step: ``block_n`` agents,
    ONE month segment.

    The month axis is the inner (fastest-varying) grid dimension, so
    the Pallas pipeline double-buffers the stream blocks: the DMA of
    month ``m+1``'s [block_n, seg] lanes overlaps compute on month
    ``m`` — the whole agent stream is never resident at once (the
    grid=(n,) kernels hold all 12 months in VMEM and serialize the
    fetch ahead of the program). Partial bucket sums live in VMEM
    scratch across the segment steps:

      * ``acc`` [12, P, block_n, r_pad] — each (month, period) tile is
        written exactly once (bucket columns are per-month); the
        month index is the leading scratch dim so the per-step write
        is a cheap dynamic-slice on rows, never on lanes;
      * ``sell_acc`` [block_n, r_pad] — the sell-weighted sum is the
        one cross-month accumulation (zeroed at m == 0);
      * the [block_n, r_pad, B_PAD] output block keeps the
        ``_kernel_month`` layout and is assembled once, on the last
        segment step (its block index is month-invariant, so Pallas
        keeps it resident across the inner axis).

    Math is ``_kernel_month``'s: per-period masked row reductions with
    the last period by subtraction from the month total (same f32
    cancellation envelope), f32 accumulation, upcast-on-read inputs
    (bf16 or int8 quantized banks).
    """
    from jax.experimental import pallas as pl

    nb = MONTHS * n_periods
    if with_signed:
        (out_i_ref, out_s_ref, acc_i, sell_i_acc,
         acc_s, sell_s_acc) = rest
    else:
        out_i_ref, acc_i, sell_i_acc = rest
        out_s_ref = acc_s = sell_s_acc = None
    m = pl.program_id(1)

    @pl.when(m == 0)
    def _init():
        sell_i_acc[...] = jnp.zeros_like(sell_i_acc)
        if with_signed:
            sell_s_acc[...] = jnp.zeros_like(sell_s_acc)

    load = load_ref[...].astype(jnp.float32)       # [block_n, seg]
    gen = gen_ref[...].astype(jnp.float32)
    sell = sell_ref[...].astype(jnp.float32)
    period = period_ref[...]
    scales_all = scales_ref[...]                   # [block_n, r_pad]

    for r0 in range(0, r_pad, r_chunk):
        scales = scales_all[:, r0:r0 + r_chunk]
        net = (load[:, None, :]
               - scales[:, :, None] * gen[:, None, :])
        pos = jnp.maximum(net, 0.0)                # [bn, rc, seg]
        sell_i_acc[:, r0:r0 + r_chunk] = (
            sell_i_acc[:, r0:r0 + r_chunk]
            + jnp.sum(pos * sell[:, None, :], axis=2))
        rem_i = jnp.sum(pos, axis=2)
        if with_signed:
            sell_s_acc[:, r0:r0 + r_chunk] = (
                sell_s_acc[:, r0:r0 + r_chunk]
                + jnp.sum(net * sell[:, None, :], axis=2))
            rem_s = jnp.sum(net, axis=2)
        for p in range(n_periods - 1):
            mask = (period == p).astype(jnp.float32)[:, None, :]
            s_pm = jnp.sum(pos * mask, axis=2)
            acc_i[m, p, :, r0:r0 + r_chunk] = s_pm
            rem_i = rem_i - s_pm
            if with_signed:
                sgn_pm = jnp.sum(net * mask, axis=2)
                acc_s[m, p, :, r0:r0 + r_chunk] = sgn_pm
                rem_s = rem_s - sgn_pm
        acc_i[m, n_periods - 1, :, r0:r0 + r_chunk] = rem_i
        if with_signed:
            acc_s[m, n_periods - 1, :, r0:r0 + r_chunk] = rem_s

    @pl.when(m == pl.num_programs(1) - 1)
    def _emit():
        fill = jnp.zeros((block_n, r_pad, B_PAD - nb - 1), jnp.float32)

        def assemble(acc, sell_acc):
            acc_v = acc[...]                      # [12, P, bn, r_pad]
            sell_v = sell_acc[...]
            body = jnp.stack(
                [acc_v[mm, p]
                 for mm in range(MONTHS) for p in range(n_periods)],
                axis=2,
            )                                     # [bn, r_pad, nb]
            return jnp.concatenate(
                [body, fill, sell_v[:, :, None]], axis=2)

        out_i_ref[...] = assemble(acc_i, sell_i_acc).astype(
            out_i_ref.dtype)
        if with_signed:
            out_s_ref[...] = assemble(acc_s, sell_s_acc).astype(
                out_s_ref.dtype)


def _sums_pallas_stream(load, gen, sell, bucket_id, scales, packed=None,
                        *, with_signed, n_periods=None, bf16=False,
                        layout=None, interpret=False):
    """Segment-streaming engine (see :func:`_kernel_stream`): an
    (agent-block x month-segment) grid whose inner axis Pallas
    double-buffers, so HBM reads of segment m+1 overlap compute on m.

    Requires UNIFORM month segments (the full-hour 768-lane layout, or
    a :meth:`DaylightLayout.uniform` compacted one — callers resolve
    that before passing ``layout``). ``interpret`` runs the kernel in
    the Pallas interpreter (the CPU parity-test path — Mosaic only
    lowers on TPU)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = scales.shape[0]
    r = scales.shape[1]
    r_pad = _round8(r)
    segs = FULL_SEG_LENS if layout is None else layout.seg_lens
    if len(set(segs)) != 1:
        raise ValueError(
            "the stream engine needs uniform month segments; pass "
            "layout.uniform() (and pack against it)"
        )
    seg = segs[0]

    load_2d, gen_2d, sell_2d, period_2d = _prep_positional(
        load, gen, sell, bucket_id, n_periods, layout, packed)
    out_dtype = _sums_out_dtype(load_2d, gen_2d, sell_2d)
    block_n = _pick_block_n(n, load_2d.dtype)
    r_chunk = _pick_r_chunk_stream(r_pad, block_n, seg, with_signed,
                                   n_periods)
    scales_p = jnp.pad(scales, ((0, 0), (0, r_pad - r)))

    n_out = 2 if with_signed else 1
    stream_spec = pl.BlockSpec(
        (block_n, seg), lambda i, m: (i, m), memory_space=pltpu.VMEM)
    acc = pltpu.VMEM((MONTHS, n_periods, block_n, r_pad), jnp.float32)
    sell_acc = pltpu.VMEM((block_n, r_pad), jnp.float32)
    outs = pl.pallas_call(
        partial(_kernel_stream, r_pad=r_pad, r_chunk=r_chunk,
                n_periods=n_periods, with_signed=with_signed,
                block_n=block_n),
        grid=(n // block_n, MONTHS),
        in_specs=[
            pl.BlockSpec((block_n, r_pad), lambda i, m: (i, 0),
                         memory_space=pltpu.VMEM),
        ] + [stream_spec] * 4,
        out_specs=[
            pl.BlockSpec((block_n, r_pad, B_PAD), lambda i, m: (i, 0, 0),
                         memory_space=pltpu.VMEM)
        ] * n_out,
        out_shape=[
            jax.ShapeDtypeStruct((n, r_pad, B_PAD), out_dtype)
        ] * n_out,
        scratch_shapes=[acc, sell_acc] * n_out,
        cost_estimate=pl.CostEstimate(
            flops=(4 + 2 * n_periods) * n_out * n * r_pad * seg * MONTHS,
            bytes_accessed=(
                4 * n * seg * MONTHS * load_2d.dtype.itemsize
                + n * r_pad * B_PAD * 4),
            transcendentals=0,
        ),
        interpret=interpret,
    )(scales_p, load_2d, gen_2d, sell_2d, period_2d)
    outs = tuple(o[:, :r] for o in outs)
    if layout is None:
        return outs
    night_i, night_s = _night_for(
        load, sell, bucket_id, layout, n_periods, with_signed, packed)
    add = lambda o, nn: (
        o.astype(jnp.float32) + nn[:, None, :]).astype(out_dtype)
    if with_signed:
        return (add(outs[0], night_i), add(outs[1], night_s))
    return (add(outs[0], night_i),)


def _sums_pallas_pair(load, gen, sell_a, bucket_a, sell_b, bucket_b,
                      scales, packed=None, *, n_periods, layout=None):
    """Fused two-tariff imports engine (see _kernel_month_pair):
    (imports_a, imports_b), each [N, R, B_PAD]. Accepts the same
    optional static DaylightLayout as :func:`_sums_pallas` (night sums
    are added per tariff structure)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = scales.shape[0]
    r = scales.shape[1]
    r_pad = _round8(r)
    segs = FULL_SEG_LENS if layout is None else layout.seg_lens
    h_lanes = sum(segs)
    r_chunk = _pick_r_chunk(r_pad, with_signed=True,
                            max_seg=max(segs))  # 2 mask sets live

    if packed is not None:
        load_2d, gen_2d, sell_a_2d, period_a_2d = _prep_positional(
            load, gen, sell_a, bucket_a, n_periods, layout, packed)
        sell_b_2d, period_b_2d = packed.sell_b, packed.period_b
    else:
        idx = None if layout is None else layout.idx
        valid = None if layout is None else layout.valid
        (load_2d, gen_2d, sell_a_2d, period_a_2d, sell_b_2d,
         period_b_2d) = (
            a[:, 0, :] for a in _month_repack(
                (load, gen,
                 sell_a, (bucket_a % n_periods).astype(jnp.int32),
                 sell_b, (bucket_b % n_periods).astype(jnp.int32)),
                idx, valid,
            )
        )
    out_dtype = _sums_out_dtype(load_2d, gen_2d, sell_a_2d)
    (load_p, gen_p, sell_a_p, period_a_p, sell_b_p, period_b_p) = (
        a[:, None, :] for a in (load_2d, gen_2d, sell_a_2d,
                                period_a_2d, sell_b_2d, period_b_2d))
    scales_p = jnp.pad(scales, ((0, 0), (0, r_pad - r)))[:, None, :]

    out3 = lambda i: (i, 0, 0)
    outs = pl.pallas_call(
        partial(_kernel_month_pair, r_pad=r_pad, r_chunk=r_chunk,
                n_periods=n_periods, seg_lens=segs),
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, 1, r_pad), out3, memory_space=pltpu.VMEM),
        ] + [
            pl.BlockSpec((1, 1, h_lanes), out3, memory_space=pltpu.VMEM)
        ] * 6,
        out_specs=[
            pl.BlockSpec((1, r_pad, B_PAD), out3, memory_space=pltpu.VMEM)
        ] * 2,
        out_shape=[
            jax.ShapeDtypeStruct((n, r_pad, B_PAD), out_dtype)
        ] * 2,
        cost_estimate=pl.CostEstimate(
            flops=(5 + 4 * n_periods) * n * r_pad * h_lanes,
            bytes_accessed=7 * n * h_lanes * 4,
            transcendentals=0,
        ),
    )(scales_p, load_p, gen_p, sell_a_p, period_a_p, sell_b_p, period_b_p)
    outs = tuple(o[:, :r] for o in outs)
    if layout is None:
        return outs
    if packed is not None:
        night_a, night_b = packed.night_imp, packed.night_imp_b
    else:
        night_a, _ = _night_sums(
            load, sell_a, bucket_a, layout.night, n_periods, False)
        night_b, _ = _night_sums(
            load, sell_b, bucket_b, layout.night, n_periods, False)
    add = lambda o, nn: (
        o.astype(jnp.float32) + nn[:, None, :]).astype(out_dtype)
    return (add(outs[0], night_a), add(outs[1], night_b))


def _sums_pallas_dot(load, gen, sell, bucket_id, scales, with_signed,
                     n_periods=None, bf16=False):
    """Round-3 one-hot + MXU-dot engine, kept for A/B benchmarking
    (impl=\"pallas_dot\"); 1.9x slower than the month kernel on v5e."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = load.shape[0]
    r = scales.shape[1]
    r_pad = _round8(r)
    h_chunk = _pick_h_chunk(r_pad, with_signed)

    load_p = _pad_hours(load)[:, None, :]
    gen_p = _pad_hours(gen)[:, None, :]
    sell_p = _pad_hours(sell)[:, None, :]
    bucket_p = _pad_hours(bucket_id, fill=PAD_BUCKET).astype(jnp.int32)[:, None, :]
    scales_p = jnp.pad(scales, ((0, 0), (0, r_pad - r)))[:, None, :]

    out3 = lambda i: (i, 0, 0)
    n_out = 2 if with_signed else 1
    outs = pl.pallas_call(
        partial(_kernel, r_pad=r_pad, h_chunk=h_chunk,
                with_signed=with_signed, bf16=bf16),
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, 1, r_pad), out3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, H_PAD), out3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, H_PAD), out3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, H_PAD), out3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, H_PAD), out3, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, r_pad, B_PAD), out3, memory_space=pltpu.VMEM)
        ] * n_out,
        out_shape=[
            jax.ShapeDtypeStruct((n, r_pad, B_PAD), jnp.float32)
        ] * n_out,
        cost_estimate=pl.CostEstimate(
            flops=2 * n_out * n * r_pad * H_PAD * B_PAD,
            bytes_accessed=4 * n * H_PAD * 4,
            transcendentals=0,
        ),
    )(scales_p, load_p, gen_p, sell_p, bucket_p)
    return tuple(o[:, :r] for o in outs)


def _sums_xla(load, gen, sell, bucket_id, scales, packed=None, *,
              n_buckets, with_signed, layout=None, soft_tau=None):
    """Pure-XLA twin (CPU tests, sharded runs): one [N, H] pass per
    scale via lax.map, bucketed with per-period masked matmuls against
    the SHARED month one-hot — no per-agent [H, B] one-hot is ever
    materialized, so memory stays O(N*H) at any agent count.

    ``bucket_id = month * P + period`` implies
    ``period = bucket_id mod P`` (P = n_buckets // 12), so the period
    mask is recovered without needing the tariff here.

    With a static :class:`DaylightLayout` the per-scale pass runs over
    the compacted daylight lanes only — the same gather/positional-
    month algebra as the pallas kernel, so CPU parity tests exercise
    the compacted path's math, not just its results — and the night
    sums are added back exactly as on TPU.
    """
    from dgen_tpu.ops.bill import monthly_period_sums

    n_periods = n_buckets // MONTHS
    n = scales.shape[0]

    if layout is None and packed is None:
        hour_period = (bucket_id % n_periods).astype(jnp.int32)

        def bucketize(x):  # [N, H] -> [N, B] month-major
            mp = jax.vmap(
                lambda row, hp: monthly_period_sums(row, hp, n_periods)
            )(x, hour_period)                                # [N, 12, P]
            return mp.reshape(n, n_buckets)

        load_c, gen_c, sell_c = load, gen, sell
        out_dtype = _sums_out_dtype(load, gen, sell)
    else:
        # month-positional lanes: the compacted daylight gather, or a
        # pack-once bundle (which may be full-hour month-padded). The
        # gather indices are static numpy — constant-folded; float
        # lanes are zeroed beyond each month's real-hour count, the
        # hour->month map positional
        segs = FULL_SEG_LENS if layout is None else layout.seg_lens
        month_of_lane = np.repeat(np.arange(MONTHS), segs)   # static
        onehot_c = np.eye(MONTHS, dtype=np.float32)[month_of_lane]
        load_2d, gen_2d, sell_2d, period_c = _prep_positional(
            load, gen, sell, bucket_id, n_periods, layout, packed)
        out_dtype = _sums_out_dtype(load_2d, gen_2d, sell_2d)
        load_c = load_2d.astype(jnp.float32)
        gen_c = gen_2d.astype(jnp.float32)
        sell_c = sell_2d.astype(jnp.float32)

        def bucketize(x):  # [N, Hc] -> [N, B] month-major
            cols = [
                (x * (period_c == p).astype(x.dtype)) @ onehot_c
                for p in range(n_periods)
            ]
            return jnp.stack(cols, axis=-1).reshape(n, n_buckets)

    def per_scale(s_r):
        net = load_c - s_r[:, None] * gen_c                  # [N, Hc]
        if soft_tau is None:
            pos = jnp.maximum(net, 0.0)
        else:
            # the differentiable twin (dgen_tpu.grad): soft
            # import/export split, kW-unit temperature
            from dgen_tpu.grad.smooth import relu_t

            pos = relu_t(net, soft_tau)
        imports = bucketize(pos)
        imp_sell = jnp.sum(pos * sell_c, axis=1)
        if with_signed:
            return (imports, imp_sell), (bucketize(net),
                                         jnp.sum(net * sell_c, axis=1))
        return ((imports, imp_sell),)

    outs = jax.lax.map(per_scale, jnp.swapaxes(scales, 0, 1))
    nights = _night_for(
        load, sell, bucket_id, layout, n_periods, with_signed, packed)
    result = []
    for (buckets, sell_sum), night_o in zip(outs, nights):
        o = jnp.swapaxes(buckets, 0, 1)                      # [N, R, B]
        o = jnp.pad(o, ((0, 0), (0, 0), (0, B_PAD - n_buckets)))
        o = o.at[:, :, SELL_COL].set(jnp.swapaxes(sell_sum, 0, 1))
        if night_o is not None:
            o = o + night_o[:, None, :]
        result.append(o.astype(out_dtype))
    return tuple(result)


def _reject_packed_for_dot(packed) -> None:
    """The legacy pallas_dot A/B engine is a full-hour reference and
    never consumes packed streams — one guard shared by every engine
    wrapper so the contract cannot drift per call site."""
    if packed is not None:
        raise ValueError("pallas_dot is a full-hour A/B reference and "
                         "does not consume packed streams")


def _resolve_impl(impl: str) -> str:
    if impl == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "pallas_stream" and jax.default_backend() != "tpu":
        # Mosaic only lowers on TPU; elsewhere the XLA twin is the
        # stream engine's math oracle (RunConfig.stream_segments can
        # therefore stay on in configs that sometimes run on CPU)
        return "xla"
    return impl


def _maybe_shard_agents(fn, mesh, n_out: int, n_in: int = 5):
    """Run a bucket-sums engine per-shard over the agent axis.

    Every input/output carries the agent dim leading and the computation
    is fully per-agent (grid=(n,)), so under a >1-device mesh the engine
    runs unchanged on each shard — this is what lets the Pallas kernel
    (not partition-aware by itself) live inside the sharded year step
    instead of downgrading to the XLA twin. (A DaylightLayout rides in
    as closed-over host constants — shared hour-axis maps, identical on
    every shard.)
    """
    if mesh is None or mesh.devices.size <= 1:
        return fn
    from dgen_tpu.utils import compat

    # the agent dim shards over EVERY mesh axis (hosts x devices grids
    # included) — a single-axis spec here would replicate the inputs
    # across host rows and GSPMD would all-gather them back (J8)
    spec = agent_spec(mesh)
    # check_vma=False: pallas_call's out_shape ShapeDtypeStructs carry no
    # varying-manual-axes info, so the default vma check rejects the
    # kernel at trace time
    return compat.shard_map(
        fn, mesh=mesh, in_specs=(spec,) * n_in, out_specs=(spec,) * n_out,
        check_vma=False,
    )


def _check_buckets(n_buckets: int) -> None:
    # ids >= PAD_BUCKET would collide with the padding id / sell column
    # of the kernel's M matrix and silently corrupt bills
    if n_buckets > PAD_BUCKET - 1:
        raise ValueError(
            f"{n_buckets} buckets (12 x n_periods) exceeds the kernel "
            f"layout limit of {PAD_BUCKET - 1} (tariffs with more than "
            f"{(PAD_BUCKET - 1) // 12} TOU periods are unsupported)"
        )


#: compile-time arguments of the bucket-sums engines — the shared
#: static vocabulary (like YEAR_STEP_STATIC_ARGNAMES /
#: serve.engine.QUERY_STATIC_ARGNAMES): the program auditor
#: (dgen_tpu.lint.prog) lowers these kernels over the same set, so the
#: audited bill-kernel programs are the ones production compiles
SUMS_STATIC_ARGNAMES = (
    "n_buckets", "impl", "bf16", "mesh", "layout", "soft_tau",
)


def _check_soft(soft_tau, resolved, layout, packed) -> None:
    """The smooth twin prices on the plain f32 full-hour XLA path only:
    the Pallas engines have no VJP and the compacted/packed layouts'
    night-sum split assumes the HARD relu's exact zeros."""
    if soft_tau is None:
        return
    if resolved != "xla":
        raise ValueError(
            f"soft_tau requires impl='xla' (got '{resolved}'); the "
            "smooth twin has no Pallas lowering"
        )
    if layout is not None or packed is not None:
        raise ValueError(
            "soft_tau is incompatible with daylight layouts / packed "
            "streams (their night-sum split assumes hard-relu zeros)"
        )


@partial(jax.jit, static_argnames=SUMS_STATIC_ARGNAMES)
def import_sums(
    load: jax.Array,      # [N, 8760] (None when ``packed`` carries it)
    gen: jax.Array,       # [N, 8760]
    sell: jax.Array,      # [N, 8760]
    bucket_id: jax.Array,  # [N, 8760] int32 in [0, n_buckets)
    scales: jax.Array,    # [N, R]
    n_buckets: int,
    impl: str = "auto",
    bf16: bool = False,
    mesh=None,
    layout: Optional[DaylightLayout] = None,
    packed: Optional[PackedStreams] = None,
    load_scale: Optional[jax.Array] = None,   # [N] int8 dequant scales
    gen_scale: Optional[jax.Array] = None,
    soft_tau: Optional[float] = None,
) -> tuple[jax.Array, jax.Array]:
    """(imports [N,R,B], imp_sell [N,R]): positive-part bucket sums and
    the sell-weighted positive-part sum for R net-load scales.

    ``layout``: optional :class:`DaylightLayout` (STATIC — hashable
    host constant) — the candidate kernel then touches only the
    compacted daylight lanes and the night hours' candidate-independent
    sums are added back; totals cover ALL hours either way. Valid only
    when ``gen`` is zero off-daylight (true for any bank-derived
    generation the layout was built from); the legacy ``pallas_dot``
    engine ignores it (full-hour A/B reference).

    ``packed``: optional :class:`PackedStreams` built against the same
    ``layout`` — the engine then skips the per-call repack gather
    (pass the raw stream arguments as None so jit sees one copy).
    ``load_scale``/``gen_scale``: per-agent f32 dequant factors for
    int8 quantized banks (:func:`_quant_fold`); the kernels run in
    quantized units (f32 upcast + accumulate) and outputs rescale
    once. ``impl="pallas_stream"`` selects the double-buffered
    (agent-block x month-segment) engine on TPU (XLA twin elsewhere).
    ``soft_tau`` (static): the differentiable twin's soft relu split —
    XLA engine only, no layout/packed (see :func:`_check_soft`)."""
    _check_buckets(n_buckets)
    resolved = _resolve_impl(impl)
    _check_soft(soft_tau, resolved, layout, packed)
    scales_eff, post = _quant_fold(scales, load_scale, gen_scale)
    if resolved == "pallas":
        fn = partial(_sums_pallas, with_signed=False,
                     n_periods=n_buckets // MONTHS, bf16=bf16,
                     layout=layout)
    elif resolved == "pallas_stream":
        fn = partial(_sums_pallas_stream, with_signed=False,
                     n_periods=n_buckets // MONTHS, bf16=bf16,
                     layout=layout)
    elif resolved == "pallas_dot":
        # full-hour engine; results are identical totals either way
        _reject_packed_for_dot(packed)
        fn = partial(_sums_pallas_dot, with_signed=False, bf16=bf16)
    else:
        fn = partial(_sums_xla, n_buckets=n_buckets, with_signed=False,
                     layout=layout, soft_tau=soft_tau)
    args = (load, gen, sell, bucket_id, scales_eff)
    if packed is not None:
        args = args + (packed,)
    (imp,) = _maybe_shard_agents(fn, mesh, 1, n_in=len(args))(*args)
    (imp,) = _quant_unfold((imp,), post)
    return imp[:, :, :n_buckets], imp[:, :, SELL_COL]


@partial(jax.jit, static_argnames=tuple(
    n for n in SUMS_STATIC_ARGNAMES if n != "bf16"
))
def import_sums_pair(
    load: jax.Array,       # [N, 8760]
    gen: jax.Array,        # [N, 8760]
    sell_a: jax.Array,     # [N, 8760] switched-tariff sell rate
    bucket_a: jax.Array,   # [N, 8760] switched-tariff bucket ids
    sell_b: jax.Array,     # [N, 8760] original-tariff sell rate
    bucket_b: jax.Array,   # [N, 8760] original-tariff bucket ids
    scales: jax.Array,     # [N, R]
    n_buckets: int,
    impl: str = "auto",
    mesh=None,
    layout: Optional[DaylightLayout] = None,
    packed: Optional[PackedStreams] = None,
    load_scale: Optional[jax.Array] = None,
    gen_scale: Optional[jax.Array] = None,
    soft_tau: Optional[float] = None,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """(imports_a [N,R,B], imp_sell_a [N,R], imports_b, imp_sell_b):
    the rate-switch search's two tariff structures priced over ONE
    shared ``relu(load - s*gen)`` grid (reference apply_rate_switch,
    agent_mutation/elec.py:838-845) — ~40% faster than two
    :func:`import_sums` calls on TPU because the net build dominates.
    ``layout``/``packed``/``load_scale`` as in :func:`import_sums`
    (night sums are added per tariff structure; a pack built with
    ``sell_b``/``bucket_b`` carries both). The stream engine has no
    fused-pair form — ``impl="pallas_stream"`` keeps the pair on the
    month kernel (still one shared net grid)."""
    _check_buckets(n_buckets)
    resolved = _resolve_impl(impl)
    _check_soft(soft_tau, resolved, layout, packed)
    scales_eff, post = _quant_fold(scales, load_scale, gen_scale)
    if resolved in ("pallas", "pallas_stream"):
        fn = partial(_sums_pallas_pair, n_periods=n_buckets // MONTHS,
                     layout=layout)
        args = (load, gen, sell_a, bucket_a, sell_b, bucket_b,
                scales_eff)
        if packed is not None:
            args = args + (packed,)
        imp_a, imp_b = _maybe_shard_agents(fn, mesh, 2, n_in=len(args))(
            *args
        )
    else:
        # XLA twin / dot engine: two independent single-tariff passes
        # (the fusion is a TPU-kernel optimization, not a semantic one)
        if resolved == "pallas_dot":
            _reject_packed_for_dot(packed)
            fa = partial(_sums_pallas_dot, with_signed=False)
        else:
            fa = partial(_sums_xla, n_buckets=n_buckets,
                         with_signed=False, layout=layout,
                         soft_tau=soft_tau)
        args_a = (load, gen, sell_a, bucket_a, scales_eff)
        args_b = (load, gen, sell_b, bucket_b, scales_eff)
        if packed is not None:
            args_a = args_a + (packed,)
            args_b = args_b + (packed.tariff_b(),)
        (imp_a,) = _maybe_shard_agents(fa, mesh, 1, n_in=len(args_a))(
            *args_a)
        (imp_b,) = _maybe_shard_agents(fa, mesh, 1, n_in=len(args_b))(
            *args_b)
    imp_a, imp_b = _quant_unfold((imp_a, imp_b), post)
    return (imp_a[:, :, :n_buckets], imp_a[:, :, SELL_COL],
            imp_b[:, :, :n_buckets], imp_b[:, :, SELL_COL])


@partial(jax.jit, static_argnames=tuple(
    n for n in SUMS_STATIC_ARGNAMES if n not in ("bf16", "layout")
))
def bucket_sums(
    load: jax.Array,
    gen: jax.Array,
    sell: jax.Array,
    bucket_id: jax.Array,
    scales: jax.Array,
    n_buckets: int,
    impl: str = "auto",
    mesh=None,
    packed: Optional[PackedStreams] = None,
    soft_tau: Optional[float] = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """(signed [N,R,B], imports [N,R,B], export_credit [N,R]) — the full
    reduction set (battery forward runs, tests).

    ``packed``: an optional FULL-HOUR :class:`PackedStreams` whose
    load/sell/period lanes are reused while ``gen`` (the battery-
    modified output, not a scale of the gen bank) is repacked fresh —
    the battery forward run then gathers one stream instead of four.
    Compacted packs are rejected (a discharging battery breaks the
    night-zero premise), and quantized packs never reach here (the
    battery path prices dequantized f32 streams)."""
    _check_buckets(n_buckets)
    resolved = _resolve_impl(impl)
    _check_soft(soft_tau, resolved, None, packed)
    if resolved == "pallas":
        fn = partial(_sums_pallas, with_signed=True,
                     n_periods=n_buckets // MONTHS)
    elif resolved == "pallas_stream":
        fn = partial(_sums_pallas_stream, with_signed=True,
                     n_periods=n_buckets // MONTHS)
    elif resolved == "pallas_dot":
        _reject_packed_for_dot(packed)
        fn = partial(_sums_pallas_dot, with_signed=True)
    else:
        fn = partial(_sums_xla, n_buckets=n_buckets, with_signed=True,
                     soft_tau=soft_tau)
    args = (load, gen, sell, bucket_id, scales)
    if packed is not None:
        args = args + (packed,)
    imp, signed = _maybe_shard_agents(fn, mesh, 2, n_in=len(args))(
        *args
    )
    # exports = relu(-net) reductions = imports - signed (columnwise)
    credit = imp[:, :, SELL_COL] - signed[:, :, SELL_COL]
    return signed[:, :, :n_buckets], imp[:, :, :n_buckets], credit


@partial(jax.jit, static_argnames=("n_periods",))
def linear_sums(
    load: jax.Array,         # [N, 8760]
    gen: jax.Array,          # [N, 8760]
    sell: jax.Array,         # [N, 8760]
    hour_period: jax.Array,  # [N, 8760] int32 TOU period per hour
    n_periods: int,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Per-agent linear bill structure, computed once per year step:
    (S_load [N,B], S_gen [N,B], S_load_sell [N], S_gen_sell [N]).

    ``signed(s) = S_load - s * S_gen`` gives exact NEM monthly sums for
    any scale; the ``*_sell`` scalars close the export-credit identity.

    Pure XLA: per TOU period, one [N, 8760] x [8760, 12] matmul against
    the SHARED month one-hot — full MXU row tiles over the agent axis,
    no per-agent kernel program needed.

    Inputs are upcast to f32 first: this runs ONCE per year step, and
    under bf16 profile banks an 8760-term bf16 accumulation would lose
    the linear identity's precision for no meaningful HBM saving.
    """
    from dgen_tpu.ops.bill import monthly_period_sums

    load = load.astype(jnp.float32)
    gen = gen.astype(jnp.float32)
    sell = sell.astype(jnp.float32)
    n = load.shape[0]

    def bucketize(x):  # vmapped shared-month-one-hot bucketing
        mp = jax.vmap(
            lambda row, hp: monthly_period_sums(row, hp, n_periods)
        )(x, hour_period)                                    # [N, 12, P]
        return mp.reshape(n, MONTHS * n_periods)

    s_l = bucketize(load)
    s_g = bucketize(gen)
    s_l_sell = jnp.sum(load * sell, axis=1)
    s_g_sell = jnp.sum(gen * sell, axis=1)
    return s_l, s_g, s_l_sell, s_g_sell


def hourly_bucket_ids(hour_period: jax.Array, n_periods: int) -> jax.Array:
    """[N, 8760] month-major bucket ids from per-agent TOU period maps."""
    month = jnp.asarray(_HOUR_MONTH, jnp.int32)[None, :]
    return month * n_periods + hour_period


def sell_rate_hourly(tariff, ts_sell: jax.Array) -> jax.Array:
    """Hourly sell rate per agent, matching bill.annual_bill's choice:
    the tariff's TOU sell price when defined, else the time-series rate
    (shared static period select — see ``bill.select_by_period`` for
    why this must not be a gather)."""
    from dgen_tpu.ops.bill import select_by_period

    tou = select_by_period(tariff.hour_period, tariff.sell_price, ts_sell)
    has_tou = jnp.any(tariff.sell_price > 0.0, axis=1, keepdims=True)
    # keep the bank dtype: under bf16 profile banks the sell stream
    # rides VMEM at 2 bytes/lane like load/gen (no-op for f32)
    return jnp.where(has_tou, tou, ts_sell).astype(ts_sell.dtype)


def _tier_charge_batched(sums_mp, tariff, soft_tau=None):
    """[N, R, 12, P] monthly sums -> [N, R] annual tiered charges.

    Same semantics as ``bill.tiered_charge`` but written as a static
    loop over the (small) tier axis so the largest intermediate stays
    [N, R, 12, P] — the vmap-of-vmap formulation materializes an extra
    T axis ([N, R, 12, P, T]), several GB at 16k+ agents x 250 scales,
    and HBM pressure there is what capped population scaling.

    ``soft_tau`` (kWh): smooth the tier-edge clips for the
    differentiable twin (dgen_tpu.grad); ``None`` = exact hard clip.
    """
    if soft_tau is None:
        def seg_fn(x, w):
            return jnp.clip(x, 0.0, w)

        def neg_fn(x):
            return jnp.minimum(x, 0.0)
    else:
        from dgen_tpu.grad.smooth import clip0_t, min0_t

        def seg_fn(x, w):
            return clip0_t(x, w, soft_tau)

        def neg_fn(x):
            return min0_t(x, soft_tau)
    price = tariff.price          # [N, P, T]
    caps = tariff.tier_cap        # [N, T]
    n_tiers = price.shape[-1]
    lower = jnp.concatenate(
        [jnp.zeros_like(caps[:, :1]), caps[:, :-1]], axis=1
    )                             # [N, T]
    width = caps - lower
    total = jnp.zeros(sums_mp.shape[:2], dtype=sums_mp.dtype)   # [N, R]
    for t in range(n_tiers):
        lo = lower[:, t][:, None, None, None]
        seg = seg_fn(sums_mp - lo, width[:, t][:, None, None, None])
        total = total + jnp.einsum("nrmp,np->nr", seg, price[:, :, t])
    # negative (net-metered export) months credit at tier-1 price
    total = total + jnp.einsum(
        "nrmp,np->nr", neg_fn(sums_mp), price[:, :, 0]
    )
    return total


def bills_from_sums(
    signed: jax.Array,    # [N, R, B]
    imports: jax.Array,   # [N, R, B]
    credit: jax.Array,    # [N, R]
    tariff,               # batched AgentTariff (leaves [N, ...])
    n_periods: int,
    soft_tau: float | None = None,
) -> jax.Array:
    """Annual bills [N, R] from full bucket sums (tier structure +
    metering selection + fixed charges; bill.annual_bill semantics)."""
    n, r, _ = signed.shape
    bill_nem = _tier_charge_batched(
        signed.reshape(n, r, MONTHS, n_periods), tariff, soft_tau)
    bill_nb = _tier_charge_batched(
        imports.reshape(n, r, MONTHS, n_periods), tariff, soft_tau) - credit

    is_nb = (tariff.metering == NET_BILLING)[:, None]
    energy_bill = jnp.where(is_nb, bill_nb, bill_nem)
    return energy_bill + MONTHS * tariff.fixed_monthly[:, None]


def _nem_energy_bill(lin, scales, tariff, n_periods, soft_tau=None):
    """[N, R] annual NEM energy bills via the linear identity
    ``signed(s) = S_load - s * S_gen`` (no fixed charges) — the single
    definition shared by the all-NEM fast path and the mixed-metering
    path, so their NEM bills cannot drift apart."""
    s_load, s_gen = lin[0], lin[1]
    n, r = scales.shape
    signed = s_load[:, None, :] - scales[:, :, None] * s_gen[:, None, :]
    return _tier_charge_batched(
        signed.reshape(n, r, MONTHS, n_periods), tariff, soft_tau)


def bills_linear_nem(
    lin: tuple[jax.Array, jax.Array, jax.Array, jax.Array],
    scales: jax.Array,    # [N, R]
    tariff,
    n_periods: int,
    soft_tau: float | None = None,
) -> jax.Array:
    """Annual bills [N, R] for an all-NET-METERING population: the
    pure linear identity — NO hourly kernel work at all. Callers must
    guarantee no agent prices on a net-billing tariff (the driver
    derives that statically from the tariffs the population actually
    references plus the NEM gate's never-closes proof)."""
    bill = _nem_energy_bill(lin, scales, tariff, n_periods, soft_tau)
    return bill + MONTHS * tariff.fixed_monthly[:, None]


def bills_linear_nb(
    lin: tuple[jax.Array, jax.Array, jax.Array, jax.Array],
    imports: jax.Array,   # [N, R, B]
    imp_sell: jax.Array,  # [N, R]
    scales: jax.Array,    # [N, R]
    tariff,
    n_periods: int,
    soft_tau: float | None = None,
) -> jax.Array:
    """Annual bills [N, R] from the search path's reduced outputs:
    NEM via the linear identity, net billing via import sums + the
    linear export-credit identity."""
    s_l_sell, s_g_sell = lin[2], lin[3]
    n, r, _ = imports.shape

    bill_nem = _nem_energy_bill(lin, scales, tariff, n_periods, soft_tau)

    credit = imp_sell - (s_l_sell[:, None] - scales * s_g_sell[:, None])
    bill_nb = _tier_charge_batched(
        imports.reshape(n, r, MONTHS, n_periods), tariff, soft_tau) - credit

    is_nb = (tariff.metering == NET_BILLING)[:, None]
    energy_bill = jnp.where(is_nb, bill_nb, bill_nem)
    return energy_bill + MONTHS * tariff.fixed_monthly[:, None]
