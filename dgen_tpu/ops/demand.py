"""Demand charges: TOU-window and flat monthly peak-demand billing.

The reference SKIPS demand charges globally in its hot loop
(``SKIP_DEMAND_CHARGES=True``, financial_functions.py:35,601) — so
nothing in the adoption pipeline depends on this module — but its
in-repo oracle implements them (tariff_functions.py:762-799: TOU-period
and flat monthly maxima priced through ``tiered_calc_vec``), and real
C&I tariffs carry them. This module provides the TPU-native equivalent
for analysis runs and forward compatibility, validated against that
oracle in tests/test_demand.py.

Semantics (oracle parity):
  * flat: the charge for each month is the tiered price of that month's
    peak net load (kW).
  * TOU: within each month, the peak over each demand-TOU window is
    priced through that window's tier structure and summed.
  * Tier pricing follows the oracle's bracket formula
    (tariff_functions.py:679 ``tiered_calc_vec``): the bracket
    containing the max pays ``(v - L[t-1]) * p[t] + L[t-1] * p[t-1]``
    — identical to cumulative accumulation for <= 2 tiers, which is
    what the corpus uses.

TPU notes: monthly/window maxima are masked max-reductions over the
static hour->month map — elementwise VPU work, not MXU; demand tariffs
are tiny [P_d, T_d] structures so the tier step is negligible.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from dgen_tpu.ops.tariff import BIG_CAP, HOURS, MONTHS, hour_month_map

# numpy on purpose: a module-level jnp constant initializes the XLA
# backend at import, breaking jax.distributed.initialize downstream
_HOUR_MONTH = np.asarray(hour_month_map())
NEG = -1e30


class DemandTariff(NamedTuple):
    """Dense demand-charge structure for one agent (vmap for many)."""

    flat_price: jax.Array    # [12, T] $/kW for the monthly peak (seasonal)
    flat_cap: jax.Array      # [12, T] kW tier caps (BIG_CAP = unbounded)
    tou_price: jax.Array     # [P, T] $/kW per demand-TOU window
    tou_cap: jax.Array       # [P, T] kW tier caps
    hour_window: jax.Array   # [8760] int32 demand-TOU window per hour

    @staticmethod
    def zeros(n_windows: int = 1, n_tiers: int = 1) -> "DemandTariff":
        return DemandTariff(
            flat_price=jnp.zeros((MONTHS, n_tiers), jnp.float32),
            flat_cap=jnp.full((MONTHS, n_tiers), BIG_CAP, jnp.float32),
            tou_price=jnp.zeros((n_windows, n_tiers), jnp.float32),
            tou_cap=jnp.full((n_windows, n_tiers), BIG_CAP, jnp.float32),
            hour_window=jnp.zeros(HOURS, jnp.int32),
        )


def _bracket_charge(v: jax.Array, caps: jax.Array, price: jax.Array) -> jax.Array:
    """Oracle tier formula (tariff_functions.py:679) for a scalar-per-
    month demand value ``v`` [...]: price of the bracket containing v.

    ``caps``/``price`` [..., T] broadcast against v[..., None].
    """
    t_count = price.shape[-1]
    lower = jnp.concatenate(
        [jnp.zeros_like(caps[..., :1]), caps[..., :-1]], axis=-1
    )
    vx = v[..., None]
    in_bracket = (vx >= lower) & (vx < caps)
    # bracket t pays (v - L[t-1]) * p[t] + L[t-1] * p[t-1]
    prev_price = jnp.concatenate(
        [price[..., :1], price[..., :-1]], axis=-1
    )
    per_tier = (vx - lower) * price + lower * jnp.where(
        jnp.arange(t_count) == 0, 0.0, prev_price
    )
    return jnp.sum(jnp.where(in_bracket, per_tier, 0.0), axis=-1)


def monthly_peaks(net_load: jax.Array, window: jax.Array,
                  n_windows: int) -> tuple[jax.Array, jax.Array]:
    """(flat [12], tou [12, P]) monthly peak net load (kW).

    Masked max over the static hour->month map; negative demand (net
    export hours) floors at 0, matching the oracle's load-distributed
    max over a boolean matrix of non-negative products."""
    x = jnp.maximum(net_load, 0.0)
    month = _HOUR_MONTH
    m_onehot = (month[:, None] == jnp.arange(MONTHS)[None, :])   # [H, 12]
    flat = jnp.max(jnp.where(m_onehot, x[:, None], NEG), axis=0)
    w_onehot = (window[:, None] == jnp.arange(n_windows)[None, :])  # [H, P]
    both = m_onehot[:, :, None] & w_onehot[:, None, :]           # [H, 12, P]
    tou = jnp.max(jnp.where(both, x[:, None, None], NEG), axis=0)
    return jnp.maximum(flat, 0.0), jnp.maximum(tou, 0.0)


@jax.jit
def annual_demand_charge(
    net_load: jax.Array,
    tariff: DemandTariff,
) -> jax.Array:
    """Annual $ of flat + TOU demand charges for one agent's [8760]
    net load (vmap over agents). The window count comes from the
    tariff's own [P, T] TOU shape (static under jit), so the map and
    the price table cannot disagree."""
    n_windows = tariff.tou_price.shape[0]
    flat, tou = monthly_peaks(net_load, tariff.hour_window, n_windows)
    flat_charge = _bracket_charge(flat, tariff.flat_cap, tariff.flat_price)
    tou_charge = _bracket_charge(
        tou, tariff.tou_cap[None, :, :], tariff.tou_price[None, :, :]
    )
    return jnp.sum(flat_charge) + jnp.sum(tou_charge)


def compile_demand_tariff(
    d_flat_prices=None,
    d_flat_levels=None,
    d_tou_prices=None,
    d_tou_levels=None,
    d_tou_8760=None,
) -> DemandTariff:
    """Host-side compiler from oracle-shaped inputs (tariff_functions
    attribute conventions: ``d_flat_*`` are [T][12] tier x month,
    ``d_tou_*`` are [T][P] tier x window, ``d_tou_8760`` the window
    map)."""
    def as_pt(prices, levels, p_fallback):
        if prices is None:
            return (np.zeros((p_fallback, 1), np.float32),
                    np.full((p_fallback, 1), BIG_CAP, np.float32))
        p = np.asarray(prices, np.float32).T        # [P, T]
        if levels is None:
            c = np.full(p.shape, BIG_CAP, np.float32)
        else:
            c = np.asarray(levels, np.float32).T.copy()
            c[c <= 0] = BIG_CAP
        return p, np.minimum(c, BIG_CAP)

    tou_p, tou_c = as_pt(d_tou_prices, d_tou_levels, 1)
    flat_p, flat_c = as_pt(d_flat_prices, d_flat_levels, MONTHS)
    if flat_p.shape[0] == 1:  # single season -> every month
        flat_p = np.broadcast_to(flat_p, (MONTHS, flat_p.shape[1])).copy()
        flat_c = np.broadcast_to(flat_c, (MONTHS, flat_c.shape[1])).copy()
    if flat_p.shape[0] != MONTHS:
        raise ValueError(
            f"d_flat prices cover {flat_p.shape[0]} months, expected 12"
        )
    hw = (np.zeros(HOURS, np.int32) if d_tou_8760 is None
          else np.asarray(d_tou_8760, np.int32))
    if hw.min(initial=0) < 0 or hw.max(initial=0) >= tou_p.shape[0]:
        raise ValueError(
            f"d_tou_8760 window ids span [{int(hw.min())}, "
            f"{int(hw.max())}] but the price table covers "
            f"[0, {tou_p.shape[0]}) windows"
        )
    return DemandTariff(
        flat_price=jnp.asarray(flat_p),
        flat_cap=jnp.asarray(flat_c),
        tou_price=jnp.asarray(tou_p),
        tou_cap=jnp.asarray(tou_c),
        hour_window=jnp.asarray(hw),
    )


def compile_demand_bank(demand_specs) -> "DemandTariff | None":
    """Per-tariff demand specs -> one batched bank (leaves [K, ...]).

    ``demand_specs``: one entry per tariff-bank row — the ``"demand"``
    sub-spec the converter attaches (io.convert.reference_tariff_to_
    demand_spec), or None for tariffs without demand charges. Returns
    None when no tariff carries any (the corpus norm: the reference
    skips them globally, financial_functions.py:35).

    Tariffs are padded to the bank's max window/tier extents by EDGE
    REPLICATION (the compile_tariffs convention): a pad tier repeats the
    real top tier's cap, so its bracket [cap, cap) is empty and it can
    never price — including when the real top cap is finite (padding
    with an unbounded cap there would open a new bracket above it and
    charge ``lower * prev_price``, diverging from
    :func:`compile_demand_tariff` for the same tariff). A pad window's
    masked peak is 0, which its tier 1 prices at 0 * price. Per-agent
    tariffs come from ``jax.tree.map(lambda x: x[tariff_idx], bank)``;
    price hourly nets with ``jax.vmap(annual_demand_charge)``.
    """
    if not demand_specs or not any(demand_specs):
        return None
    from dgen_tpu.ops.tariff import expand_schedule_8760

    ts = []
    for spec in demand_specs:
        if not spec:
            ts.append(DemandTariff.zeros())
            continue
        kwargs = {
            k: spec[k]
            for k in ("d_flat_prices", "d_flat_levels",
                      "d_tou_prices", "d_tou_levels")
            if k in spec
        }
        if "d_wkday_12by24" in spec:
            kwargs["d_tou_8760"] = expand_schedule_8760(
                spec["d_wkday_12by24"],
                spec.get("d_wkend_12by24", spec["d_wkday_12by24"]),
            )
        ts.append(compile_demand_tariff(**kwargs))

    P = max(t.tou_price.shape[0] for t in ts)
    T = max(max(t.tou_price.shape[1], t.flat_price.shape[1]) for t in ts)

    def pad2(a, r, c):
        a = np.asarray(a, np.float32)
        return np.pad(a, ((0, r - a.shape[0]), (0, c - a.shape[1])),
                      mode="edge")

    return DemandTariff(
        flat_price=jnp.asarray(
            np.stack([pad2(t.flat_price, MONTHS, T) for t in ts])),
        flat_cap=jnp.asarray(
            np.stack([pad2(t.flat_cap, MONTHS, T) for t in ts])),
        tou_price=jnp.asarray(
            np.stack([pad2(t.tou_price, P, T) for t in ts])),
        tou_cap=jnp.asarray(
            np.stack([pad2(t.tou_cap, P, T) for t in ts])),
        hour_window=jnp.asarray(
            np.stack([np.asarray(t.hour_window) for t in ts])),
    )
