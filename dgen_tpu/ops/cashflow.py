"""Multi-year levered cashflow -> NPV / payback: the TPU replacement for
PySAM ``Cashloan`` (reference financial_functions.py:287 ``loan.execute()``).

Scope is the subset dGen exercises (SURVEY.md §2.7): host-owned systems,
loan-or-cash purchase, federal ITC, fed+state income tax with MACRS-5
depreciation for non-residential agents (reference
financial_functions.py:416-421), state CBI/PBI/IBI incentives (reference
financial_functions.py:1014 ``process_incentives``), and the
bill-savings "energy value" stream produced by the bill engine. O&M is
carried as an explicit parameter but the reference zeroes it in the hot
loop (financial_functions.py:124-127,202).

All functions are scalar-agent kernels meant to be ``jax.vmap``-ed over
the agent axis; year axes are static-shaped.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from dgen_tpu.config import PAYBACK_NEVER

# MACRS 5-year half-year-convention schedule (what SAM's depr type 2
# applies for commercial systems). numpy on purpose: a module-level jnp
# constant initializes the XLA backend at import, which breaks
# jax.distributed.initialize in launch.main().
MACRS_5 = np.array([0.20, 0.32, 0.192, 0.1152, 0.1152, 0.0576], dtype=np.float32)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class FinanceParams:
    """Per-agent financing terms (reference financial_functions.py:385-394).

    All leaves are scalars for a single agent; vmap for the table.
    ``tax_rate`` is split 70/30 federal/state exactly as the reference
    does (financial_functions.py:387,393).
    """

    down_payment_fraction: jax.Array
    loan_interest_rate: jax.Array
    loan_term_yrs: jax.Array        # int32
    real_discount_rate: jax.Array
    inflation_rate: jax.Array
    tax_rate: jax.Array
    itc_fraction: jax.Array
    #: 1.0 for non-res agents -> depreciation + deductible
    #: interest (business expense); 0.0 for res.
    is_commercial: jax.Array
    #: annual O&M $ (year-1 dollars, inflates)
    om_per_year: jax.Array
    #: [D] depreciation schedule fractions (the reference's data-driven
    #: ``deprec_sch`` column, agent_mutation/elec.py:157
    #: ``apply_depreciation_schedule``); None = the MACRS-5 default
    deprec_sch: jax.Array = None

    @staticmethod
    def example() -> "FinanceParams":
        f32 = jnp.float32
        return FinanceParams(
            down_payment_fraction=f32(1.0),
            loan_interest_rate=f32(0.05),
            loan_term_yrs=jnp.int32(20),
            real_discount_rate=f32(0.027),
            inflation_rate=f32(0.025),
            tax_rate=f32(0.257),
            itc_fraction=f32(0.30),
            is_commercial=f32(0.0),
            om_per_year=f32(0.0),
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class IncentiveParams:
    """Compiled state incentives for one agent.

    The reference nests a per-(state, sector) DataFrame of incentive rows
    into each agent cell (agent_mutation/elec.py:685-694) and re-sorts it
    per sizing call (financial_functions.py:1014). Here incentives are
    compiled at ingest to fixed-width scalars: the top-2 CBI/IBI/PBI rows
    by value, exactly the number ``process_incentives`` consumes.
    """

    cbi_usd_p_w: jax.Array      # [2] $/W capacity-based
    cbi_max_usd: jax.Array      # [2]
    ibi_frac: jax.Array         # [2] fraction of installed cost
    ibi_max_usd: jax.Array      # [2]
    pbi_usd_p_kwh: jax.Array    # [2] $/kWh production-based
    pbi_years: jax.Array        # [2] int32 duration
    #: [2] 1.0 = the $/kWh rate decays linearly to zero over the
    #: duration (reference eqn_builder 'linear_decay',
    #: financial_functions.py:1379-1385); 0.0 = flat rate, the only
    #: mode the reference's own hot path uses
    #: (process_incentives :1072 repeats a flat amount)
    pbi_decay: jax.Array = None

    @staticmethod
    def zeros() -> "IncentiveParams":
        z2 = jnp.zeros(2, dtype=jnp.float32)
        return IncentiveParams(
            cbi_usd_p_w=z2, cbi_max_usd=z2, ibi_frac=z2, ibi_max_usd=z2,
            pbi_usd_p_kwh=z2, pbi_years=jnp.zeros(2, dtype=jnp.int32),
            pbi_decay=z2,
        )


def nominal_discount_rate(real: jax.Array, inflation: jax.Array) -> jax.Array:
    return (1.0 + real) * (1.0 + inflation) - 1.0


def loan_schedule(principal: jax.Array, rate: jax.Array, term: jax.Array,
                  n_years: int) -> tuple[jax.Array, jax.Array]:
    """(payment [Y], interest [Y]) of a level-payment amortizing loan.

    Payments run for ``term`` years then stop; ``n_years`` is the static
    analysis horizon. Closed form (no scan): the start-of-year balance
    of a level-payment loan is
    ``B_t = P*(1+r)^t - pmt*((1+r)^t - 1)/r``, so every year's interest
    is one vectorized expression — keeps the cashflow kernel free of
    sequential steps under large-batch vmap.
    """
    term_f = term.astype(jnp.float32)
    r = rate
    y = jnp.arange(n_years, dtype=jnp.float32)
    active = (y < term_f).astype(jnp.float32)

    # level payment; guard rate ~ 0
    small_r = r <= 1e-9
    r_safe = jnp.where(small_r, 1.0, r)
    annuity = jnp.where(
        small_r,
        1.0 / jnp.maximum(term_f, 1.0),
        r_safe / (1.0 - (1.0 + r_safe) ** (-term_f)),
    )
    pmt = principal * annuity

    growth = (1.0 + r) ** y                                   # [Y]
    balance_start = jnp.where(
        small_r,
        principal - pmt * y,
        principal * growth - pmt * (growth - 1.0) / r_safe,
    )
    interests = balance_start * r * active
    payments = pmt * active
    return payments, interests


def incentive_cashflows(
    inc: IncentiveParams,
    system_kw: jax.Array,
    installed_cost: jax.Array,
    annual_kwh: jax.Array,
    degradation: jax.Array,
    n_years: int,
) -> tuple[jax.Array, jax.Array]:
    """(upfront $, pbi stream [Y]) from compiled state incentives.

    CBI: $/W x kW x 1000, clamped to its max (reference
    financial_functions.py:1317 ``check_incentive_constraints``).
    IBI: fraction x installed cost, clamped. PBI: $/kWh x degraded
    production for the row's duration — flat, or decaying linearly to
    zero at the end of the duration when the row's ``pbi_decay`` is set
    (reference eqn_builder 'linear_decay', financial_functions.py:1379:
    ``value(ts) = rate * (1 - ts/expiration)`` for ts = 1..expiration).
    """
    cbi = jnp.sum(jnp.minimum(inc.cbi_usd_p_w * system_kw * 1000.0, inc.cbi_max_usd))
    ibi = jnp.sum(jnp.minimum(inc.ibi_frac * installed_cost, inc.ibi_max_usd))

    y = jnp.arange(n_years, dtype=jnp.float32)
    prod = annual_kwh * (1.0 - degradation) ** y                       # [Y]
    dur = inc.pbi_years[:, None].astype(jnp.float32)                   # [2, 1]
    active = (y[None, :] < dur).astype(jnp.float32)                    # [2, Y]
    rate = inc.pbi_usd_p_kwh[:, None]
    if inc.pbi_decay is not None:
        ts = y[None, :] + 1.0
        decay_f = jnp.clip(1.0 - ts / jnp.maximum(dur, 1.0), 0.0, 1.0)
        rate = rate * jnp.where(inc.pbi_decay[:, None] > 0, decay_f, 1.0)
    pbi = jnp.sum(rate * prod[None, :] * active, axis=0)
    return cbi + ibi, pbi


@partial(jax.jit, static_argnames=("n_years",))
def cashflow(
    energy_value: jax.Array,
    installed_cost: jax.Array,
    fin: FinanceParams,
    n_years: int,
    system_kw: jax.Array = None,
    annual_kwh: jax.Array = None,
    degradation: jax.Array = None,
    inc: IncentiveParams = None,
) -> dict:
    """After-tax levered cashflow for one agent.

    Inputs: ``energy_value`` [Y] nominal bill savings (bill engine),
    ``installed_cost`` total upfront $ (already including the
    cap-cost multiplier and any one-time interconnection charge,
    reference financial_functions.py:280-282).

    Returns dict with ``cf`` [Y+1] (year 0 = -equity), ``npv`` (nominal
    discounting, matching Cashloan's ``Outputs.npv``), and the
    tax/loan components for inspection.
    """
    f32 = jnp.float32
    zero = jnp.zeros((), dtype=f32)
    system_kw = zero if system_kw is None else system_kw
    annual_kwh = zero if annual_kwh is None else annual_kwh
    degradation = zero if degradation is None else degradation
    inc = IncentiveParams.zeros() if inc is None else inc

    down = installed_cost * fin.down_payment_fraction
    principal = installed_cost - down
    payments, interests = loan_schedule(
        principal, fin.loan_interest_rate, fin.loan_term_yrs, n_years
    )

    fed_rate = fin.tax_rate * 0.7
    sta_rate = fin.tax_rate * 0.3
    # combined marginal rate with state tax deductible from federal
    tax_eff = fed_rate + sta_rate - fed_rate * sta_rate

    # Federal ITC, credited in year 1 (reference financial_functions.py:285).
    itc = fin.itc_fraction * installed_cost
    year1 = (jnp.arange(n_years) == 0).astype(f32)

    # Depreciation for commercial, basis reduced by half the ITC
    # (SAM convention for depr type 2); schedule is the data-driven
    # deprec_sch when supplied (reference apply_depreciation_schedule,
    # elec.py:157), MACRS-5 otherwise.
    sch = MACRS_5 if fin.deprec_sch is None else fin.deprec_sch
    basis = installed_cost * (1.0 - 0.5 * fin.itc_fraction)
    depr = jnp.zeros(n_years, dtype=f32).at[: sch.shape[-1]].set(
        sch[: min(sch.shape[-1], n_years)] * basis
    )
    depr_savings = depr * tax_eff * fin.is_commercial
    interest_savings = interests * tax_eff * fin.is_commercial

    upfront_inc, pbi = incentive_cashflows(
        inc, system_kw, installed_cost, annual_kwh, degradation, n_years
    )

    y = jnp.arange(n_years, dtype=f32)
    om = fin.om_per_year * (1.0 + fin.inflation_rate) ** y

    cf_years = (
        energy_value
        - payments
        - om
        + interest_savings
        + depr_savings
        + itc * year1
        + upfront_inc * year1
        + pbi
    )
    cf0 = -down
    cf = jnp.concatenate([cf0[None], cf_years])

    dnom = nominal_discount_rate(fin.real_discount_rate, fin.inflation_rate)
    disc = (1.0 + dnom) ** (-jnp.arange(n_years + 1, dtype=f32))
    npv = jnp.sum(cf * disc)

    return {
        "cf": cf,
        "npv": npv,
        "payments": payments,
        "interest": interests,
        "itc": itc,
        "depreciation": depr * fin.is_commercial,
    }


def payback_period(cf: jax.Array, soft: bool = False) -> jax.Array:
    """Fractional payback year from a [Y+1] cashflow (year 0 = equity).

    Semantics match the reference's vectorized implementation
    (financial_functions.py:1241 ``calc_payback_vectorized``): the LAST
    negative-to-positive crossing of the cumulative cashflow (its
    ``np.amax`` over ``neg_to_pos_years``, :1252 — the docstring there
    says "first" but the code takes the last, and the implementation is
    the parity target), linearly interpolated within that year;
    ``PAYBACK_NEVER`` (30.1) if it never turns positive; 0 if the
    cumulative flow is positive from year 0; rounded to 0.1.

    ``soft=True`` (the differentiable twin, dgen_tpu.grad) skips the
    final round-to-0.1: the crossing-year selection is a
    piecewise-constant gather (zero gradient, deliberately — the
    envelope through the selected year's ``cum`` values carries the
    payback gradient), and the within-year interpolation ``frac`` is
    smooth in the cashflow, so dropping the snap is all grad needs.
    """
    cum = jnp.cumsum(cf)
    n = cf.shape[0] - 1  # tech lifetime

    no_payback = jnp.logical_or(cum[-1] <= 0.0, jnp.all(cum <= 0.0))
    instant = jnp.all(cum > 0.0)

    crossed = jnp.diff(jnp.sign(cum)) > 0          # [n]
    # LAST positive crossing (non-monotone cashflows — e.g. a year-1
    # ITC inflow followed by loan-payment years — can cross repeatedly)
    bi = (n - 1 - jnp.argmax(crossed[::-1])).astype(jnp.int32)
    bi = jnp.where(jnp.any(crossed), bi, n - 1)
    base_year = bi.astype(jnp.float32)
    base_val = cum[bi]
    next_val = cum[bi + 1]
    frac = base_val / (base_val - next_val + 1e-9)
    pp = base_year + frac
    pp = jnp.where(no_payback, PAYBACK_NEVER, jnp.where(instant, 0.0, pp))
    if soft:
        return pp
    return jnp.round(pp * 10.0) / 10.0
