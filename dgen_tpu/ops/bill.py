"""Utility-bill engine: the TPU replacement for PySAM ``Utilityrate5``.

The reference evaluates every sizing-objective call by running the SSC
C++ rate engine over an 8760 load/generation pair, one agent at a time
(reference financial_functions.py:270 ``utilityrate.execute()``). Here
the bill is a pure JAX function of dense arrays, vmappable over the
whole agent table and differentiable-by-construction.

Scope = exactly the subset the reference exercises (SURVEY.md §7):
  * TOU energy charges with monthly tier accumulation, 12x24 schedules.
  * Monthly fixed charges.
  * Net metering (monthly netting at retail, signed monthly-period sums,
    negative sums credited at the period's tier-1 price — semantics of
    the reference's in-repo oracle ``bill_calculator``
    tariff_functions.py:701 with ``full_retail_nem=True``, generalized to
    correct multi-tier accumulation as in SSC).
  * Net billing: imports billed on the TOU/tier structure, exports
    credited hourly at either a time-series sell rate (wholesale price x
    retail multiplier, reference financial_functions.py:182) or a TOU
    sell price (the CA NEM3 0.25 x buy rule, financial_functions.py:186).
  * Demand charges are intentionally absent from the hot loop: the
    reference globally skips them (``SKIP_DEMAND_CHARGES=True``,
    financial_functions.py:35). An oracle-validated TOU/flat demand
    engine for analysis runs lives in :mod:`dgen_tpu.ops.demand`.

TPU notes: the hour->month reduction is expressed as a masked matmul
against a static [8760, 12] month one-hot so it rides the MXU instead of
lowering to scatter-adds; the TOU-period loop is a static unrolled loop
over the (small) padded period count.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from dgen_tpu.ops.tariff import (
    HOURS,
    MONTHS,
    NET_BILLING,
    TariffBank,
    hour_month_map,
)

# Static [8760, 12] month one-hot, shared by every bill evaluation.
# Kept as NUMPY (folded to a device constant at trace time): a
# module-level jnp constant would initialize the XLA backend at import,
# breaking jax.distributed.initialize in launch.main().
_MONTH_ONEHOT = np.eye(MONTHS, dtype=np.float32)[hour_month_map()]


class AgentTariff(NamedTuple):
    """One agent's tariff slice, gathered from a :class:`TariffBank`."""

    price: jax.Array        # [P, T]
    tier_cap: jax.Array     # [T]
    sell_price: jax.Array   # [P]
    hour_period: jax.Array  # [8760] int32
    fixed_monthly: jax.Array  # scalar
    metering: jax.Array     # scalar int32


def gather_tariff(bank: TariffBank, tariff_idx: jax.Array) -> AgentTariff:
    """Index the bank for one agent (vmap over ``tariff_idx`` for many)."""
    return AgentTariff(
        price=bank.price[tariff_idx],
        tier_cap=bank.tier_cap[tariff_idx],
        sell_price=bank.sell_price[tariff_idx],
        hour_period=bank.hour_period[tariff_idx],
        fixed_monthly=bank.fixed_monthly[tariff_idx],
        metering=bank.metering[tariff_idx],
    )


def select_by_period(hour_period: jax.Array, per_period: jax.Array,
                     default: jax.Array) -> jax.Array:
    """Expand per-TOU-period values onto the hour axis by a static
    compare/select loop over the (small) period axis.

    NOT a gather on purpose: ``take_along_axis``/fancy indexing along an
    [8760] axis lowers to a pathologically slow TPU path (profiled at
    ~0.7 GB/s — one such gather was 87% of a whole 16k-agent year
    step). ``per_period``'s LAST axis is the period axis; leading axes
    must broadcast against ``default``/``hour_period``.
    """
    out = jnp.zeros_like(default)
    for p in range(per_period.shape[-1]):
        out = jnp.where(hour_period == p, per_period[..., p:p + 1], out)
    return out


def monthly_period_sums(x: jax.Array, hour_period: jax.Array, n_periods: int) -> jax.Array:
    """Sum an [8760] series into [12, P] month x TOU-period buckets.

    Expressed as P masked [8760]x[8760,12] matmuls (MXU-friendly) rather
    than a scatter-add segment sum.
    """
    per_period = []
    for p in range(n_periods):
        mask = (hour_period == p).astype(x.dtype)
        per_period.append((x * mask) @ _MONTH_ONEHOT)  # [12]
    return jnp.stack(per_period, axis=-1)  # [12, P]


def tiered_charge(sums: jax.Array, price: jax.Array, tier_cap: jax.Array,
                  soft_tau: float | None = None) -> jax.Array:
    """Proper cumulative tiered energy charge.

    ``sums``: [12, P] monthly energy per period (kWh, may be negative
    under net metering). Positive energy is charged tier by tier against
    the monthly caps; negative energy is credited at the period's tier-1
    price (oracle semantics, reference tariff_functions.py:687).
    Returns [12] monthly charges.

    ``soft_tau`` (kWh) smooths the tier-edge clips with softplus
    surrogates (grad.smooth) so marginal prices are differentiable
    across tier boundaries; ``None`` (default) lowers the exact hard
    clip.
    """
    lower = jnp.concatenate([jnp.zeros_like(tier_cap[:1]), tier_cap[:-1]])  # [T]
    width = tier_cap - lower
    if soft_tau is None:
        # [12, P, T]: energy falling inside each tier
        seg = jnp.clip(sums[..., None] - lower, 0.0, width)
        neg_sums = jnp.minimum(sums, 0.0)
    else:
        from dgen_tpu.grad.smooth import clip0_t, min0_t

        seg = clip0_t(sums[..., None] - lower, width, soft_tau)
        neg_sums = min0_t(sums, soft_tau)
    pos = jnp.einsum("mpt,pt->m", seg, price)
    neg = jnp.einsum("mp,p->m", neg_sums, price[:, 0])
    return pos + neg


@partial(jax.jit, static_argnames=("n_periods", "soft_tau"))
def annual_bill(
    net_load: jax.Array,
    tariff: AgentTariff,
    ts_sell: jax.Array,
    n_periods: int,
    soft_tau: float | None = None,
) -> jax.Array:
    """Annual bill for one agent given a signed hourly net grid load.

    ``net_load`` [8760]: load - system output at the meter (kW ~= kWh/h);
    positive = import, negative = export.
    ``ts_sell`` [8760]: time-series sell rate $/kWh used under net
    billing when the tariff's TOU ``sell_price`` is all-zero.

    Both metering styles are evaluated and selected per agent (the
    metering option is data, not structure, so agents with different
    compensation styles batch together under vmap).

    ``soft_tau`` (static) selects the differentiable twin: soft
    import/export splits (kW units) and soft tier clips (the same tau
    in kWh — monthly sums are O(100x) the hourly scale, so tier edges
    smooth proportionally tighter). ``None`` = the bit-exact hard path.
    """
    hp = tariff.hour_period

    # --- Net metering: signed monthly netting at retail ---
    sums_signed = monthly_period_sums(net_load, hp, n_periods)
    bill_nem = jnp.sum(tiered_charge(
        sums_signed, tariff.price, tariff.tier_cap, soft_tau))

    # --- Net billing: imports billed, exports credited at sell rate ---
    if soft_tau is None:
        imports = jnp.maximum(net_load, 0.0)
        exports = jnp.maximum(-net_load, 0.0)
    else:
        from dgen_tpu.grad.smooth import relu_t

        imports = relu_t(net_load, soft_tau)
        exports = relu_t(-net_load, soft_tau)
    sums_imp = monthly_period_sums(imports, hp, n_periods)
    import_charges = jnp.sum(tiered_charge(
        sums_imp, tariff.price, tariff.tier_cap, soft_tau))
    # Hourly sell rate: TOU sell if the tariff defines one, else the TS
    # rate (static period select, see select_by_period).
    tou_sell_hourly = select_by_period(hp, tariff.sell_price, ts_sell)
    has_tou_sell = jnp.any(tariff.sell_price > 0.0)
    sell_hourly = jnp.where(has_tou_sell, tou_sell_hourly, ts_sell)
    export_credit = jnp.sum(exports * sell_hourly)
    bill_nb = import_charges - export_credit

    energy_bill = jnp.where(tariff.metering == NET_BILLING, bill_nb, bill_nem)
    return energy_bill + MONTHS * tariff.fixed_monthly


def escalation_factors(n_years: int, inflation: jax.Array, escalation: jax.Array) -> jax.Array:
    """[Y] nominal price factor per analysis year (year 1 = 1.0).

    Utilityrate5 compounds inflation and the real rate escalation into
    nominal retail prices (reference feeds ``rate_escalation`` and
    ``inflation_rate`` separately, financial_functions.py:364-368).
    """
    y = jnp.arange(n_years, dtype=jnp.float32)
    return ((1.0 + inflation) * (1.0 + escalation)) ** y


def degradation_factors(n_years: int, degradation: jax.Array) -> jax.Array:
    """[Y] PV output factor per analysis year (year 1 = 1.0)."""
    y = jnp.arange(n_years, dtype=jnp.float32)
    return (1.0 - degradation) ** y


@partial(jax.jit, static_argnames=("n_periods", "n_years", "soft_tau"))
def bill_series(
    load: jax.Array,
    system_out: jax.Array,
    tariff: AgentTariff,
    ts_sell: jax.Array,
    inflation: jax.Array,
    escalation: jax.Array,
    degradation: jax.Array,
    n_periods: int,
    n_years: int,
    tariff_wo: AgentTariff | None = None,
    soft_tau: float | None = None,
) -> tuple[jax.Array, jax.Array]:
    """(bills_with_sys [Y], bills_without_sys [Y]) in nominal dollars.

    Replaces the reference's 25-pass SSC rate engine: PV output degrades
    compounding annually, retail prices escalate nominally, load is held
    constant across the analysis period (Utilityrate5 semantics with
    ``system_use_lifetime_output=0``, reference
    financial_functions.py:366).

    The no-system bill is computed once and scaled by the price factor
    (its net load never changes); the with-system bill re-evaluates the
    import/export split every year because degradation shifts it
    nonlinearly.

    ``tariff_wo`` prices the counterfactual no-system bill when the
    adopter switches to a DG rate on adoption (reference
    agent_mutation/elec.py:838 ``apply_rate_switch``: with-system on the
    switched rate, baseline on the original).
    """
    pf = escalation_factors(n_years, inflation, escalation)     # [Y]
    df = degradation_factors(n_years, degradation)              # [Y]

    bill_wo_y1 = annual_bill(
        load, tariff if tariff_wo is None else tariff_wo, ts_sell, n_periods,
        soft_tau,
    )
    bills_wo = bill_wo_y1 * pf

    def year_bill(deg_f):
        net = load - system_out * deg_f
        return annual_bill(net, tariff, ts_sell, n_periods, soft_tau)

    bills_w = jax.vmap(year_bill)(df) * pf
    return bills_w, bills_wo
