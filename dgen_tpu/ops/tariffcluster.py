"""Tariff-corpus clustering: shared rate banks at tight pad widths.

``compile_tariffs`` (ops.tariff) pads every tariff to the corpus-global
``max_periods`` / ``max_tiers``, so one 4-period 3-tier outlier makes
every flat-rate agent pay 12x the bucket lanes it needs — the
bucket-sums kernel's minor axis is ``12 * n_periods`` buckets and its
tier clip loops ``n_tiers`` times. Real URDB corpora collapse heavily:
a handful of structural shapes covers almost all rows. This module is
the layout half of the fix (AMBER's columnar-layout-first argument,
PAPERS.md [2], applied to the rate dimension):

* :func:`analyze_bank` canonicalizes compiled ``TariffBank`` rows into
  K structural clusters keyed by ``(metering mode, true period count,
  true tier count, demand-charge presence)``. Every member of a
  cluster shares exact tight extents, so the cluster's SHARED dense
  rate bank is sliced at its own pad widths — and byte-identical
  canonical rows are deduplicated, so N tariffs collapse to the few
  distinct rate structures the corpus actually contains.
* :func:`plan_layout` computes the cluster-major agent permutation,
  layered on the state-major device packing (parallel.partition):
  within each device shard, agents are stably reordered
  cluster-major (cluster within state within host) and each
  per-(device, cluster) segment padded to a uniform length with
  masked filler rows — the same gather/valid-mask idiom
  ``partition_table`` uses, so compiled shapes stay static across
  devices and results keyed by ``agent_id`` are invariant.

The compute half lives in models.simulation: ``year_step`` runs the
sizing kernel once per cluster at the cluster's tight ``n_periods``
with the cluster's ``net_billing`` flag, so single-period clusters
statically skip the TOU period scatter, single-tier clusters skip the
tier clip, and flat/NEM clusters route to the linear program — one
compiled program per structural signature, budgeted like sweep groups
(docs/perf.md "Tariff clustering").

CLI: ``python -m dgen_tpu.ops.tariffcluster --report`` prints the
cluster histogram + modeled lane-op savings for a package or a
synthetic world (wired into tools/check.sh as a smoke).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, NamedTuple, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from dgen_tpu.ops.tariff import NET_BILLING, TariffBank


class ClusterSpec(NamedTuple):
    """Static (hashable) signature of one cluster — part of year_step's
    ``cluster`` static argument, so two tables with the same cluster
    structure share every compiled program."""

    metering: int     # NET_METERING | NET_BILLING
    n_periods: int    # true TOU period count (tight pad width)
    n_tiers: int      # true tier count (tight pad width)
    has_demand: bool  # always False today (SKIP_DEMAND_CHARGES)
    n_rates: int      # deduplicated rate rows in the shared bank
    seg_len: int      # per-device rows of this cluster's segment
    offset: int       # per-device row offset of the segment
    #: statically proven per-cluster net-billing flag: False routes the
    #: whole cluster to the linear-NEM program (run_static_flags logic
    #: applied cluster-locally)
    net_billing: bool


class ClusterLayout(NamedTuple):
    """Static description of a cluster-major agent layout (the
    ``cluster`` static of year_step). All traced data — the compact
    banks and the per-row local tariff indices — travels separately."""

    clusters: Tuple[ClusterSpec, ...]
    n_dev: int
    local_len: int    # per-device rows = sum of segment lengths

    @property
    def n_clusters(self) -> int:
        return len(self.clusters)

    @property
    def n_rows(self) -> int:
        return self.n_dev * self.local_len

    def with_flags(self, flags: Tuple[bool, ...]) -> "ClusterLayout":
        """Replace the per-cluster net-billing flags (after the host
        proves them against a specific set of scenario inputs)."""
        if len(flags) != self.n_clusters:
            raise ValueError(
                f"{len(flags)} flags for {self.n_clusters} clusters")
        return self._replace(clusters=tuple(
            c._replace(net_billing=bool(f))
            for c, f in zip(self.clusters, flags)))

    def pin_net_billing(self, net_billing: bool) -> "ClusterLayout":
        """Conservatively pin every cluster to one global flag — the
        sweep planner's one-compile-per-group contract (a pinned-True
        group must not compile per-scenario cluster programs; True is
        exact for every cluster, it only skips the linear shortcut)."""
        return self.with_flags((bool(net_billing),) * self.n_clusters)

    def cluster_of_rows(self) -> np.ndarray:
        """[n_dev * local_len] int32: cluster id of each laid-out row."""
        per_dev = np.empty(self.local_len, dtype=np.int32)
        for ci, c in enumerate(self.clusters):
            per_dev[c.offset:c.offset + c.seg_len] = ci
        return np.tile(per_dev, self.n_dev)


@dataclasses.dataclass(frozen=True)
class ClusterPlan:
    """Host-side corpus analysis: global tariff index -> (cluster,
    local row of the cluster's shared compact bank)."""

    keys: Tuple[Tuple[int, int, int, bool], ...]
    members: Tuple[Tuple[int, ...], ...]   # global tariff ids per cluster
    banks: Tuple[TariffBank, ...]          # compact, deduplicated banks
    cluster_of_tariff: np.ndarray          # [K_global] int32
    local_of_tariff: np.ndarray            # [K_global] int32

    @property
    def n_clusters(self) -> int:
        return len(self.keys)


def analyze_bank(tariffs: TariffBank) -> ClusterPlan:
    """Canonicalize a compiled bank into structural clusters.

    Two tariffs land in one cluster iff they share
    ``(metering, n_periods, n_tiers, has_demand)`` — so every member's
    tight slice has identical shape and the cluster bank pads nothing.
    Within a cluster, tariffs whose canonical bytes (tight price /
    caps / sell / schedule / fixed / metering) match are deduplicated
    onto one shared bank row.
    """
    met = np.asarray(tariffs.metering)
    n_p = np.asarray(tariffs.n_periods)
    n_t = np.asarray(tariffs.n_tiers)
    price = np.asarray(tariffs.price)
    tier_cap = np.asarray(tariffs.tier_cap)
    sell = np.asarray(tariffs.sell_price)
    sched = np.asarray(tariffs.hour_period)
    fixed = np.asarray(tariffs.fixed_monthly)

    K = tariffs.n_tariffs
    keys: list = []
    key_of: Dict[Tuple[int, int, int, bool], int] = {}
    members: list = []
    dedup: list = []        # per cluster: canonical bytes -> local row
    rows: list = []         # per cluster: list of global source rows
    cluster_of = np.zeros(K, dtype=np.int32)
    local_of = np.zeros(K, dtype=np.int32)

    for k in range(K):
        P, T = int(n_p[k]), int(n_t[k])
        key = (int(met[k]), P, T, False)
        ci = key_of.get(key)
        if ci is None:
            ci = len(keys)
            key_of[key] = ci
            keys.append(key)
            members.append([])
            dedup.append({})
            rows.append([])
        canon = b"".join((
            np.ascontiguousarray(price[k, :P, :T]).tobytes(),
            np.ascontiguousarray(tier_cap[k, :T]).tobytes(),
            np.ascontiguousarray(sell[k, :P]).tobytes(),
            np.ascontiguousarray(sched[k]).tobytes(),
            np.float32(fixed[k]).tobytes(),
        ))
        li = dedup[ci].get(canon)
        if li is None:
            li = len(rows[ci])
            dedup[ci][canon] = li
            rows[ci].append(k)
        members[ci].append(k)
        cluster_of[k] = ci
        local_of[k] = li

    banks = []
    for (m, P, T, _), src in zip(keys, rows):
        src = np.asarray(src, dtype=np.int64)
        banks.append(TariffBank(
            price=jnp.asarray(price[src][:, :P, :T]),
            tier_cap=jnp.asarray(tier_cap[src][:, :T]),
            sell_price=jnp.asarray(sell[src][:, :P]),
            hour_period=jnp.asarray(sched[src]),
            fixed_monthly=jnp.asarray(fixed[src]),
            metering=jnp.asarray(met[src]),
            n_periods=jnp.asarray(n_p[src]),
            n_tiers=jnp.asarray(n_t[src]),
        ))
    return ClusterPlan(
        keys=tuple(tuple(k) for k in keys),
        members=tuple(tuple(m) for m in members),
        banks=tuple(banks),
        cluster_of_tariff=cluster_of,
        local_of_tariff=local_of,
    )


def plan_layout(
    plan: ClusterPlan,
    tariff_idx: np.ndarray,
    mask: np.ndarray,
    n_dev: int,
    pad_mult: int,
) -> Tuple[ClusterLayout, np.ndarray, np.ndarray, np.ndarray]:
    """Cluster-major layout of an (already device-partitioned) table.

    Within each device shard of ``n_dev`` equal shards, REAL rows
    (``mask > 0``) are stably reordered by cluster id — preserving the
    state-major order within each cluster — and each per-(device,
    cluster) segment is padded to a device-uniform, ``pad_mult``-rounded
    length. Padding slots gather a real in-segment row with valid 0
    (the partition_table idiom), so every compiled shape is static.

    Returns ``(layout, gather, valid, cluster_tidx)``:

    * ``gather`` [N'] int64 — new position -> source row of the input
      layout (the permutation; its inverse is :func:`original_positions`)
    * ``valid`` [N'] float32 — 1 for real rows, 0 for cluster padding
    * ``cluster_tidx`` [N'] int32 — per-row LOCAL index into the row's
      cluster bank (0 on padding slots)

    Only clusters with at least one real member row appear in the
    layout (in plan order), so unused corpus tariffs cost nothing.
    """
    tariff_idx = np.asarray(tariff_idx)
    mask = np.asarray(mask)
    N = len(tariff_idx)
    if n_dev < 1 or N % n_dev:
        raise ValueError(f"{N} rows not divisible into {n_dev} shards")
    local = N // n_dev
    cid = plan.cluster_of_tariff[tariff_idx]
    real = mask > 0

    # per-device stable grouping by cluster id
    seg_rows = [[None] * plan.n_clusters for _ in range(n_dev)]
    counts = np.zeros((n_dev, plan.n_clusters), dtype=np.int64)
    for d in range(n_dev):
        sl = slice(d * local, (d + 1) * local)
        rows_d = np.nonzero(real[sl])[0] + d * local
        cid_d = cid[rows_d]
        for ci in range(plan.n_clusters):
            seg = rows_d[cid_d == ci]
            seg_rows[d][ci] = seg
            counts[d, ci] = len(seg)

    kept = [ci for ci in range(plan.n_clusters) if counts[:, ci].max() > 0]
    specs = []
    off = 0
    for ci in kept:
        need = int(counts[:, ci].max())
        seg_len = max(-(-need // pad_mult) * pad_mult, pad_mult)
        m, P, T, hd = plan.keys[ci]
        specs.append(ClusterSpec(
            metering=m, n_periods=P, n_tiers=T, has_demand=hd,
            n_rates=plan.banks[ci].n_tariffs, seg_len=seg_len,
            offset=off, net_billing=m == NET_BILLING,
        ))
        off += seg_len
    local_len = off

    gather = np.zeros(n_dev * local_len, dtype=np.int64)
    valid = np.zeros(n_dev * local_len, dtype=np.float32)
    for d in range(n_dev):
        # padding filler must stay in-shard: any real row works (the
        # mask zeroes its contribution), prefer one from the segment's
        # own cluster so even the dead lanes run in-range gathers
        shard_real = np.nonzero(real[d * local:(d + 1) * local])[0]
        shard_fill = (shard_real[0] + d * local) if len(shard_real) \
            else d * local
        for spec, ci in zip(specs, kept):
            seg = seg_rows[d][ci]
            fill = seg[0] if len(seg) else shard_fill
            o = d * local_len + spec.offset
            gather[o:o + len(seg)] = seg
            gather[o + len(seg):o + spec.seg_len] = fill
            valid[o:o + len(seg)] = 1.0

    cluster_tidx = plan.local_of_tariff[tariff_idx[gather]].astype(np.int32)
    # a filler gathered from another cluster (empty segment on this
    # device) would index out of the segment's compact bank — clamp it
    # to row 0; the slot is masked either way
    gathered_cid = cid[gather]
    layout = ClusterLayout(clusters=tuple(specs), n_dev=n_dev,
                           local_len=local_len)
    own_cid = np.asarray(
        [kept[c] for c in layout.cluster_of_rows()], dtype=np.int64)
    cluster_tidx = np.where(gathered_cid == own_cid, cluster_tidx, 0)
    return layout, gather, valid, cluster_tidx


def banks_for_layout(
    plan: ClusterPlan, layout: ClusterLayout
) -> Tuple[TariffBank, ...]:
    """The layout's compact banks, in layout cluster order.

    ``plan_layout`` drops clusters with no real member rows, so the
    layout's clusters are a (plan-ordered) subset of the plan's —
    matched here by structural key, which is unique per cluster."""
    by_key = {k: b for k, b in zip(plan.keys, plan.banks)}
    return tuple(
        by_key[(c.metering, c.n_periods, c.n_tiers, c.has_demand)]
        for c in layout.clusters
    )


def original_positions(gather: np.ndarray, valid: np.ndarray,
                       n_original: int) -> np.ndarray:
    """[n_original] int64: position of each source row in the laid-out
    order (-1 for source rows that were dropped, i.e. masked padding of
    the input layout). The inverse permutation — gathering a laid-out
    result at these positions restores source order bit-exactly."""
    pos = np.full(n_original, -1, dtype=np.int64)
    idx = np.nonzero(np.asarray(valid) > 0)[0]
    pos[np.asarray(gather)[idx]] = idx
    return pos


# ---------------------------------------------------------------------------
# Reporting: cluster histogram + modeled lane-op savings
# ---------------------------------------------------------------------------

def cluster_report(
    tariffs: TariffBank,
    tariff_idx: Optional[np.ndarray] = None,
    mask: Optional[np.ndarray] = None,
) -> dict:
    """Cluster histogram + modeled bucket-lane savings.

    The bucket-sums kernel's per-agent lane work scales with its bucket
    minor axis, ``12 * n_periods`` (ops.billpallas); linear/NEM
    clusters run the closed-form program with zero kernel lanes. The
    model compares ``sum_c agents_c * 12 * P_c`` (net-billing clusters
    only, at tight pads) against every agent paying
    ``12 * max_periods`` in one global kernel — the unclustered cost
    whenever the corpus has any net-billing tariff. NEM clusters are
    counted as linear (their gate-closure proof is input-dependent;
    docs/perf.md "Tariff clustering" covers the conservative case).
    """
    plan = analyze_bank(tariffs)
    if tariff_idx is None:
        agents_of = {
            ci: len(m) for ci, m in enumerate(plan.members)}
        n_agents = tariffs.n_tariffs
    else:
        tariff_idx = np.asarray(tariff_idx)
        if mask is not None:
            tariff_idx = tariff_idx[np.asarray(mask) > 0]
        cnt = np.bincount(plan.cluster_of_tariff[tariff_idx],
                          minlength=plan.n_clusters)
        agents_of = {ci: int(cnt[ci]) for ci in range(plan.n_clusters)}
        n_agents = int(tariff_idx.shape[0])

    clusters = []
    lanes_clustered = 0
    for ci, (m, P, T, hd) in enumerate(plan.keys):
        nb = m == NET_BILLING
        lanes = agents_of[ci] * 12 * P if nb else 0
        lanes_clustered += lanes
        clusters.append({
            "metering": int(m),
            "n_periods": int(P),
            "n_tiers": int(T),
            "has_demand": bool(hd),
            "n_tariffs": len(plan.members[ci]),
            "n_rates": plan.banks[ci].n_tariffs,
            "n_agents": agents_of[ci],
            "net_billing": nb,
            "bucket_lanes": lanes,
        })
    any_nb = any(c["net_billing"] for c in clusters)
    lanes_global = n_agents * 12 * tariffs.max_periods if any_nb else 0
    return {
        "n_tariffs": tariffs.n_tariffs,
        "n_clusters": plan.n_clusters,
        "n_agents": n_agents,
        "global_pad": {"n_periods": tariffs.max_periods,
                       "n_tiers": tariffs.max_tiers},
        "clusters": clusters,
        "bucket_lanes_global": int(lanes_global),
        "bucket_lanes_clustered": int(lanes_clustered),
        "modeled_lane_savings": round(
            1.0 - lanes_clustered / lanes_global, 4) if lanes_global else 0.0,
    }


def main(argv=None) -> int:
    """``python -m dgen_tpu.ops.tariffcluster --report``: the cluster
    histogram of a saved agent package or a synthetic national world
    (tools/check.sh smoke)."""
    import argparse
    import json

    p = argparse.ArgumentParser(
        prog="python -m dgen_tpu.ops.tariffcluster",
        description="tariff-corpus cluster histogram + modeled lane-op "
                    "savings (docs/perf.md 'Tariff clustering')",
    )
    p.add_argument("--report", action="store_true", required=True,
                   help="print the cluster report as JSON")
    p.add_argument("--package", default="",
                   help="agent package dir (io.package); default: a "
                        "synthetic world")
    p.add_argument("--agents", type=int, default=4096,
                   help="synthetic world size (ignored with --package)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--tariff-mix", default="mixed",
                   help="synthetic corpus selector (models.synth)")
    args = p.parse_args(argv)

    if args.package:
        # CLI-only, lazy: the kernel layer stays importable
        # without the IO/model stack
        from dgen_tpu.io.package import load_population  # dgenlint: disable=L5

        pop = load_population(args.package)
        src = {"package": args.package}
    else:
        from dgen_tpu.models.synth import (  # dgenlint: disable=L5
            NationalSpec, generate_world)

        pop = generate_world(NationalSpec(
            n_agents=args.agents, seed=args.seed,
            tariff_mix=args.tariff_mix))
        src = {"synthetic": {"agents": args.agents, "seed": args.seed,
                             "tariff_mix": args.tariff_mix}}
    report = cluster_report(
        pop.tariffs, np.asarray(pop.table.tariff_idx),
        np.asarray(pop.table.mask))
    report["source"] = src
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
