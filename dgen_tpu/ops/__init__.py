"""Device-side compute kernels: tariff compilation, bill engine, battery
dispatch, multi-year cashflow, and the NPV-optimal sizing search.

These replace the reference's native PySAM/SSC C++ simulation core
(reference financial_functions.py:26-32) with fused, vmappable JAX
kernels (SURVEY.md §2.7).
"""

from dgen_tpu.ops import (  # noqa: F401
    bill,
    billpallas,
    cashflow,
    dispatch,
    sizing,
    tariff,
)
