"""Dense tariff representation and the tariff compiler.

The reference keeps each agent's retail tariff as a nested Python dict
(``tariff_dict``) in a DataFrame cell, normalizes it per sizing call
(reference financial_functions.py:962 ``normalize_tariff``), and feeds it
to the PySAM ``Utilityrate5`` C++ engine. None of that can live on a TPU
device path: strings, ragged period/tier structures, and per-call dict
parsing all break XLA tracing.

Here tariffs are compiled ONCE at ingest into a bank of dense, padded
tensors (``TariffBank``) that every kernel indexes by ``tariff_idx``:

  * ``price[K, P, T]``   — buy $/kWh for tariff k, TOU period p, tier t.
  * ``tier_cap[K, T]``   — monthly kWh cap of each tier (harmonized
                           across periods, reference
                           financial_functions.py:919
                           ``_harmonize_tier_caps_and_units``); unbounded
                           tiers use ``BIG_CAP``.
  * ``sell_price[K, P]`` — TOU sell $/kWh (column 6 of the reference's
                           ``ur_ec_tou_mat``); used for CA-NEM3-style
                           tariffs where sell = 0.25 x buy (reference
                           financial_functions.py:180-191).
  * ``hour_period[K, 8760]`` — hour-of-year -> TOU period map, flattened
                           from the 12x24 weekday/weekend schedules
                           (reference ``ur_ec_sched_weekday/weekend``).
  * ``fixed_monthly[K]`` — monthly fixed charge.
  * ``metering[K]``      — 0 = net metering (monthly netting at retail),
                           2 = net billing (imports billed, exports
                           credited at a sell rate). Demand charges are
                           skipped, matching the reference's global
                           ``SKIP_DEMAND_CHARGES=True``
                           (financial_functions.py:35).
  * ``n_periods[K]``, ``n_tiers[K]`` — true extents (padding beyond is
                           priced 0 / capped BIG).

Normalization semantics reproduced from the reference compiler
(financial_functions.py:830-1007): period ids remapped contiguous,
every period given the same tier count (padded with an unbounded clone
of its last tier), a single per-tier cap across periods (min finite cap,
else unbounded).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

# Unbounded-tier sentinel. The reference uses 1e38 (financial_functions.py:839);
# we keep it finite and well inside float32 range.
BIG_CAP = 1e38

HOURS = 8760
MONTHS = 12

# Metering options (subset the reference exercises; Utilityrate5 codes).
NET_METERING = 0
NET_BILLING = 2

# Cumulative hours at each month boundary for a non-leap year
# (same table as reference tariff_functions.py:751).
MONTH_HOURS = np.array(
    [0, 744, 1416, 2160, 2880, 3624, 4344, 5088, 5832, 6552, 7296, 8016, 8760],
    dtype=np.int64,
)


def hour_month_map() -> np.ndarray:
    """[8760] int32: hour-of-year -> month index 0..11."""
    out = np.zeros(HOURS, dtype=np.int32)
    for m in range(MONTHS):
        out[MONTH_HOURS[m]:MONTH_HOURS[m + 1]] = m
    return out


def hour_weekend_map(jan1_dow: int = 0) -> np.ndarray:
    """[8760] bool: True where the hour falls on a weekend day.

    The reference's schedule expansion needs a calendar convention; we fix
    Jan 1 = Monday (``jan1_dow=0``) for determinism across runs.
    """
    day = np.arange(HOURS) // 24
    dow = (day + jan1_dow) % 7
    return dow >= 5


_HOUR_MONTH = hour_month_map()
_HOUR_WEEKEND = hour_weekend_map()
_HOUR_OF_DAY = (np.arange(HOURS) % 24).astype(np.int32)


def expand_schedule_8760(wkday_12x24: np.ndarray, wkend_12x24: np.ndarray) -> np.ndarray:
    """Flatten 12x24 weekday/weekend period schedules to an [8760] map.

    Period ids in the input are 0-based here (the reference uses 1-based
    for PySAM; the compiler handles the shift).
    """
    wkday = np.asarray(wkday_12x24, dtype=np.int32)
    wkend = np.asarray(wkend_12x24, dtype=np.int32)
    by_day = np.where(_HOUR_WEEKEND, wkend[_HOUR_MONTH, _HOUR_OF_DAY], wkday[_HOUR_MONTH, _HOUR_OF_DAY])
    return by_day.astype(np.int32)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class TariffBank:
    """Bank of K compiled tariffs as dense padded device arrays."""

    price: jax.Array        # [K, P, T] float32 buy $/kWh
    tier_cap: jax.Array     # [K, T] float32 monthly kWh cap per tier
    sell_price: jax.Array   # [K, P] float32 TOU sell $/kWh (0 if unused)
    hour_period: jax.Array  # [K, 8760] int32 TOU period per hour
    fixed_monthly: jax.Array  # [K] float32 $/month
    metering: jax.Array     # [K] int32 (NET_METERING | NET_BILLING)
    n_periods: jax.Array    # [K] int32
    n_tiers: jax.Array      # [K] int32

    @property
    def n_tariffs(self) -> int:
        return self.price.shape[0]

    @property
    def max_periods(self) -> int:
        return self.price.shape[1]

    @property
    def max_tiers(self) -> int:
        return self.price.shape[2]


def _coerce_12x24(mat: Optional[Sequence[Sequence[int]]]) -> np.ndarray:
    """Pad/trim an arbitrary schedule to a strict 12x24 int array of 0s
    where missing (reference financial_functions.py:719 ``_sched_12x24``)."""
    out = np.zeros((12, 24), dtype=np.int32)
    if mat is None:
        return out
    a = np.asarray(mat)
    if a.ndim != 2:
        return out
    r = min(12, a.shape[0])
    c = min(24, a.shape[1])
    out[:r, :c] = a[:r, :c].astype(np.int32)
    return out


def normalize_tariff_spec(spec: Dict[str, Any]) -> Dict[str, Any]:
    """Normalize a raw tariff spec dict into contiguous-period,
    equal-tier, harmonized-cap dense numpy form.

    Accepted keys (a tpu-friendly distillation of the reference's
    ``tariff_dict`` after its own normalization — see
    financial_functions.py:962 ``normalize_tariff``):

      - ``e_prices``: [T][P] buy price per tier x period (legacy layout) OR
        ``price``: [P][T].
      - ``e_levels``: [T][P] tier caps (legacy) OR ``tier_cap``: [T].
      - ``e_wkday_12by24`` / ``e_wkend_12by24``: 12x24 0-based period ids.
      - ``fixed_charge``: $/month.
      - ``metering``: 0 net-metering | 2 net-billing (default 0).
      - ``sell_frac_of_buy``: scalar; if >0 the TOU sell price is this
        fraction of the tier-1 buy price (the CA NEM3 rule, reference
        financial_functions.py:186-191 uses 0.25).

    Returns dict with keys price [P,T], tier_cap [T], sell_price [P],
    wkday/wkend 12x24 (0-based contiguous), fixed_monthly, metering.
    """
    if "price" in spec:
        price = np.asarray(spec["price"], dtype=np.float64)  # [P, T]
    else:
        e_prices = np.asarray(spec.get("e_prices", [[0.1]]), dtype=np.float64)  # [T, P]
        price = e_prices.T
    n_periods, n_tiers = price.shape

    if "tier_cap" in spec:
        caps = np.asarray(spec["tier_cap"], dtype=np.float64)
    else:
        e_levels = spec.get("e_levels")
        if e_levels is None:
            caps = np.full(n_tiers, BIG_CAP)
        else:
            lv = np.asarray(e_levels, dtype=np.float64)  # [T, P]
            # Harmonize: one cap per tier = min finite cap across periods,
            # else unbounded (reference financial_functions.py:948-953).
            caps = np.empty(n_tiers)
            for t in range(n_tiers):
                row = lv[t]
                finite = row[(row > 0) & (row < 1e37)]
                caps[t] = finite.min() if finite.size else BIG_CAP
    caps = np.maximum.accumulate(caps)  # enforce nondecreasing
    caps[-1] = BIG_CAP  # top tier always unbounded

    wkday = _coerce_12x24(spec.get("e_wkday_12by24"))
    wkend = _coerce_12x24(spec.get("e_wkend_12by24"))

    # Remap period ids used by schedules+price rows to contiguous 0..P-1
    # (reference financial_functions.py:853-862).
    used = np.unique(np.concatenate([wkday.ravel(), wkend.ravel()]))
    used = used[(used >= 0) & (used < n_periods)]
    if used.size == 0:
        used = np.array([0])
    remap = np.zeros(max(n_periods, int(used.max()) + 1), dtype=np.int32)
    remap[used] = np.arange(used.size, dtype=np.int32)
    wkday = remap[np.clip(wkday, 0, remap.size - 1)]
    wkend = remap[np.clip(wkend, 0, remap.size - 1)]
    price = price[used, :]
    n_periods = used.size

    sell_frac = float(spec.get("sell_frac_of_buy", 0.0))
    sell_price = price[:, 0] * sell_frac if sell_frac > 0 else np.zeros(n_periods)

    return {
        "price": price,
        "tier_cap": caps,
        "sell_price": sell_price,
        "wkday": wkday,
        "wkend": wkend,
        "fixed_monthly": float(spec.get("fixed_charge", 0.0)),
        "metering": int(spec.get("metering", NET_METERING)),
    }


def compile_tariffs(
    specs: List[Dict[str, Any]],
    max_periods: Optional[int] = None,
    max_tiers: Optional[int] = None,
) -> TariffBank:
    """Compile raw tariff specs into a padded :class:`TariffBank`.

    Padding beyond a tariff's true extents is priced at the tariff's
    top-tier price with unbounded caps, so padded entries never alter a
    bill (monthly energy can't reach them / schedules never select them).
    """
    normed = [normalize_tariff_spec(s) for s in specs]
    P = max_periods or max(n["price"].shape[0] for n in normed)
    T = max_tiers or max(n["price"].shape[1] for n in normed)
    K = len(normed)

    price = np.zeros((K, P, T), dtype=np.float32)
    tier_cap = np.full((K, T), BIG_CAP, dtype=np.float32)
    sell_price = np.zeros((K, P), dtype=np.float32)
    hour_period = np.zeros((K, HOURS), dtype=np.int32)
    fixed_monthly = np.zeros(K, dtype=np.float32)
    metering = np.zeros(K, dtype=np.int32)
    n_periods = np.zeros(K, dtype=np.int32)
    n_tiers = np.zeros(K, dtype=np.int32)

    for k, n in enumerate(normed):
        p, t = n["price"].shape
        if p > P or t > T:
            raise ValueError(f"tariff {k} exceeds bank shape ({p}x{t} > {P}x{T})")
        price[k, :p, :t] = n["price"]
        # pad tiers with the last tier's price (unbounded cap -> inert)
        if t < T:
            price[k, :p, t:] = n["price"][:, -1:]
        # pad periods with period 0's prices (schedules never select them)
        if p < P:
            price[k, p:, :] = price[k, 0:1, :]
        tier_cap[k, :t] = n["tier_cap"]
        sell_price[k, :p] = n["sell_price"]
        hour_period[k] = expand_schedule_8760(n["wkday"], n["wkend"])
        fixed_monthly[k] = n["fixed_monthly"]
        metering[k] = n["metering"]
        n_periods[k] = p
        n_tiers[k] = t

    return TariffBank(
        price=jnp.asarray(price),
        tier_cap=jnp.asarray(tier_cap),
        sell_price=jnp.asarray(sell_price),
        hour_period=jnp.asarray(hour_period),
        fixed_monthly=jnp.asarray(fixed_monthly),
        metering=jnp.asarray(metering),
        n_periods=jnp.asarray(n_periods),
        n_tiers=jnp.asarray(n_tiers),
    )


def flat_tariff(price: float, fixed: float = 0.0, metering: int = NET_METERING,
                sell_frac_of_buy: float = 0.0) -> Dict[str, Any]:
    """Convenience: single-period single-tier flat-rate tariff spec."""
    return {
        "price": [[price]],
        "fixed_charge": fixed,
        "metering": metering,
        "sell_frac_of_buy": sell_frac_of_buy,
    }
