"""Validated run/scenario configuration.

The reference splits configuration across module constants (config.py),
validated ``ModelSettings``/``ScenarioSettings`` property objects
(settings.py:19,266), env-var overrides, and an Excel input workbook.
Here a scenario is a single frozen dataclass validated at construction;
there is no Excel/DB layer — inputs are files loaded by ``dgen_tpu.io``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence, Tuple

SECTORS = ("res", "com", "ind")
SECTOR_IDX = {s: i for i, s in enumerate(SECTORS)}

#: Payback grid the max-market-share curves are tabulated on:
#: 0.0..30.1 in steps of 0.1 (the reference discretizes payback to a
#: x100 integer factor for its lookup, financial_functions.py:1290, and
#: uses 30.1 as the "never pays back" sentinel, :1259).
PAYBACK_GRID_MAX = 30.1
PAYBACK_GRID_STEP = 0.1
PAYBACK_GRID_N = int(round(PAYBACK_GRID_MAX / PAYBACK_GRID_STEP)) + 1  # 302
PAYBACK_NEVER = 30.1

#: synthetic Bass-diffusion defaults (p, q, teq_yr1) used by
#: scenario.uniform_inputs AND as the fill for state x sector groups a
#: bass_params.csv drop-in does not cover — single source so the two
#: cannot drift (the real curves live only in the reference's Postgres
#: dump, data_functions.py:279)
BASS_DEFAULTS = (0.0015, 0.35, 2.0)


def _check(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(msg)


@dataclasses.dataclass(frozen=True)
class ScenarioConfig:
    """Per-scenario settings (validated analogue of reference
    settings.py:266 ``ScenarioSettings``)."""

    name: str = "default"
    start_year: int = 2014
    end_year: int = 2050
    #: solar diffusion steps forward two years per solve
    #: (reference diffusion_functions_elec.py:285)
    year_step: int = 2
    sectors: Tuple[str, ...] = SECTORS
    #: analysis horizon for bills/cashflow (reference financing terms
    #: set economic_lifetime_yrs = 30)
    economic_lifetime_yrs: int = 30
    #: historical anchor years rescaled to observed deployment
    #: (reference diffusion_functions_elec.py:99)
    anchor_years: Tuple[int, ...] = (2014, 2016, 2018)
    #: enable the battery-attachment post-diffusion step
    storage_enabled: bool = True
    annual_inflation: float = 0.025

    def __post_init__(self) -> None:
        _check(1990 <= self.start_year <= 2050, "start_year out of range")
        _check(self.start_year <= self.end_year <= 2050,
               "end_year must be in [start_year, 2050]")
        _check(self.year_step in (1, 2), "year_step must be 1 or 2")
        _check(all(s in SECTORS for s in self.sectors), "unknown sector")
        _check(1 <= self.economic_lifetime_yrs <= 50, "bad lifetime")
        _check(0.0 <= self.annual_inflation < 0.5, "bad inflation")

    @property
    def model_years(self) -> Sequence[int]:
        return list(range(self.start_year, self.end_year + 1, self.year_step))


@dataclasses.dataclass(frozen=True)
class RunConfig:
    """Machine/run settings (analogue of reference settings.py:19
    ``ModelSettings``). Env overrides mirror the reference's
    ``LOCAL_CORES``-style hooks (settings.py:484-494)."""

    #: pad the agent axis to a multiple of this (TPU lane friendliness)
    agent_pad_multiple: int = 128
    #: golden-section iterations for the PV sizing search
    sizing_iters: int = 12
    #: agent-axis chunk for the streaming year step (rows PER DEVICE per
    #: chunk). Chunking bounds peak HBM to one chunk's [chunk, 8760]
    #: intermediates so populations far beyond the whole-table ceiling
    #: (~50k agents on a 16 GB chip) fit — the TPU answer to the
    #: reference's per-state task sharding (submit_all.sh:8-46).
    #: ``None`` (default) derives the chunk from the device HBM budget
    #: (models.simulation.auto_agent_chunk) — like the reference, the
    #: operator never picks memory shapes; ``0`` forces the whole-table
    #: path; ``>0`` fixes the chunk by hand.
    agent_chunk: Optional[int] = None
    #: number of devices to shard agents over (None = all available)
    n_devices: Optional[int] = None
    #: reorder agents so states are shard-local under a multi-device
    #: mesh (parallel.partition, the reference's per-state task binning)
    partition_by_state: bool = True
    #: run the invariant harness every year step (utils.invariants —
    #: the reference's run_with_runtime_tests analogue; host sync cost)
    debug_invariants: bool = False
    #: daylight-compacted bill kernels (ops.billpallas.DaylightLayout):
    #: the sizing search's candidate kernels run only over the union
    #: daylight lanes of the generation bank (~half the hour axis for
    #: rooftop solar); night-hour bucket sums are candidate-independent
    #: and added back exactly. Off by default — the full-hour path is
    #: the parity oracle; results agree to ~1e-5 relative (f32
    #: re-association only). Env: DGEN_TPU_DAYLIGHT.
    daylight_compact: bool = False
    #: store the hourly load/gen/wholesale profile banks in bfloat16
    #: (f32 upcast inside the kernels): halves the O(N*8760) HBM
    #: traffic and footprint of the sizing hot loop, so
    #: auto_agent_chunk picks ~1.7x larger streaming chunks. Inputs are
    #: rounded to ~3 significant digits — bills shift ~0.1-1%; see
    #: docs/perf.md for the measured golden-run envelope. Off by
    #: default. Env: DGEN_TPU_BF16_BANKS.
    bf16_banks: bool = False
    #: store the ProfileBank load/gen streams as int8 codes with
    #: per-row f32 scale factors (ops.billpallas._quant_fold): the
    #: sizing hot loop's dominant O(N*8760) HBM streams shrink to ONE
    #: byte per hour (4x under f32, 2x under bf16 — the wholesale/sell
    #: stream keeps the bank float dtype), kernels upcast + accumulate
    #: in f32, and the dispatch/linear_sums/naep/keep_hourly floors
    #: price dequantized f32 — the same floor rule as bf16_banks.
    #: Inputs round to 1/254 of each bank row's range (~0.4% worst
    #: case); see docs/perf.md for the measured golden envelope. Off by
    #: default — the f32 full-hour path stays the parity oracle. Env:
    #: DGEN_TPU_QUANT_BANKS.
    quant_banks: bool = False
    #: gather the sizing search's month-positional candidate streams
    #: ONCE per size_agents call (billpallas.PackedStreams) instead of
    #: once per bucket-sums engine call — one repack gather (and one
    #: night-sums pass under daylight_compact) per year instead of up
    #: to three. Off by default (the per-call path is the parity
    #: oracle). Env: DGEN_TPU_PACK_ONCE.
    pack_once: bool = False
    #: run the candidate kernels on the double-buffered (agent-block x
    #: month-segment) stream engine (billpallas._sums_pallas_stream):
    #: the DMA of month segment m+1 overlaps compute on segment m, so
    #: HBM reads hide behind the VPU floor instead of serializing
    #: ahead of each agent's program. TPU only — elsewhere the XLA
    #: twin runs (same math). Off by default. Env: DGEN_TPU_STREAM.
    stream_segments: bool = False
    #: differentiable smooth-boundary twin (dgen_tpu.grad): replace the
    #: objective's non-differentiable kinks — tariff-tier / TOU-bucket
    #: edges, the hard relu import/export splits, the payback rounding
    #: and the payback->MMS table snap — with temperature-controlled
    #: softplus/soft-min surrogates (plus straight-through estimators
    #: at the deliberate hard gates), so the NPV objective and the full
    #: multi-year rollout support jax.grad. Off by default — the f32
    #: full-hour hard path stays the bit-exact oracle and the committed
    #: program fingerprints never move. Smooth runs force the plain XLA
    #: f32 kernels (no daylight/pack/quant/bf16/pallas). Env:
    #: DGEN_TPU_SOFT.
    soft_boundaries: bool = False
    #: smoothing temperature for soft_boundaries, in the objective's
    #: native units (kW at the hourly import/export splits, kWh at the
    #: monthly tier edges, years at the payback gates). Smaller tracks
    #: the hard objective tighter; larger smooths gradients further
    #: from each kink. Env: DGEN_TPU_SOFT_TAU.
    soft_tau: float = 0.1
    #: tariff-clustered sizing (ops.tariffcluster, docs/perf.md "Tariff
    #: clustering"): canonicalize the compiled tariff corpus into
    #: structural clusters keyed by (metering, true periods, true
    #: tiers, demand presence), reorder agents cluster-major within
    #: each device shard, and run the sizing kernel once per cluster at
    #: the cluster's tight pad widths with shared deduplicated rate
    #: banks — single-period clusters skip the TOU scatter, flat/NEM
    #: clusters route to the linear program. One compiled program per
    #: structural signature, results keyed by agent_id unchanged.
    #: Auto-disabled (with a log line) when rate switching is active —
    #: a base/switch tariff pair can straddle clusters. Off by default;
    #: the global-bank path stays the parity oracle and the committed
    #: program fingerprints never move. Env: DGEN_TPU_CLUSTER.
    cluster_tariffs: bool = False
    #: background host-IO pipeline (io.hostio.HostPipeline): per-year
    #: result collection, RunExporter parquet writes and orbax
    #: checkpoint saves run on worker threads against one batched
    #: device fetch per year, so the driver keeps dispatching year
    #: steps back to back instead of serializing on every host
    #: consumer. None (default) = on unless the DGEN_TPU_ASYNC_IO env
    #: kill switch says 0; False restores the serialized per-year path
    #: (the bit-exact parity oracle); True forces it on. Applies to
    #: single- AND multi-process (jax.distributed) runs: each process's
    #: pipeline writes only its own addressable shard (parity proven
    #: byte-identical by tests/test_gang.py), so multi-process runs
    #: default on too — except ``collect=True`` there, which fetches
    #: full GLOBAL arrays and always serializes. debug runs
    #: (debug_invariants) and DGEN_TPU_PROFILE always serialize — they
    #: need per-year host sync regardless.
    async_host_io: Optional[bool] = None
    #: arm the steady-state retrace guard (lint.guard.RetraceGuard):
    #: once the first two executed years have compiled the
    #: first_year=True/False program pair, any FRESH XLA compile or
    #: jaxpr trace in a later year fails the run — retrace storms
    #: surface as errors at year 3, not as a 10x wall-time report
    guard_retrace: bool = False
    #: deterministic fault-injection spec (resilience.faults grammar,
    #: e.g. ``"ckpt_save@2;year_step@3:oom"``) — installed by the run
    #: supervisor / fault drills before the first attempt. None (the
    #: production value) injects nothing; plain Simulation.run ignores
    #: the field unless something installs the registry. Env:
    #: DGEN_TPU_FAULTS.
    faults: Optional[str] = None
    #: load-time bad-data validation (resilience.quarantine): host-side
    #: schema/range/finiteness/reference checks over the agent table,
    #: profile banks (incl. int8 quant sidecars) and tariff bank at
    #: Simulation construction; malformed rows are QUARANTINED (rewritten
    #: to inert padding, mask 0 — exact-zero contributions everywhere)
    #: with a reasoned report instead of poisoning their whole state.
    #: None (default) = on unless the DGEN_TPU_VALIDATE env kill switch
    #: says 0; clean inputs are untouched (object identity), so the
    #: default costs one host-side scan and changes nothing.
    validate_inputs: Optional[bool] = None
    #: always-on numerical-health sentinel (models.health): cheap fused
    #: on-device reductions per year (nonfinite counts + gross bound
    #: breaches on bills/NPV/market-share per leaf) riding the existing
    #: host-IO fetch — works under the async pipeline, unlike
    #: debug_invariants.  None (default) = on unless DGEN_TPU_SENTINEL
    #: says 0.  Breaches WARN by default; see ``sentinel_escalate``.
    health_sentinel: Optional[bool] = None
    #: escalate sentinel breaches as HealthBreachError instead of
    #: warning — the run supervisor's detect -> attribute -> quarantine
    #: -> resume loop rides this (run_supervised turns it on unless
    #: explicitly disabled).  None/False = warn only.
    sentinel_escalate: Optional[bool] = None
    #: stable agent ids to quarantine by fiat at Simulation construction
    #: (applied on top of validation findings) — the supervisor's
    #: sentinel escalation round-trips the attributed ids through here
    #: so the re-entered attempt re-runs with the offenders contained
    quarantine_ids: Optional[Tuple[int, ...]] = None
    seed: int = 0

    def __post_init__(self) -> None:
        _check(self.agent_pad_multiple >= 1, "bad pad multiple")
        _check(4 <= self.sizing_iters <= 64, "sizing_iters out of range")
        _check(self.agent_chunk is None or self.agent_chunk >= 0,
               "agent_chunk must be None (auto) or >= 0")
        _check(self.soft_tau > 0.0, "soft_tau must be > 0")
        if self.soft_boundaries:
            _check(
                not (self.daylight_compact or self.bf16_banks
                     or self.quant_banks or self.pack_once
                     or self.stream_segments),
                "soft_boundaries requires the plain f32 full-hour XLA "
                "path (no daylight_compact/bf16_banks/quant_banks/"
                "pack_once/stream_segments)",
            )
            _check(
                not self.cluster_tariffs,
                "soft_boundaries requires the plain f32 full-hour XLA "
                "path (no cluster_tariffs)",
            )
        if self.quarantine_ids is not None:
            _check(
                all(int(a) == a for a in self.quarantine_ids),
                "quarantine_ids must be integer agent ids",
            )

    @property
    def soft_tau_static(self) -> Optional[float]:
        """The static smoothing temperature the compiled programs key
        on: the float when ``soft_boundaries`` is set, else ``None``
        (the hard path — every kernel lowers its original bit-exact
        program)."""
        return float(self.soft_tau) if self.soft_boundaries else None

    @property
    def async_io_enabled(self) -> bool:
        """The resolved async host-IO decision: the explicit field when
        set, else on unless the ``DGEN_TPU_ASYNC_IO`` kill switch says
        0/false/off (read at run time, so an operator can flip an
        already-built config back to the serialized oracle)."""
        if self.async_host_io is not None:
            return self.async_host_io
        return os.environ.get("DGEN_TPU_ASYNC_IO", "") not in (
            "0", "false", "off"
        )

    @property
    def validate_enabled(self) -> bool:
        """The resolved load-time validation decision: the explicit
        field when set, else on unless the ``DGEN_TPU_VALIDATE`` kill
        switch says 0/false/off (read at construction time)."""
        if self.validate_inputs is not None:
            return self.validate_inputs
        return os.environ.get("DGEN_TPU_VALIDATE", "") not in (
            "0", "false", "off"
        )

    @property
    def sentinel_enabled(self) -> bool:
        """The resolved health-sentinel decision: the explicit field
        when set, else on unless ``DGEN_TPU_SENTINEL`` says
        0/false/off (read at run time, like the async-IO switch)."""
        if self.health_sentinel is not None:
            return self.health_sentinel
        return os.environ.get("DGEN_TPU_SENTINEL", "") not in (
            "0", "false", "off"
        )

    @classmethod
    def from_env(cls, **overrides) -> "RunConfig":
        if "n_devices" not in overrides and os.environ.get("DGEN_TPU_DEVICES"):
            overrides["n_devices"] = int(os.environ["DGEN_TPU_DEVICES"])
        if "agent_chunk" not in overrides and \
                os.environ.get("DGEN_TPU_AGENT_CHUNK"):
            overrides["agent_chunk"] = int(os.environ["DGEN_TPU_AGENT_CHUNK"])
        # "0"/"false" mean OFF (same convention as DGEN_TPU_TESTS)
        def flag(name: str) -> bool:
            return os.environ.get(name, "") not in ("", "0", "false")

        if "debug_invariants" not in overrides and flag("DGEN_TPU_DEBUG"):
            overrides["debug_invariants"] = True
        if "guard_retrace" not in overrides and flag("DGEN_TPU_GUARD"):
            overrides["guard_retrace"] = True
        if "daylight_compact" not in overrides and flag("DGEN_TPU_DAYLIGHT"):
            overrides["daylight_compact"] = True
        if "bf16_banks" not in overrides and flag("DGEN_TPU_BF16_BANKS"):
            overrides["bf16_banks"] = True
        if "quant_banks" not in overrides and flag("DGEN_TPU_QUANT_BANKS"):
            overrides["quant_banks"] = True
        if "pack_once" not in overrides and flag("DGEN_TPU_PACK_ONCE"):
            overrides["pack_once"] = True
        if "stream_segments" not in overrides and flag("DGEN_TPU_STREAM"):
            overrides["stream_segments"] = True
        if "soft_boundaries" not in overrides and flag("DGEN_TPU_SOFT"):
            overrides["soft_boundaries"] = True
        if "cluster_tariffs" not in overrides and flag("DGEN_TPU_CLUSTER"):
            overrides["cluster_tariffs"] = True
        if "soft_tau" not in overrides and \
                os.environ.get("DGEN_TPU_SOFT_TAU"):
            overrides["soft_tau"] = float(os.environ["DGEN_TPU_SOFT_TAU"])
        if "faults" not in overrides and os.environ.get("DGEN_TPU_FAULTS"):
            overrides["faults"] = os.environ["DGEN_TPU_FAULTS"].strip()
        # async_host_io deliberately NOT baked from the env here: the
        # field stays None so async_io_enabled re-reads the
        # DGEN_TPU_ASYNC_IO kill switch at run time — baking it would
        # freeze the value at config-build time and silently ignore an
        # operator flipping the switch on an already-built config
        return cls(**overrides)


def _is_pow2(n: int) -> bool:
    return n >= 1 and (n & (n - 1)) == 0


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Settings for the online what-if query engine
    (:mod:`dgen_tpu.serve`): the microbatcher's bucket/queue shape and
    the HTTP front-end. Every compile-relevant knob is a power of two
    so the set of program shapes a serving process can ever build is
    fixed up front (``log2(max_batch/min_bucket)+1`` bucket programs —
    RetraceGuard-clean steady state)."""

    #: largest microbatch (rows per device program); queries coalesce
    #: up to this many agent rows into one padded bucket
    max_batch: int = 64
    #: smallest padded bucket; single-agent queries compile/run at this
    #: width (1 = a dedicated single-shot program)
    min_bucket: int = 1
    #: deadline flush: a queued request waits at most this long for
    #: co-batching before its (possibly underfull) bucket dispatches
    max_wait_ms: float = 5.0
    #: admission control: submissions beyond this many queued requests
    #: are rejected with ``serve.QueueFullError`` instead of growing
    #: the queue (and the tail latency) without bound
    max_queue: int = 256
    #: HTTP front-end bind address (``python -m dgen_tpu.serve``);
    #: port 0 binds an ephemeral port (tests)
    host: str = "127.0.0.1"
    port: int = 8178
    #: compile every bucket program before accepting traffic, so no
    #: request ever pays a compile (RunConfig.guard_retrace then holds
    #: from the first query on)
    warmup: bool = True
    #: per-request deadline: a handler waits at most this long on the
    #: batcher future before answering 504 (a hung device program must
    #: cost one bounded request, never a wedged handler thread)
    request_timeout_s: float = 60.0
    #: per-connection socket timeout: a client that never finishes
    #: sending its body (or never reads its response) releases the
    #: handler thread after this long instead of holding it forever
    socket_timeout_s: float = 30.0
    #: precomputed answer-surface directory (serve.surface): when set,
    #: the engine mmaps the surface at boot (provenance-gated — a
    #: surface built under a different config_hash/git_sha/population
    #: is refused with a named reason) and answers zero-override
    #: queries for covered years engine-free.  None = engine path only.
    surface_dir: Optional[str] = None
    #: cross-replica exact result cache directory (serve.resultcache):
    #: when set, bucketed answers are cached in this shared directory
    #: keyed by (year, override key, bucket, rows, provenance) — every
    #: replica of a fleet points at the same directory.  None = off.
    result_cache_dir: Optional[str] = None
    #: result-cache entry bound (files); least-recently-used entries
    #: are evicted on store
    result_cache_entries: int = 512

    def __post_init__(self) -> None:
        _check(_is_pow2(self.max_batch), "max_batch must be a power of two")
        _check(_is_pow2(self.min_bucket) and self.min_bucket <= self.max_batch,
               "min_bucket must be a power of two <= max_batch")
        _check(self.max_wait_ms >= 0.0, "max_wait_ms must be >= 0")
        _check(self.max_queue >= 1, "max_queue must be >= 1")
        _check(0 <= self.port <= 65535, "port out of range")
        _check(self.request_timeout_s > 0.0, "request_timeout_s must be > 0")
        _check(self.socket_timeout_s > 0.0, "socket_timeout_s must be > 0")
        _check(self.result_cache_entries >= 1,
               "result_cache_entries must be >= 1")

    @property
    def buckets(self) -> Tuple[int, ...]:
        """The fixed compile shapes, ascending (powers of two from
        min_bucket to max_batch)."""
        out = []
        b = self.min_bucket
        while b <= self.max_batch:
            out.append(b)
            b *= 2
        return tuple(out)

    @classmethod
    def from_env(cls, **overrides) -> "ServeConfig":
        """Env switches, same conventions as :meth:`RunConfig.from_env`:
        DGEN_TPU_SERVE_MAX_BATCH, DGEN_TPU_SERVE_WAIT_MS,
        DGEN_TPU_SERVE_QUEUE, DGEN_TPU_SERVE_HOST, DGEN_TPU_SERVE_PORT,
        DGEN_TPU_SERVE_WARMUP (0/false = off),
        DGEN_TPU_SERVE_REQ_TIMEOUT_S, DGEN_TPU_SERVE_SOCK_TIMEOUT_S,
        DGEN_TPU_SERVE_SURFACE (answer-surface dir),
        DGEN_TPU_SERVE_CACHE_DIR / DGEN_TPU_SERVE_CACHE_ENTRIES
        (result cache)."""
        env = os.environ.get
        if "surface_dir" not in overrides and env("DGEN_TPU_SERVE_SURFACE"):
            overrides["surface_dir"] = env("DGEN_TPU_SERVE_SURFACE")
        if ("result_cache_dir" not in overrides
                and env("DGEN_TPU_SERVE_CACHE_DIR")):
            overrides["result_cache_dir"] = env("DGEN_TPU_SERVE_CACHE_DIR")
        if ("result_cache_entries" not in overrides
                and env("DGEN_TPU_SERVE_CACHE_ENTRIES")):
            overrides["result_cache_entries"] = int(
                env("DGEN_TPU_SERVE_CACHE_ENTRIES"))
        if "max_batch" not in overrides and env("DGEN_TPU_SERVE_MAX_BATCH"):
            overrides["max_batch"] = int(env("DGEN_TPU_SERVE_MAX_BATCH"))
        if "max_wait_ms" not in overrides and env("DGEN_TPU_SERVE_WAIT_MS"):
            overrides["max_wait_ms"] = float(env("DGEN_TPU_SERVE_WAIT_MS"))
        if "max_queue" not in overrides and env("DGEN_TPU_SERVE_QUEUE"):
            overrides["max_queue"] = int(env("DGEN_TPU_SERVE_QUEUE"))
        if "host" not in overrides and env("DGEN_TPU_SERVE_HOST"):
            overrides["host"] = env("DGEN_TPU_SERVE_HOST")
        if "port" not in overrides and env("DGEN_TPU_SERVE_PORT"):
            overrides["port"] = int(env("DGEN_TPU_SERVE_PORT"))
        if "warmup" not in overrides and env("DGEN_TPU_SERVE_WARMUP"):
            overrides["warmup"] = env("DGEN_TPU_SERVE_WARMUP") not in (
                "0", "false", "off"
            )
        if ("request_timeout_s" not in overrides
                and env("DGEN_TPU_SERVE_REQ_TIMEOUT_S")):
            overrides["request_timeout_s"] = float(
                env("DGEN_TPU_SERVE_REQ_TIMEOUT_S"))
        if ("socket_timeout_s" not in overrides
                and env("DGEN_TPU_SERVE_SOCK_TIMEOUT_S")):
            overrides["socket_timeout_s"] = float(
                env("DGEN_TPU_SERVE_SOCK_TIMEOUT_S"))
        return cls(**overrides)


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Settings for the multi-replica serving fleet
    (:mod:`dgen_tpu.serve.fleet` / :mod:`dgen_tpu.serve.front`): how
    many replicas, when a replica counts as routable, how the front's
    per-replica circuit breakers trip and recover, when the fleet sheds
    load, and how a drain is bounded.  Env prefix: ``DGEN_TPU_FLEET_*``
    (:meth:`from_env`)."""

    #: replica processes the supervisor keeps alive
    n_replicas: int = 2
    #: front bind address (0 = ephemeral, for tests/drills)
    host: str = "127.0.0.1"
    port: int = 8177
    #: a freshly spawned replica must reach READY (portfile written AND
    #: /readyz green) within this wall, or it is killed and counted as
    #: a death
    boot_timeout_s: float = 180.0
    #: supervisor monitor cadence (liveness polls, restart scheduling)
    poll_interval_s: float = 0.2
    #: crash-loop circuit breaker: more than this many deaths inside
    #: ``restart_window_s`` marks the replica FAILED (no more restarts
    #: — a crash loop burns CPU and log space, never heals itself)
    max_restarts: int = 5
    restart_window_s: float = 120.0
    #: front per-replica breaker: consecutive forward failures/timeouts
    #: that OPEN the breaker, and how long it stays open before one
    #: HALF_OPEN probe request is allowed through
    breaker_failures: int = 3
    breaker_cooldown_s: float = 1.0
    #: front -> replica forward deadline (connect + response); a hung
    #: replica costs one timeout, then the breaker takes it out
    request_timeout_s: float = 30.0
    #: shed when aggregate READY-replica queue depth exceeds this
    #: fraction of aggregate queue capacity (sum of max_queue)
    shed_queue_frac: float = 0.8
    #: Retry-After seconds stamped on every fleet 503 (shed, drain,
    #: no-replica) — the client's bounded-retry contract
    retry_after_s: float = 1.0
    #: fleet /metricz scrape cadence (the load-shed signal's freshness)
    metricz_interval_s: float = 0.5
    #: graceful drain bound: in-flight requests get this long to finish
    #: after SIGTERM before the process exits anyway
    drain_timeout_s: float = 30.0
    #: occupancy-driven autoscaling (serve.autoscale.Autoscaler): scale
    #: the fleet between min_replicas and max_replicas from the
    #: aggregated /metricz pressure signal instead of holding
    #: n_replicas fixed.  Off by default — the PR 9 fixed-fleet
    #: behavior is unchanged until an operator opts in.
    autoscale: bool = False
    #: autoscale bounds (n_replicas is the BOOT size and must sit
    #: inside them when autoscaling is on)
    min_replicas: int = 1
    max_replicas: int = 4
    #: scale-up trigger: aggregate queue depth >= this fraction of
    #: aggregate queue capacity, OR mean batch occupancy >= the
    #: occupancy threshold, sustained for scale_up_sustain_s
    scale_up_queue_frac: float = 0.25
    scale_up_occupancy: float = 0.75
    scale_up_sustain_s: float = 2.0
    #: scale-down trigger: queue empty below this fraction AND batch
    #: occupancy below the occupancy bound, sustained for
    #: scale_down_sustain_s (hysteresis: the down thresholds must sit
    #: strictly below the up thresholds or the fleet oscillates)
    scale_down_queue_frac: float = 0.02
    scale_down_occupancy: float = 0.25
    scale_down_sustain_s: float = 10.0
    #: minimum wall between ANY two scale actions (a freshly added
    #: replica needs time to go READY and absorb load before the
    #: signal is trusted again)
    scale_cooldown_s: float = 5.0
    #: autoscaler decision cadence
    scale_interval_s: float = 0.5

    def __post_init__(self) -> None:
        _check(self.n_replicas >= 1, "n_replicas must be >= 1")
        _check(self.min_replicas >= 1, "min_replicas must be >= 1")
        _check(self.max_replicas >= self.min_replicas,
               "max_replicas must be >= min_replicas")
        if self.autoscale:
            _check(
                self.min_replicas <= self.n_replicas <= self.max_replicas,
                "with autoscale on, n_replicas (the boot size) must lie "
                "within [min_replicas, max_replicas]",
            )
        _check(0.0 < self.scale_up_queue_frac <= 1.0,
               "scale_up_queue_frac must be in (0, 1]")
        _check(0.0 <= self.scale_down_queue_frac
               < self.scale_up_queue_frac,
               "scale_down_queue_frac must be < scale_up_queue_frac "
               "(hysteresis)")
        _check(0.0 < self.scale_up_occupancy <= 1.0,
               "scale_up_occupancy must be in (0, 1]")
        _check(0.0 <= self.scale_down_occupancy < self.scale_up_occupancy,
               "scale_down_occupancy must be < scale_up_occupancy "
               "(hysteresis)")
        _check(self.scale_up_sustain_s >= 0,
               "scale_up_sustain_s must be >= 0")
        _check(self.scale_down_sustain_s >= 0,
               "scale_down_sustain_s must be >= 0")
        _check(self.scale_cooldown_s >= 0, "scale_cooldown_s must be >= 0")
        _check(self.scale_interval_s > 0, "scale_interval_s must be > 0")
        _check(0 <= self.port <= 65535, "port out of range")
        _check(self.boot_timeout_s > 0, "boot_timeout_s must be > 0")
        _check(self.poll_interval_s > 0, "poll_interval_s must be > 0")
        _check(self.max_restarts >= 0, "max_restarts must be >= 0")
        _check(self.restart_window_s > 0, "restart_window_s must be > 0")
        _check(self.breaker_failures >= 1, "breaker_failures must be >= 1")
        _check(self.breaker_cooldown_s >= 0,
               "breaker_cooldown_s must be >= 0")
        _check(self.request_timeout_s > 0, "request_timeout_s must be > 0")
        _check(0.0 < self.shed_queue_frac <= 1.0,
               "shed_queue_frac must be in (0, 1]")
        _check(self.retry_after_s >= 0, "retry_after_s must be >= 0")
        _check(self.metricz_interval_s > 0, "metricz_interval_s must be > 0")
        _check(self.drain_timeout_s > 0, "drain_timeout_s must be > 0")

    @classmethod
    def from_env(cls, **overrides) -> "FleetConfig":
        """Env switches: DGEN_TPU_FLEET_REPLICAS, DGEN_TPU_FLEET_HOST,
        DGEN_TPU_FLEET_PORT, DGEN_TPU_FLEET_BOOT_TIMEOUT_S,
        DGEN_TPU_FLEET_MAX_RESTARTS, DGEN_TPU_FLEET_BREAKER_FAILURES,
        DGEN_TPU_FLEET_BREAKER_COOLDOWN_S,
        DGEN_TPU_FLEET_REQ_TIMEOUT_S, DGEN_TPU_FLEET_SHED_FRAC,
        DGEN_TPU_FLEET_RETRY_AFTER_S, DGEN_TPU_FLEET_DRAIN_TIMEOUT_S,
        DGEN_TPU_FLEET_AUTOSCALE (1/true = on),
        DGEN_TPU_FLEET_MIN_REPLICAS, DGEN_TPU_FLEET_MAX_REPLICAS,
        DGEN_TPU_FLEET_SCALE_UP_QUEUE_FRAC,
        DGEN_TPU_FLEET_SCALE_UP_SUSTAIN_S,
        DGEN_TPU_FLEET_SCALE_DOWN_SUSTAIN_S,
        DGEN_TPU_FLEET_SCALE_COOLDOWN_S."""
        env = os.environ.get
        if "autoscale" not in overrides and env("DGEN_TPU_FLEET_AUTOSCALE"):
            overrides["autoscale"] = env(
                "DGEN_TPU_FLEET_AUTOSCALE") not in ("0", "false", "off")
        for key, envname, conv in (
            ("n_replicas", "DGEN_TPU_FLEET_REPLICAS", int),
            ("host", "DGEN_TPU_FLEET_HOST", str),
            ("port", "DGEN_TPU_FLEET_PORT", int),
            ("boot_timeout_s", "DGEN_TPU_FLEET_BOOT_TIMEOUT_S", float),
            ("max_restarts", "DGEN_TPU_FLEET_MAX_RESTARTS", int),
            ("breaker_failures", "DGEN_TPU_FLEET_BREAKER_FAILURES", int),
            ("breaker_cooldown_s",
             "DGEN_TPU_FLEET_BREAKER_COOLDOWN_S", float),
            ("request_timeout_s", "DGEN_TPU_FLEET_REQ_TIMEOUT_S", float),
            ("shed_queue_frac", "DGEN_TPU_FLEET_SHED_FRAC", float),
            ("retry_after_s", "DGEN_TPU_FLEET_RETRY_AFTER_S", float),
            ("drain_timeout_s", "DGEN_TPU_FLEET_DRAIN_TIMEOUT_S", float),
            ("min_replicas", "DGEN_TPU_FLEET_MIN_REPLICAS", int),
            ("max_replicas", "DGEN_TPU_FLEET_MAX_REPLICAS", int),
            ("scale_up_queue_frac",
             "DGEN_TPU_FLEET_SCALE_UP_QUEUE_FRAC", float),
            ("scale_up_sustain_s",
             "DGEN_TPU_FLEET_SCALE_UP_SUSTAIN_S", float),
            ("scale_down_sustain_s",
             "DGEN_TPU_FLEET_SCALE_DOWN_SUSTAIN_S", float),
            ("scale_cooldown_s",
             "DGEN_TPU_FLEET_SCALE_COOLDOWN_S", float),
        ):
            if key not in overrides and env(envname):
                overrides[key] = conv(env(envname))
        return cls(**overrides)


@dataclasses.dataclass(frozen=True)
class GangConfig:
    """Settings for the multi-process gang supervisor
    (:mod:`dgen_tpu.resilience.gang`): how many worker processes a
    simulation gang runs at, when a worker counts as stalled, how many
    whole-gang restarts the crash-loop breaker allows, and the elastic
    shrink plan a permanently-lost host falls back to.  Env prefix:
    ``DGEN_TPU_GANG_*`` (:meth:`from_env`).

    Unlike the serving fleet (independent replicas), a jax.distributed
    gang is all-or-nothing: one dead or stalled worker poisons every
    collective, so recovery is always tear-down-and-relaunch of the
    WHOLE gang from the manifest frontier."""

    #: worker processes in the gang (``DGEN_NUM_PROCESSES``)
    n_processes: int = 2
    #: accelerator devices per worker.  ``total_devices`` (when set)
    #: overrides this per launch so an elastic shrink keeps the GLOBAL
    #: mesh size constant on CPU (4 procs x 1 dev -> 2 procs x 2 dev):
    #: the same compiled program, bit-identical resumes.  On real TPU
    #: hardware the per-host device count is fixed and a shrink lowers
    #: the global device count instead.
    devices_per_process: int = 1
    total_devices: Optional[int] = None
    #: jax platform pinned into each worker ("" = inherit; CPU gangs
    #: are the test/drill shape, the multi-host TPU path sets "")
    platform: str = "cpu"
    #: a freshly spawned gang must produce its first per-year heartbeat
    #: (worker boot + distributed bring-up + first-year compile) within
    #: this wall, or the gang is torn down and counted as a death
    boot_timeout_s: float = 600.0
    #: once a worker has heartbeat at least one completed year, a
    #: heartbeat older than this marks the worker STALLED (wedged
    #: device, paging storm) — the gang is torn down and relaunched.
    #: This is a FLOOR: the supervisor scales the live bound to
    #: GangSupervisor.STALL_GRACE_FACTOR x the slowest observed
    #: year-over-year heartbeat gap, so gangs whose steady-state years
    #: are simply long are not killed as stalled
    stall_timeout_s: float = 120.0
    #: supervisor monitor cadence
    poll_interval_s: float = 0.2
    #: crash-loop breaker: more than this many gang deaths inside
    #: ``restart_window_s`` stops restarts at the current process count
    #: (the shrink plan, if any, then takes over)
    max_restarts: int = 3
    restart_window_s: float = 600.0
    #: elastic fallback: process counts to drop to, in order, when the
    #: crash-loop breaker trips — the run resumes from the manifest
    #: frontier at P' workers instead of dying (empty = fail instead)
    shrink_plan: Tuple[int, ...] = ()
    #: SIGTERM drain bound: workers get this long to agree on a save
    #: year (the synchronized emergency-checkpoint barrier) and exit
    #: before the supervisor kills them
    drain_timeout_s: float = 60.0
    #: coordinator bind host (workers are children of this process)
    coordinator_host: str = "127.0.0.1"

    def __post_init__(self) -> None:
        _check(self.n_processes >= 1, "n_processes must be >= 1")
        _check(self.devices_per_process >= 1,
               "devices_per_process must be >= 1")
        _check(self.total_devices is None or self.total_devices >= 1,
               "total_devices must be None or >= 1")
        _check(self.boot_timeout_s > 0, "boot_timeout_s must be > 0")
        _check(self.stall_timeout_s > 0, "stall_timeout_s must be > 0")
        _check(self.poll_interval_s > 0, "poll_interval_s must be > 0")
        _check(self.max_restarts >= 0, "max_restarts must be >= 0")
        _check(self.restart_window_s > 0, "restart_window_s must be > 0")
        plan = self.shrink_plan
        _check(
            all(1 <= p < self.n_processes for p in plan)
            and all(a > b for a, b in zip(plan, plan[1:])),
            "shrink_plan must be strictly decreasing process counts "
            "below n_processes",
        )
        if self.total_devices is not None:
            # a plan entry that does not divide total_devices would
            # silently fall back to devices_per_process and change the
            # GLOBAL mesh size mid-run — the invariant the elastic
            # resume's same-program expectations ride; fail at
            # construction, not at the relaunch that needed it
            _check(
                all(self.total_devices % p == 0
                    for p in (self.n_processes, *plan)),
                "total_devices must divide evenly at n_processes and "
                "every shrink_plan entry (the global mesh size must "
                "stay constant through an elastic shrink)",
            )
        _check(self.drain_timeout_s > 0, "drain_timeout_s must be > 0")

    def devices_for(self, n_processes: int) -> int:
        """Per-worker device count for a launch at ``n_processes``:
        ``total_devices`` split evenly when set and divisible, else
        ``devices_per_process``."""
        total = self.total_devices
        if total is not None and total % n_processes == 0:
            return total // n_processes
        return self.devices_per_process

    @classmethod
    def from_env(cls, **overrides) -> "GangConfig":
        """Env switches: DGEN_TPU_GANG_PROCESSES,
        DGEN_TPU_GANG_DEVICES_PER_PROCESS, DGEN_TPU_GANG_TOTAL_DEVICES,
        DGEN_TPU_GANG_PLATFORM, DGEN_TPU_GANG_BOOT_TIMEOUT_S,
        DGEN_TPU_GANG_STALL_TIMEOUT_S, DGEN_TPU_GANG_MAX_RESTARTS,
        DGEN_TPU_GANG_SHRINK_PLAN (comma list, e.g. "2,1"),
        DGEN_TPU_GANG_DRAIN_TIMEOUT_S."""
        env = os.environ.get
        for key, envname, conv in (
            ("n_processes", "DGEN_TPU_GANG_PROCESSES", int),
            ("devices_per_process",
             "DGEN_TPU_GANG_DEVICES_PER_PROCESS", int),
            ("total_devices", "DGEN_TPU_GANG_TOTAL_DEVICES", int),
            ("platform", "DGEN_TPU_GANG_PLATFORM", str),
            ("boot_timeout_s", "DGEN_TPU_GANG_BOOT_TIMEOUT_S", float),
            ("stall_timeout_s", "DGEN_TPU_GANG_STALL_TIMEOUT_S", float),
            ("max_restarts", "DGEN_TPU_GANG_MAX_RESTARTS", int),
            ("drain_timeout_s", "DGEN_TPU_GANG_DRAIN_TIMEOUT_S", float),
        ):
            if key not in overrides and env(envname):
                overrides[key] = conv(env(envname))
        if "shrink_plan" not in overrides and env("DGEN_TPU_GANG_SHRINK_PLAN"):
            overrides["shrink_plan"] = tuple(
                int(p) for p in
                env("DGEN_TPU_GANG_SHRINK_PLAN").split(",") if p.strip()
            )
        return cls(**overrides)
