"""dgenlint core: module indexing, jit-reachability, suppression.

The rules in :mod:`dgen_tpu.lint.rules` need to know which functions
can execute *inside an XLA trace* — a host sync (``.item()``,
``np.asarray``) is an anti-pattern only there, while the same call in
the tariff compiler or the run driver is correct host code. This module
builds that context once per lint run:

  * every ``.py`` file is parsed into a :class:`ModuleInfo` (functions,
    import aliases, per-line suppressions, resolved module name);
  * jit ROOTS are functions decorated with ``jax.jit`` (bare, called,
    or via ``partial(jax.jit, ...)``) plus module-level
    ``f = jax.jit(g)`` wrappings;
  * a cross-module call graph is built from dotted call targets and
    bare function references passed as arguments (covers ``lax.scan``
    bodies, ``vmap`` targets, ``pallas_call`` kernels and ``partial``
    closures), and reachability is the BFS closure from the roots.
    Nested functions of a reachable function are reachable.

The call graph is an over-approximation (a function *referenced* from
jitted code counts as jit-reachable) — for a linter that errs on the
strict side, which is the useful direction.

Suppression: append ``# dgenlint: disable=L1`` (comma-separate several
rule ids, or ``all``) to the flagged line; a file-wide opt-out is
``# dgenlint: disable-file=L3`` on its own line.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

_SUPPRESS_RE = re.compile(r"#\s*dgenlint:\s*disable=([A-Za-z0-9_,\s]+)")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*dgenlint:\s*disable-file=([A-Za-z0-9_,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding, printable as ``path:line: RULE message``."""

    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _is_jit_expr(node: ast.AST) -> bool:
    d = dotted(node)
    return d is not None and (d == "jit" or d.endswith(".jit"))


def is_jit_decorator(dec: ast.AST) -> bool:
    """``@jax.jit``, ``@jax.jit(...)``, ``@partial(jax.jit, ...)``."""
    if _is_jit_expr(dec):
        return True
    if isinstance(dec, ast.Call):
        if _is_jit_expr(dec.func):
            return True
        d = dotted(dec.func)
        if d in ("partial", "functools.partial") and dec.args:
            return _is_jit_expr(dec.args[0])
    return False


def jit_decorator_call(func: ast.AST) -> Optional[ast.Call]:
    """The Call form of a jit decorator (None for bare ``@jax.jit``)."""
    for dec in getattr(func, "decorator_list", ()):
        if isinstance(dec, ast.Call) and is_jit_decorator(dec):
            return dec
    return None


@dataclasses.dataclass
class FuncInfo:
    """One function (or method / nested function) definition."""

    qualname: str                  # "year_step", "Cls.meth", "f.inner"
    node: ast.AST                  # FunctionDef | AsyncFunctionDef
    module: "ModuleInfo"
    parent: Optional["FuncInfo"]
    class_name: Optional[str]      # enclosing class, for self.* edges
    is_jit_root: bool = False
    calls: Set[str] = dataclasses.field(default_factory=set)

    @property
    def fullname(self) -> str:
        return f"{self.module.modname}.{self.qualname}"


class ModuleInfo:
    """Parsed view of one source file."""

    def __init__(self, path: str, modname: str, source: str) -> None:
        self.path = path
        self.modname = modname
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        self.is_package = os.path.basename(path) == "__init__.py"
        self.imports: Dict[str, str] = {}      # alias -> dotted target
        self.import_nodes: List[Tuple[int, str]] = []  # (line, module)
        self.functions: List[FuncInfo] = []
        self.constants: Dict[str, int] = {}    # module-level int consts
        self.suppressed: Dict[int, Set[str]] = {}
        self.file_suppressed: Set[str] = set()
        self._scan_suppressions()
        _Indexer(self).visit(self.tree)
        self._fold_constants()

    # -- suppressions ---------------------------------------------------
    def _scan_suppressions(self) -> None:
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                self.suppressed.setdefault(i, set()).update(rules)
            m = _SUPPRESS_FILE_RE.search(line)
            if m:
                self.file_suppressed.update(
                    r.strip() for r in m.group(1).split(",") if r.strip()
                )

    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppressed or "all" in self.file_suppressed:
            return True
        at = self.suppressed.get(line, ())
        return rule in at or "all" in at

    # -- tiny constant folder (for Pallas block shapes) -----------------
    def _fold_constants(self) -> None:
        for node in self.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                val = self.const_value(node.value)
                if val is not None:
                    self.constants[node.targets[0].id] = val

    def const_value(self, node: ast.AST) -> Optional[int]:
        """Evaluate int constants / module constant names / + - * //."""
        if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                and not isinstance(node.value, bool):
            return node.value
        if isinstance(node, ast.Name):
            return self.constants.get(node.id)
        if isinstance(node, ast.BinOp):
            left = self.const_value(node.left)
            right = self.const_value(node.right)
            if left is None or right is None:
                return None
            if isinstance(node.op, ast.Add):
                return left + right
            if isinstance(node.op, ast.Sub):
                return left - right
            if isinstance(node.op, ast.Mult):
                return left * right
            if isinstance(node.op, ast.FloorDiv) and right != 0:
                return left // right
        return None


class _Indexer(ast.NodeVisitor):
    """One pass: imports, functions, jit roots, call edges."""

    def __init__(self, module: ModuleInfo) -> None:
        self.m = module
        self.func_stack: List[FuncInfo] = []
        self.class_stack: List[str] = []

    # -- imports --------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.m.imports[a.asname or a.name.split(".")[0]] = (
                a.name if a.asname else a.name.split(".")[0]
            )
            self.m.import_nodes.append((node.lineno, a.name))
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        base = node.module or ""
        if node.level:  # relative: resolve against this module's package
            # a package __init__'s own package IS its modname; a plain
            # module's package drops the final segment first
            drop = node.level - 1 if self.m.is_package else node.level
            pkg_parts = self.m.modname.split(".")
            if drop:
                pkg_parts = pkg_parts[:-drop]
            base = ".".join(pkg_parts + ([node.module] if node.module else []))
        for a in node.names:
            if a.name == "*":
                continue
            self.m.imports[a.asname or a.name] = f"{base}.{a.name}"
            self.m.import_nodes.append((node.lineno, f"{base}.{a.name}"))
        self.generic_visit(node)

    # -- scopes ---------------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def _visit_func(self, node) -> None:
        parent = self.func_stack[-1] if self.func_stack else None
        prefix = (
            f"{parent.qualname}." if parent
            else (f"{self.class_stack[-1]}." if self.class_stack else "")
        )
        info = FuncInfo(
            qualname=f"{prefix}{node.name}",
            node=node,
            module=self.m,
            parent=parent,
            class_name=self.class_stack[-1] if self.class_stack else None,
            is_jit_root=any(
                is_jit_decorator(d) for d in node.decorator_list
            ),
        )
        self.m.functions.append(info)
        self.func_stack.append(info)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- call edges -----------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        if self.func_stack:
            owner = self.func_stack[-1]
            d = dotted(node.func)
            if d:
                owner.calls.add(d)
            # bare function references passed as arguments: scan/vmap
            # bodies, pallas kernels, partial closures
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                ref = dotted(arg)
                if ref:
                    owner.calls.add(ref)
        else:
            # module level: f = jax.jit(g) marks g as a root
            if _is_jit_expr(node.func) and node.args:
                ref = dotted(node.args[0])
                if ref:
                    for fn in self.m.functions:
                        if fn.qualname == ref:
                            fn.is_jit_root = True
        self.generic_visit(node)


class ProjectIndex:
    """All modules plus the jit-reachability closure."""

    def __init__(self, modules: Iterable[ModuleInfo]) -> None:
        self.modules: List[ModuleInfo] = list(modules)
        self.functions: Dict[str, FuncInfo] = {}
        for m in self.modules:
            for fn in m.functions:
                self.functions[fn.fullname] = fn
        self.reachable: Set[str] = set()
        self._close_reachability()

    # -- edge resolution ------------------------------------------------
    def _resolve(self, caller: FuncInfo, target: str) -> List[FuncInfo]:
        m = caller.module
        head, _, rest = target.partition(".")
        out: List[str] = []
        if head == "self" and caller.class_name and rest:
            out.append(f"{m.modname}.{caller.class_name}.{rest}")
        elif head in m.imports:
            base = m.imports[head]
            out.append(f"{base}.{rest}" if rest else base)
        elif not rest:
            # bare name: sibling module function (any nesting level) or
            # a local function in an enclosing scope
            out.append(f"{m.modname}.{target}")
            scope = caller
            while scope is not None:
                out.append(f"{m.modname}.{scope.qualname}.{target}")
                scope = scope.parent
        return [self.functions[n] for n in out if n in self.functions]

    def _close_reachability(self) -> None:
        work = [fn for fn in self.functions.values() if fn.is_jit_root]
        while work:
            fn = work.pop()
            if fn.fullname in self.reachable:
                continue
            self.reachable.add(fn.fullname)
            # nested defs run inside the same trace
            prefix = fn.qualname + "."
            for other in fn.module.functions:
                if other.qualname.startswith(prefix):
                    work.append(other)
            for target in fn.calls:
                work.extend(self._resolve(fn, target))

    def is_reachable(self, fn: FuncInfo) -> bool:
        return fn.fullname in self.reachable

    def reachable_in(self, module: ModuleInfo) -> List[FuncInfo]:
        return [fn for fn in module.functions if self.is_reachable(fn)]


def walk_own_body(fn: FuncInfo) -> Iterable[ast.AST]:
    """Walk a function's body without descending into nested function
    or class definitions (those have their own FuncInfo); lambdas are
    walked as part of the enclosing function."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn.node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def module_name_for(path: str) -> str:
    """Dotted module name, walking up while ``__init__.py`` exists."""
    path = os.path.abspath(path)
    stem = os.path.splitext(os.path.basename(path))[0]
    parts = [] if stem == "__init__" else [stem]
    d = os.path.dirname(path)
    while os.path.exists(os.path.join(d, "__init__.py")):
        parts.insert(0, os.path.basename(d))
        d = os.path.dirname(d)
    return ".".join(parts) if parts else stem


def parse_file(path: str) -> ModuleInfo:
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    return ModuleInfo(path, module_name_for(path), src)


def parse_source(src: str, filename: str = "<snippet>",
                 modname: str = "snippet") -> ModuleInfo:
    return ModuleInfo(filename, modname, src)
