"""Concurrency-tier rule ids and one-line summaries.

Split out of :mod:`dgen_tpu.lint.conc` for the same reason
:mod:`dgen_tpu.lint.prog_ids` exists for the J rules: ``--list-rules``
must print every tier's id table without importing any tier's
implementation.  (The conc tier is jax-free anyway, but the id table
staying dependency-free is the invariant worth keeping uniform.)
:mod:`dgen_tpu.lint.conc.crules` builds its registry from this table so
the two cannot drift.
"""

from __future__ import annotations

from typing import Dict

CONC_RULE_SUMMARIES: Dict[str, str] = {
    "C1": "cross-thread write to self.* state without the class lock",
    "C2": "blocking call (sleep/HTTP/subprocess/join/queue) under a lock",
    "C3": "lock-acquisition order cycle / non-reentrant re-acquire",
    "C4": "non-atomic check-then-act on a shared container outside a lock",
    "C5": "unsafe lazy-init / broken double-checked locking",
    "C6": "thread started without an owner (no daemon=, no join)",
}
