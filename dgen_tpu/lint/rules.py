"""dgenlint rules L1-L12: JAX/TPU anti-patterns for the dgen-tpu stack.

Every rule is a generator ``rule(module, index) -> (line, message)``;
:func:`run_rules` applies suppressions and wraps results in
:class:`~dgen_tpu.lint.core.Finding`. The rule ids, what they catch and
why each matters on TPU are documented operator-facing in
``docs/lint.md`` — keep the two in sync.

Scope notes:

  * L1/L2/L4/L8 only fire inside jit-REACHABLE functions (see
    core.ProjectIndex): the same ``np.asarray`` that silently syncs a
    traced value is correct in the host-side tariff compiler.
  * ``int()`` is deliberately NOT a host-sync trigger: trace-time shape
    arithmetic (``int(mesh.devices.size)``) is pervasive and legal.
  * L5/L6/L7 are structural and fire anywhere in the file.
  * L9 is the inverse scope: a HOST-driver rule (per-year run loops),
    with the async pipeline module itself exempt — its fetch stage is
    where the device_get belongs.
  * L10 is a host-side SERVING rule: it fires in request-handling
    functions (name/class heuristic), anywhere in the repo.
  * L11 is a host-side ARTIFACT rule: write-mode opens and frame
    writers are fine inside (or handed to) the temp+rename helpers
    (resilience.atomic), flagged everywhere else.
  * L12 is a host-side SERVING rule like L10 (request-path heuristic,
    anywhere in the repo): per-request growth of a ``self`` container
    with no eviction evidence in the class.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

from dgen_tpu.lint.core import (
    Finding,
    FuncInfo,
    ModuleInfo,
    ProjectIndex,
    dotted,
    jit_decorator_call,
    is_jit_decorator,
    walk_own_body,
)

RuleHit = Tuple[int, str]

_JNP = "jax.numpy."
_NP = "numpy."

#: jnp constructors whose shape argument must be trace-static
_SHAPE_CTORS = {
    "zeros": (0,), "ones": (0,), "empty": (0,), "full": (0,),
    "arange": (0, 1, 2), "linspace": (0, 1, 2), "eye": (0, 1),
}

#: reductions whose result is a traced scalar/array — a shape built
#: from one of these is data-dependent
_REDUCTION_METHODS = {
    "sum", "max", "min", "prod", "mean", "count_nonzero", "item",
    "argmax", "argmin", "nonzero",
}


def _resolve(m: ModuleInfo, d: Optional[str]) -> Optional[str]:
    """Expand the leading import alias of a dotted name
    (``np.asarray`` -> ``numpy.asarray``)."""
    if d is None:
        return None
    head, _, rest = d.partition(".")
    base = m.imports.get(head)
    if base is None:
        return d
    return f"{base}.{rest}" if rest else base


def _reachable_nodes(
    m: ModuleInfo, index: ProjectIndex
) -> Iterator[Tuple[FuncInfo, ast.AST]]:
    for fn in index.reachable_in(m):
        for node in walk_own_body(fn):
            yield fn, node


# ---------------------------------------------------------------------------
# L1 — host syncs on traced values
# ---------------------------------------------------------------------------

_L1_CALLS = {
    "numpy.asarray", "numpy.array", "numpy.any", "numpy.all",
    "jax.device_get",
}


def rule_l1(m: ModuleInfo, index: ProjectIndex) -> Iterable[RuleHit]:
    """Host-sync calls in jit-reachable code: ``float()/bool()`` on
    non-literals, ``.item()/.tolist()``, ``np.asarray/np.array``,
    ``jax.device_get``."""
    for _fn, node in _reachable_nodes(m, index):
        if not isinstance(node, ast.Call):
            continue
        r = _resolve(m, dotted(node.func))
        if r in _L1_CALLS:
            yield node.lineno, (
                f"`{dotted(node.func)}` in jit-reachable code forces a "
                "device sync / host round-trip on traced values"
            )
            continue
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in ("float", "bool")
            and len(node.args) == 1
            and not isinstance(node.args[0], ast.Constant)
        ):
            yield node.lineno, (
                f"`{node.func.id}()` on a non-literal in jit-reachable "
                "code blocks on the device value (ConcretizationTypeError "
                "under trace, silent sync outside)"
            )
            continue
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in ("item", "tolist")
            and not node.args
        ):
            yield node.lineno, (
                f"`.{node.func.attr}()` in jit-reachable code transfers "
                "device values to host"
            )


# ---------------------------------------------------------------------------
# L2 — Python control flow on array values
# ---------------------------------------------------------------------------

def _arrayish_test(m: ModuleInfo, expr: ast.AST) -> Optional[ast.AST]:
    """A subexpression that evaluates to a traced array in boolean
    position: jnp/lax calls, ``.any()``/``.all()`` method calls."""
    for n in ast.walk(expr):
        if not isinstance(n, ast.Call):
            continue
        r = _resolve(m, dotted(n.func))
        if r and (r.startswith(_JNP) or r.startswith("jax.lax.")):
            return n
        if (
            isinstance(n.func, ast.Attribute)
            and n.func.attr in ("any", "all")
            and not n.args
        ):
            return n
    return None


def rule_l2(m: ModuleInfo, index: ProjectIndex) -> Iterable[RuleHit]:
    """``if``/``while``/``assert`` on array values in jit-reachable
    code — needs ``lax.cond``/``lax.select``/``jnp.where``."""
    for _fn, node in _reachable_nodes(m, index):
        if isinstance(node, (ast.If, ast.While)):
            hit = _arrayish_test(m, node.test)
            kind = "if" if isinstance(node, ast.If) else "while"
            if hit is not None:
                yield node.lineno, (
                    f"Python `{kind}` on an array value retraces or "
                    "fails under jit; use lax.cond/lax.select/jnp.where"
                )
        elif isinstance(node, ast.Assert):
            hit = _arrayish_test(m, node.test)
            if hit is not None:
                yield node.lineno, (
                    "`assert` on an array value syncs (or breaks) under "
                    "jit; use checkify or a host-side invariant check"
                )


# ---------------------------------------------------------------------------
# L3 — dtype hygiene (float64 must not reach the device)
# ---------------------------------------------------------------------------

_F64 = ("numpy.float64", "jax.numpy.float64")


def _is_f64_expr(m: ModuleInfo, node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and node.value == "float64":
        return True
    if isinstance(node, ast.Name) and node.id == "float":
        return True  # python float == f64 as a dtype
    return _resolve(m, dotted(node)) in _F64


def rule_l3(m: ModuleInfo, index: ProjectIndex) -> Iterable[RuleHit]:
    """float64 in the device path: any f64 mention in jit-reachable
    code, or an explicit f64 dtype on a jnp array constructor anywhere
    (doubles the HBM agent-table footprint and falls off the VPU fast
    path)."""
    reported = set()   # lines already flagged (one finding per line)
    for _fn, node in _reachable_nodes(m, index):
        if _is_f64_expr(m, node) and not isinstance(node, ast.Name):
            if node.lineno not in reported:
                reported.add(node.lineno)
                yield node.lineno, (
                    "float64 in jit-reachable code widens traced values "
                    "(f64 is unsupported/slow on TPU; keep the device "
                    "path f32)"
                )
        elif isinstance(node, ast.keyword) and node.arg == "dtype" \
                and _is_f64_expr(m, node.value):
            if node.value.lineno not in reported:
                reported.add(node.value.lineno)
                spelled = (
                    "python `float` as a dtype means f64"
                    if isinstance(node.value, ast.Name)
                    else "keep the device path f32"
                )
                yield node.value.lineno, (
                    f"dtype=float64 in jit-reachable code ({spelled})"
                )
    # anywhere: an explicitly-f64 jnp array is f64 *on device*
    for node in ast.walk(m.tree):
        if not isinstance(node, ast.Call):
            continue
        r = _resolve(m, dotted(node.func))
        if not (r and r.startswith(_JNP)):
            continue
        for kw in node.keywords:
            if (
                kw.arg == "dtype" and _is_f64_expr(m, kw.value)
                and node.lineno not in reported
            ):
                reported.add(node.lineno)
                yield node.lineno, (
                    "explicit float64 dtype on a jnp array doubles HBM "
                    "for that buffer and breaks the f32 agent-table "
                    "contract"
                )


# ---------------------------------------------------------------------------
# L4 — data-dependent array construction inside jitted bodies
# ---------------------------------------------------------------------------

def _data_dependent(m: ModuleInfo, expr: ast.AST) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            r = _resolve(m, dotted(n.func))
            if r and (r.startswith(_JNP) or r.startswith("jax.lax.")):
                return True
            if (
                isinstance(n.func, ast.Attribute)
                and n.func.attr in _REDUCTION_METHODS
            ):
                return True
    return False


def rule_l4(m: ModuleInfo, index: ProjectIndex) -> Iterable[RuleHit]:
    """Array constructors whose shape derives from traced values inside
    jit-reachable code — shapes must be static under XLA."""
    for _fn, node in _reachable_nodes(m, index):
        if not isinstance(node, ast.Call):
            continue
        r = _resolve(m, dotted(node.func))
        if not (r and r.startswith(_JNP)):
            continue
        member = r[len(_JNP):]
        arg_idx = _SHAPE_CTORS.get(member)
        if arg_idx is None:
            continue
        for i in arg_idx:
            if i < len(node.args) and _data_dependent(m, node.args[i]):
                yield node.lineno, (
                    f"`jnp.{member}` with a data-dependent shape cannot "
                    "be traced (shapes are static under jit); compute a "
                    "static bound and mask instead"
                )
                break


# ---------------------------------------------------------------------------
# L5 — layering
# ---------------------------------------------------------------------------

#: (package prefix of the module, forbidden import prefixes, why)
_LAYERS = (
    ("dgen_tpu.ops.", ("dgen_tpu.models", "dgen_tpu.io"),
     "ops/ is the kernel layer; it must stay importable without the "
     "model or IO stack"),
    ("dgen_tpu.models.", ("dgen_tpu.io.store",),
     "models/ must not bind to the columnar store backend"),
    ("dgen_tpu.utils.", ("dgen_tpu.ops", "dgen_tpu.models", "dgen_tpu.io",
                         "dgen_tpu.parallel"),
     "utils/ is the leaf layer"),
)


def rule_l5(m: ModuleInfo, index: ProjectIndex) -> Iterable[RuleHit]:
    """Layering: ops/ must not import models/ or io/; models/ must not
    import io/store; utils/ imports nothing above it."""
    for pkg, forbidden, why in _LAYERS:
        # the package __init__ itself (modname == pkg minus the dot)
        # is part of the layer too
        if not (m.modname.startswith(pkg) or m.modname == pkg[:-1]):
            continue
        for line, target in m.import_nodes:
            for f in forbidden:
                if target == f or target.startswith(f + "."):
                    yield line, (
                        f"`{m.modname}` imports `{target}`: {why}"
                    )


# ---------------------------------------------------------------------------
# L6 — Pallas block-shape / dtype rules
# ---------------------------------------------------------------------------

def _imports_pallas(m: ModuleInfo) -> bool:
    return any(
        target.startswith("jax.experimental.pallas")
        for _line, target in m.import_nodes
    )


def rule_l6(m: ModuleInfo, index: ProjectIndex) -> Iterable[RuleHit]:
    """In Pallas modules: BlockSpec trailing dims must be lane/sublane
    aligned (multiples of (8, 128), singletons allowed) and no f64
    anywhere (the TPU vector unit has no f64 path)."""
    if not _imports_pallas(m):
        return
    for node in ast.walk(m.tree):
        if _is_f64_expr(m, node) and not isinstance(node, ast.Name):
            yield node.lineno, (
                "float64 in a Pallas module: Mosaic kernels have no f64 "
                "path"
            )
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if not (d and (d == "BlockSpec" or d.endswith(".BlockSpec"))):
            continue
        if not node.args or not isinstance(node.args[0], ast.Tuple):
            continue
        dims = [m.const_value(e) for e in node.args[0].elts]
        if len(dims) >= 1 and dims[-1] is not None:
            if dims[-1] != 1 and dims[-1] % 128 != 0:
                yield node.lineno, (
                    f"BlockSpec last (lane) dim {dims[-1]} is not a "
                    "multiple of 128 — Mosaic pads every block to the "
                    "8x128 tile, wasting VMEM and bandwidth"
                )
        if len(dims) >= 2 and dims[-2] is not None:
            if dims[-2] != 1 and dims[-2] % 8 != 0:
                yield node.lineno, (
                    f"BlockSpec sublane dim {dims[-2]} is not a multiple "
                    "of 8 — the f32 tile is (8, 128); unaligned blocks "
                    "pad and copy"
                )


# ---------------------------------------------------------------------------
# L7 — year-step entry points must donate the carry
# ---------------------------------------------------------------------------

def rule_l7(m: ModuleInfo, index: ProjectIndex) -> Iterable[RuleHit]:
    """A jitted function threading a cross-step ``carry`` must donate
    it (``donate_argnames=('carry',)``): without donation every year
    holds two copies of the carry in HBM and XLA cannot alias the
    update in place."""
    for fn in m.functions:
        node = fn.node
        if not any(is_jit_decorator(d) for d in node.decorator_list):
            continue
        params = [a.arg for a in (
            node.args.posonlyargs + node.args.args + node.args.kwonlyargs
        )]
        if "carry" not in params:
            continue
        call = jit_decorator_call(node)
        kwargs = {kw.arg for kw in call.keywords} if call is not None else set()
        if not kwargs & {"donate_argnums", "donate_argnames"}:
            yield node.lineno, (
                f"jitted `{fn.qualname}` threads a `carry` but does not "
                "donate it; add donate_argnames=('carry',) so XLA "
                "aliases the cross-step state in place"
            )


# ---------------------------------------------------------------------------
# L8 — debug leftovers in hot paths
# ---------------------------------------------------------------------------

_L8_CALLS = {"jax.debug.print", "jax.debug.breakpoint", "pdb.set_trace",
             "pdb.post_mortem"}


def rule_l8(m: ModuleInfo, index: ProjectIndex) -> Iterable[RuleHit]:
    """Debug leftovers: ``jax.debug.print``/``breakpoint``/``print``/
    ``pdb`` in jit-reachable code (each inserts a host callback that
    serializes the device pipeline), and ``import pdb`` anywhere."""
    for _fn, node in _reachable_nodes(m, index):
        if not isinstance(node, ast.Call):
            continue
        r = _resolve(m, dotted(node.func))
        if r in _L8_CALLS:
            yield node.lineno, (
                f"`{dotted(node.func)}` left in jit-reachable code "
                "stalls the device pipeline on a host callback"
            )
        elif isinstance(node.func, ast.Name) and node.func.id in (
            "print", "breakpoint"
        ):
            yield node.lineno, (
                f"`{node.func.id}()` left in jit-reachable code (fires "
                "at trace time only, or stalls the pipeline)"
            )
    for line, target in m.import_nodes:
        if target == "pdb" or target.startswith("pdb."):
            yield line, "`import pdb` left in library code"


# ---------------------------------------------------------------------------
# L9 — synchronous host fetches inside per-year driver loops
# ---------------------------------------------------------------------------

#: the async pipeline itself: its fetch stage IS the sanctioned
#: device_get (it runs on a worker thread, off the dispatch path)
_L9_EXEMPT_MODULES = ("dgen_tpu.io.hostio",)

_L9_FETCHES = {"jax.device_get"}
#: np constructors that force a D2H copy when handed a device array;
#: only flagged when the argument is rooted at a per-year output/carry
#: binding, where it is certainly a device array
_L9_NP_CTORS = {"numpy.asarray", "numpy.array"}
_L9_DEVICE_ROOTS = {"outs", "out", "outputs", "carry", "snap"}


def _is_year_loop(node: ast.For) -> bool:
    """A per-year driver loop: binds a loop variable named
    ``year``/``yi``/``year_idx``, or iterates (an ``enumerate`` of)
    something whose name ends in ``years``."""
    names = {
        t.id for t in ast.walk(node.target) if isinstance(t, ast.Name)
    }
    if names & {"year", "yi", "year_idx"}:
        return True
    it = node.iter
    if (
        isinstance(it, ast.Call)
        and dotted(it.func) == "enumerate"
        and it.args
    ):
        it = it.args[0]
    d = dotted(it)
    return bool(d) and d.split(".")[-1].endswith("years")


def rule_l9(m: ModuleInfo, index: ProjectIndex) -> Iterable[RuleHit]:
    """Synchronous ``jax.device_get`` / ``np.asarray(<device array>)``
    inside per-year loop bodies outside :mod:`dgen_tpu.io.hostio`: each
    one serializes the driver against the device every year — route
    the consumer through the host-IO pipeline instead."""
    if m.modname in _L9_EXEMPT_MODULES:
        return
    reported = set()
    for node in ast.walk(m.tree):
        if not isinstance(node, ast.For) or not _is_year_loop(node):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call) or sub.lineno in reported:
                continue
            r = _resolve(m, dotted(sub.func))
            if r in _L9_FETCHES:
                reported.add(sub.lineno)
                yield sub.lineno, (
                    f"synchronous `{dotted(sub.func)}` in a per-year "
                    "loop blocks dispatch on the D2H copy every year; "
                    "route the consumer through io.hostio.HostPipeline "
                    "(or suppress if this IS the serialized oracle)"
                )
            elif r in _L9_NP_CTORS and sub.args:
                arg = dotted(sub.args[0])
                if arg and arg.split(".")[0] in _L9_DEVICE_ROOTS:
                    reported.add(sub.lineno)
                    yield sub.lineno, (
                        f"`{dotted(sub.func)}({arg})` in a per-year "
                        "loop fetches a device array synchronously; "
                        "route it through io.hostio.HostPipeline"
                    )


# ---------------------------------------------------------------------------
# L10 — jit construction inside request-handling paths
# ---------------------------------------------------------------------------
#
# The serving layer's contract is FIXED compile shapes: every device
# program a process can run is built (and warmed) at engine
# construction. A ``jax.jit`` reachable from a request handler breaks
# that silently — each distinct request shape/static pays an 80-170 s
# XLA compile ON the request path, which is a p99 catastrophe the
# averages hide. RetraceGuard catches the fact at runtime; this rule
# catches the code shape statically.

def _is_request_fn(fn: FuncInfo) -> bool:
    """Request-handling heuristic: http.server ``do_*`` verbs, any
    ``handle``/``request`` in the function name, or a method of a
    ``*Handler`` class."""
    name = fn.node.name.lower()
    if name.startswith("do_") or "handle" in name or "request" in name:
        return True
    return bool(fn.class_name and fn.class_name.lower().endswith("handler"))


def rule_l10(m: ModuleInfo, index: ProjectIndex) -> Iterable[RuleHit]:
    """``jax.jit`` (or ``partial(jax.jit, ...)``) constructed inside a
    request-handling function: per-request compiles. Build jitted query
    programs once at engine init (module level or constructor) and
    dispatch to them from handlers."""
    for fn in m.functions:
        inside = fn if _is_request_fn(fn) else fn.parent
        while inside is not None and not _is_request_fn(inside):
            inside = inside.parent
        if inside is None:
            continue
        # a function's OWN decorators evaluate once at def time, not
        # per request: skip them in the Call scan (a jit-DECORATED
        # handler is fine; a jit-decorated def NESTED in a handler is
        # reported once, by the FunctionDef branch of the parent scan)
        own_decorators = {id(d) for d in fn.node.decorator_list}
        for node in walk_own_body(fn):
            if id(node) in own_decorators:
                continue
            if isinstance(node, ast.Call) and is_jit_decorator(node):
                yield node.lineno, (
                    "`jax.jit` constructed inside request-handling "
                    f"path `{fn.qualname}`: every request (shape) pays "
                    "a fresh trace/compile on the serving path — build "
                    "the jitted program once at engine init and "
                    "dispatch to fixed bucket shapes"
                )
            elif isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and any(is_jit_decorator(d) for d in node.decorator_list):
                yield node.lineno, (
                    f"jit-decorated function defined inside request-"
                    f"handling path `{fn.qualname}`: the decorator "
                    "builds a fresh jit wrapper (empty compile cache) "
                    "per request — define it once at module/engine "
                    "scope"
                )


# ---------------------------------------------------------------------------
# L11 — bare run-artifact writes outside the temp+rename helpers
# ---------------------------------------------------------------------------
#
# Run artifacts (parquet partitions, meta/manifest JSON, bank files)
# must be crash-consistent: a killed writer may leave a *.tmp sibling,
# never a truncated file at the published path.  The sanctioned path is
# dgen_tpu.resilience.atomic (write to temp, one os.replace).  This
# rule flags write-mode ``open`` and direct ``.to_parquet``/``.to_csv``
# calls in functions that neither call an atomic_* helper nor perform
# the rename themselves (and whose enclosing functions don't either —
# a nested writer handed to atomic_write is fine).

#: a function (or an enclosing one) calling any of these IS the
#: temp+rename path, not a bypass of it
_L11_SAFE_CALL_SUFFIXES = (
    "atomic_write", "atomic_write_text", "atomic_write_json",
    "atomic_write_bytes", "atomic_to_parquet",
)
_L11_RENAMES = {"os.replace", "os.rename"}
_L11_FRAME_WRITERS = {"to_parquet", "to_csv"}


def _l11_write_mode(node: ast.Call) -> Optional[str]:
    """The literal write mode of an ``open`` call, or None."""
    if not (isinstance(node.func, ast.Name) and node.func.id == "open"):
        return None
    mode = None
    if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
        mode = node.args[1].value
    for kw in node.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    if isinstance(mode, str) and mode[:1] in ("w", "a", "x"):
        return mode
    return None


def _l11_fn_is_safe(m: ModuleInfo, fn: FuncInfo) -> bool:
    """fn or an enclosing function calls an atomic_* helper or does the
    rename itself."""
    node: Optional[FuncInfo] = fn
    while node is not None:
        for sub in walk_own_body(node):
            if not isinstance(sub, ast.Call):
                continue
            d = dotted(sub.func)
            if d is None:
                continue
            if _resolve(m, d) in _L11_RENAMES:
                return True
            if d.split(".")[-1] in _L11_SAFE_CALL_SUFFIXES:
                return True
        node = node.parent
    return False


def rule_l11(m: ModuleInfo, index: ProjectIndex) -> Iterable[RuleHit]:
    """Bare write-mode ``open``/``to_parquet``/``to_csv`` outside the
    temp+rename helpers: a kill mid-write leaves a truncated artifact
    at the published path.  Route the write through
    ``dgen_tpu.resilience.atomic`` (or do the temp+``os.replace`` dance
    in the same function)."""
    safe_cache: Dict[int, bool] = {}

    def safe(fn: FuncInfo) -> bool:
        k = id(fn)
        if k not in safe_cache:
            safe_cache[k] = _l11_fn_is_safe(m, fn)
        return safe_cache[k]

    for fn in m.functions:
        if safe(fn):
            continue
        for node in walk_own_body(fn):
            if not isinstance(node, ast.Call):
                continue
            mode = _l11_write_mode(node)
            if mode is not None:
                yield node.lineno, (
                    f"bare `open(..., '{mode}')` writes an artifact "
                    "in place — a kill mid-write leaves it truncated; "
                    "use dgen_tpu.resilience.atomic (temp + os.replace)"
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _L11_FRAME_WRITERS
                and (node.args or node.keywords)
            ):
                yield node.lineno, (
                    f"bare `.{node.func.attr}(...)` writes a run "
                    "artifact in place — a kill mid-write leaves it "
                    "truncated; use resilience.atomic.atomic_to_parquet "
                    "(temp + os.replace)"
                )


# ---------------------------------------------------------------------------
# L12 — unbounded in-memory caches in request-handling paths
# ---------------------------------------------------------------------------
#
# A serving process is long-lived: any dict/list it grows per REQUEST
# (a result memo keyed by request data, a seen-requests log) is a slow
# memory leak that an averages-dashboard never shows — the process
# OOMs at 3 a.m. after weeks of organic key diversity.  The serve
# layer's caches (the override-variant LRU, the file-backed result
# cache) are bounded by construction; this rule catches the unbounded
# shape statically: a ``self.X[key] = ...`` store or ``self.X.append``
# in a request-path function whose class never evicts X (no
# popitem/pop/clear/remove/del, no ``maxlen=`` bound at construction).

#: request-path heuristic (superset of L10's): http.server do_* verbs,
#: handle/request names, *Handler methods, plus the serving vocabulary
#: (submit/query/route)
_L12_NAME_PARTS = ("handle", "request", "submit", "query", "route")

#: a class calling any of these on the attribute IS bounding it
_L12_EVICTORS = {"popitem", "pop", "clear", "remove"}


def _is_l12_request_fn(fn: FuncInfo) -> bool:
    name = fn.node.name.lower()
    if name.startswith("do_") or any(t in name for t in _L12_NAME_PARTS):
        return True
    return bool(fn.class_name and fn.class_name.lower().endswith("handler"))


def _l12_self_attr(node: ast.AST) -> Optional[str]:
    """``'x'`` for a ``self.x`` attribute expression, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _l12_bounded_attrs(m: ModuleInfo) -> set:
    """(class, attr) pairs with eviction/bound evidence anywhere in
    the class: an evictor call, a ``del self.X[...]``, or a
    ``maxlen=``-bounded constructor assignment."""
    bounded = set()
    for fn in m.functions:
        cls = fn.class_name
        if cls is None:
            continue
        for node in walk_own_body(fn):
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                attr = _l12_self_attr(node.func.value)
                if attr is not None and node.func.attr in _L12_EVICTORS:
                    bounded.add((cls, attr))
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        attr = _l12_self_attr(t.value)
                        if attr is not None:
                            bounded.add((cls, attr))
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                value = node.value
                if isinstance(value, ast.Call) and any(
                    kw.arg == "maxlen" for kw in value.keywords
                ):
                    for t in targets:
                        attr = _l12_self_attr(t)
                        if attr is not None:
                            bounded.add((cls, attr))
    return bounded


def rule_l12(m: ModuleInfo, index: ProjectIndex) -> Iterable[RuleHit]:
    """Request-keyed accumulation into an unbounded ``self`` container
    inside request-handling paths: the class must evict (or bound at
    construction) anything a request can grow."""
    bounded = _l12_bounded_attrs(m)
    for fn in m.functions:
        if fn.class_name is None:
            continue
        inside = fn if _is_l12_request_fn(fn) else fn.parent
        while inside is not None and not _is_l12_request_fn(inside):
            inside = inside.parent
        if inside is None:
            continue
        for node in walk_own_body(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if not isinstance(t, ast.Subscript):
                        continue
                    attr = _l12_self_attr(t.value)
                    if (
                        attr is not None
                        and not isinstance(t.slice, ast.Constant)
                        and (fn.class_name, attr) not in bounded
                    ):
                        yield node.lineno, (
                            f"`self.{attr}[...]` grows per request in "
                            f"`{fn.qualname}` and nothing in the class "
                            "ever evicts it — a long-lived serving "
                            "process leaks until OOM; bound it (LRU "
                            "popitem, maxlen, or the file-backed "
                            "result cache)"
                        )
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("append", "setdefault")
            ):
                attr = _l12_self_attr(node.func.value)
                if (
                    attr is not None
                    and (fn.class_name, attr) not in bounded
                ):
                    yield node.lineno, (
                        f"`self.{attr}.{node.func.attr}(...)` grows "
                        f"per request in `{fn.qualname}` with no "
                        "eviction anywhere in the class — bound it "
                        "(deque(maxlen=...), explicit eviction) or "
                        "move it off the request path"
                    )


# ---------------------------------------------------------------------------
# Registry / driver
# ---------------------------------------------------------------------------

RULES: Dict[str, Tuple[str, object]] = {
    "L1": ("host-sync calls in jit-reachable code", rule_l1),
    "L2": ("Python control flow on array values", rule_l2),
    "L3": ("float64 leaking into the device path", rule_l3),
    "L4": ("data-dependent array shapes under jit", rule_l4),
    "L5": ("layering violations (ops/models/io/utils)", rule_l5),
    "L6": ("Pallas block-shape / dtype alignment", rule_l6),
    "L7": ("missing carry donation on year-step entry points", rule_l7),
    "L8": ("debug leftovers in hot paths", rule_l8),
    "L9": ("synchronous host fetches in per-year driver loops", rule_l9),
    "L10": ("jit construction inside request-handling paths", rule_l10),
    "L11": ("bare run-artifact writes outside temp+rename", rule_l11),
    "L12": ("unbounded in-memory caches in request paths", rule_l12),
}


def run_rules(
    index: ProjectIndex,
    modules: Optional[Iterable[ModuleInfo]] = None,
    select: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Run (selected) rules over ``modules`` (default: every indexed
    module), honoring suppression comments; sorted by path/line."""
    mods = list(modules) if modules is not None else index.modules
    chosen = list(select) if select is not None else list(RULES)
    unknown = [r for r in chosen if r not in RULES]
    if unknown:
        raise ValueError(f"unknown rule id(s): {', '.join(unknown)}")
    findings: List[Finding] = []
    for m in mods:
        for rule_id in chosen:
            _summary, impl = RULES[rule_id]
            for line, msg in impl(m, index):
                if not m.is_suppressed(rule_id, line):
                    findings.append(Finding(rule_id, m.path, line, msg))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
