"""CLI: ``python -m dgen_tpu.lint [paths...]``.

Two halves share the exit convention (0 clean, 1 findings, 2 usage
error):

* default — the AST linter (rules L1-L11) over source paths; no jax
  import, safe anywhere.
* ``--programs`` — the jaxpr/HLO program auditor (rules J0-J6,
  :mod:`dgen_tpu.lint.prog`): traces and lowers every registered
  jitted entry point over the static-config grid on the CPU backend
  (``JAX_PLATFORMS`` defaults to cpu for the audit; no devices, no
  data) and gates compiled cost fingerprints against
  ``tools/prog_baseline.json``.

``--json`` emits a machine-readable finding list (one object per
finding); the default text format is ``path:line: RULE message``, one
per line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from dgen_tpu.lint import PACKAGE_ROOT, RULES, lint_paths


def _findings_out(findings, as_json: bool, label: str) -> int:
    if as_json:
        print(json.dumps(
            [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "message": f.message}
                for f in findings
            ],
            indent=1,
        ))
    else:
        for f in findings:
            print(f)
        n = len(findings)
        print(
            f"{label}: {n} finding{'s' if n != 1 else ''}"
            if n else f"{label}: clean",
            file=sys.stderr,
        )
    return 1 if findings else 0


def _run_programs(args) -> int:
    # the auditor only ever needs to TRACE — never run — so default to
    # the CPU backend unless the operator pinned one explicitly (a TPU
    # bring-up just to parse programs wastes minutes and a chip)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from dgen_tpu.lint import prog

    if args.list_programs:
        for name in prog.entry_names():
            print(name)
        return 0
    entries = None
    if args.entries:
        entries = [e.strip() for e in args.entries.split(",") if e.strip()]
    select = None
    if args.select:
        select = [r.strip() for r in args.select.split(",") if r.strip()]
    try:
        findings, report = prog.audit_programs(
            entries=entries,
            grid=args.grid,
            select=select,
            baseline_path=args.baseline,
            update_baselines=args.update_baselines,
            tolerance=args.tolerance,
        )
    except ValueError as e:
        print(f"dgenlint: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(
            {
                "findings": [
                    {"rule": f.rule, "path": f.path, "line": f.line,
                     "message": f.message}
                    for f in findings
                ],
                "report": report,
            },
            indent=1,
        ))
        return 1 if findings else 0
    for name, e in sorted(report["entries"].items()):
        print(
            f"dgenlint-prog: {name}: {e['variants']} variant(s) -> "
            f"{e['predicted_compile_groups']} compile group(s)"
            + (f", {e['failed']} FAILED" if e["failed"] else ""),
            file=sys.stderr,
        )
    j6 = report.get("j6") or {}
    if j6.get("note"):
        print(f"dgenlint-prog: {j6['note']}", file=sys.stderr)
    if j6.get("updated"):
        print(
            f"dgenlint-prog: baseline written to {j6['updated']} "
            f"({len(j6['entries'])} entries)",
            file=sys.stderr,
        )
    return _findings_out(findings, False, "dgenlint-prog")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dgen_tpu.lint",
        description="dgenlint: JAX/TPU anti-pattern linter + program "
                    "auditor (rules documented in docs/lint.md)",
    )
    ap.add_argument(
        "paths", nargs="*",
        help=f"files/directories to lint (default: {PACKAGE_ROOT})",
    )
    ap.add_argument(
        "--select", metavar="RULES",
        help="comma-separated rule ids to run (default: all)",
    )
    ap.add_argument(
        "--json", action="store_true", help="emit findings as JSON",
    )
    ap.add_argument(
        "--list-rules", action="store_true",
        help="print the rule ids and summaries, then exit",
    )
    prog_group = ap.add_argument_group(
        "program auditor (--programs)",
    )
    prog_group.add_argument(
        "--programs", action="store_true",
        help="audit the lowered jaxpr/StableHLO of every registered "
             "jitted entry point (rules J0-J6) instead of linting "
             "source",
    )
    prog_group.add_argument(
        "--entries", metavar="NAMES",
        help="comma-separated registry entries to audit (default: all; "
             "see --list-programs)",
    )
    prog_group.add_argument(
        "--grid", choices=("default", "fast"), default="default",
        help="static-config grid depth: 'fast' audits each entry's "
             "base point only",
    )
    prog_group.add_argument(
        "--list-programs", action="store_true",
        help="print the registered entry names, then exit",
    )
    prog_group.add_argument(
        "--baseline", metavar="PATH",
        help="J6 cost-baseline JSON (default: tools/prog_baseline.json)",
    )
    prog_group.add_argument(
        "--update-baselines", action="store_true",
        help="rewrite the J6 baseline from the current programs "
             "instead of gating against it",
    )
    prog_group.add_argument(
        "--tolerance", type=float, default=None,
        help="override the J6 relative drift tolerance",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule_id, (summary, _impl) in RULES.items():
            print(f"{rule_id}  {summary}")
        # the J-rules live behind --programs but share the id space;
        # their id table is jax-free (the implementations are not)
        from dgen_tpu.lint.prog_ids import PROGRAM_RULE_SUMMARIES

        for rule_id, summary in PROGRAM_RULE_SUMMARIES.items():
            print(f"{rule_id}  {summary}  (--programs)")
        return 0

    if args.programs or args.list_programs:
        return _run_programs(args)

    select = None
    if args.select:
        select = [r.strip() for r in args.select.split(",") if r.strip()]
    try:
        findings = lint_paths(args.paths or None, select=select)
    except (ValueError, OSError, SyntaxError) as e:
        print(f"dgenlint: {e}", file=sys.stderr)
        return 2
    return _findings_out(findings, args.json, "dgenlint")


if __name__ == "__main__":
    sys.exit(main())
