"""CLI: ``python -m dgen_tpu.lint [paths...]``.

Two halves share the exit convention (0 clean, 1 findings, 2 usage
error):

* default — the AST linter (rules L1-L11) over source paths; no jax
  import, safe anywhere.
* ``--programs`` — the jaxpr/HLO program auditor (rules J0-J10,
  :mod:`dgen_tpu.lint.prog`): traces and lowers every registered
  jitted entry point over the static-config grid on the CPU backend
  (``JAX_PLATFORMS`` defaults to cpu for the audit; no devices, no
  data) and gates compiled cost fingerprints against
  ``tools/prog_baseline.json``.
* ``--conc`` — the thread-safety tier (rules C1-C6,
  :mod:`dgen_tpu.lint.conc`): per-class thread-entry inference + lock
  dominance over the concurrent host modules (serve/, io/hostio.py,
  resilience/, utils/timing.py, parallel/ by default; paths override).
  No jax import either.

``--json`` emits a machine-readable finding list (one object per
finding); the default text format is ``path:line: RULE message``, one
per line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from dgen_tpu.lint import PACKAGE_ROOT, RULES, lint_paths


def _findings_out(findings, as_json: bool, label: str) -> int:
    if as_json:
        print(json.dumps(
            [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "message": f.message}
                for f in findings
            ],
            indent=1,
        ))
    else:
        for f in findings:
            print(f)
        n = len(findings)
        print(
            f"{label}: {n} finding{'s' if n != 1 else ''}"
            if n else f"{label}: clean",
            file=sys.stderr,
        )
    return 1 if findings else 0


def _parse_mesh_shapes(arg):
    if not arg:
        return None
    from dgen_tpu.parallel.mesh import parse_mesh_shape

    return [parse_mesh_shape(s) for s in arg.split(",") if s.strip()]


def _force_mesh_devices(shapes) -> None:
    """Request enough virtual CPU devices for the mesh grid BEFORE the
    backend initializes (the whole audit is trace/lower/compile — no
    execution — so virtual devices are all it ever needs)."""
    from dgen_tpu.lint.prog.registry import MESH_GRID_DEFAULT
    from dgen_tpu.utils import compat

    grid = shapes or list(MESH_GRID_DEFAULT)
    need = max(int(h) * int(d) for h, d in grid)
    compat.set_cpu_device_count(max(need, 1))


def _advisory_banner(note: str) -> None:
    """A downgraded cost gate must be LOUD: an operator (or a CI log
    reader) who misses it ships unreviewed cost changes."""
    for line in (
        "*" * 66,
        "*** COST GATES (J6/J7/J10) DOWNGRADED TO ADVISORY — NOT ENFORCED",
        f"*** {note}",
        "*** re-seed on purpose with:",
        "***     python -m dgen_tpu.lint --programs --update-baselines",
        "***     (add --mesh for the J7/J10 mesh section)",
        "*" * 66,
    ):
        print(f"dgenlint-prog: {line}", file=sys.stderr)


def _run_programs(args) -> int:
    # the auditor only ever needs to TRACE — never run — so default to
    # the CPU backend unless the operator pinned one explicitly (a TPU
    # bring-up just to parse programs wastes minutes and a chip)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        mesh_shapes = _parse_mesh_shapes(args.mesh_shapes)
    except ValueError as e:
        print(f"dgenlint: {e}", file=sys.stderr)
        return 2
    if mesh_shapes and not (args.mesh or args.explain):
        # an explicitly requested mesh grid must never be a silent
        # no-op (the operator would believe the shapes were audited)
        print(
            "dgenlint: --mesh-shapes requires --mesh (or --explain)",
            file=sys.stderr,
        )
        return 2
    if args.hbm_gb is not None and not args.mesh:
        # same principle for the J9 budget: without the mesh tier the
        # memory gate never runs, and a silent exit-0 would read as
        # "the footprint was gated at this budget"
        print("dgenlint: --hbm-gb requires --mesh", file=sys.stderr)
        return 2
    if args.mesh or args.explain:
        _force_mesh_devices(mesh_shapes)
    from dgen_tpu.lint import prog

    if args.list_programs:
        for name in prog.entry_names():
            print(name)
        return 0
    if args.explain:
        try:
            # an explicit --mesh-shapes implies the mesh view (the
            # guard above lets it through without --mesh)
            print(prog.explain_entry(
                args.explain, mesh=args.mesh or bool(mesh_shapes),
                mesh_shapes=mesh_shapes,
            ))
        except ValueError as e:
            print(f"dgenlint: {e}", file=sys.stderr)
            return 2
        return 0
    entries = None
    if args.entries:
        entries = [e.strip() for e in args.entries.split(",") if e.strip()]
    select = None
    if args.select:
        select = [r.strip() for r in args.select.split(",") if r.strip()]
    try:
        findings, report = prog.audit_programs(
            entries=entries,
            grid=args.grid,
            select=select,
            baseline_path=args.baseline,
            update_baselines=args.update_baselines,
            tolerance=args.tolerance,
            mesh=args.mesh,
            mesh_shapes=mesh_shapes,
            hbm_budget_gb=args.hbm_gb,
        )
    except ValueError as e:
        print(f"dgenlint: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(
            {
                "findings": [
                    {"rule": f.rule, "path": f.path, "line": f.line,
                     "message": f.message}
                    for f in findings
                ],
                "report": report,
            },
            indent=1,
        ))
        return 1 if findings else 0
    for name, e in sorted(report["entries"].items()):
        print(
            f"dgenlint-prog: {name}: {e['variants']} variant(s) -> "
            f"{e['predicted_compile_groups']} compile group(s)"
            + (f", {e['failed']} FAILED" if e["failed"] else ""),
            file=sys.stderr,
        )
    for spec_id, m in sorted((report.get("mesh") or {}).items()):
        colls = ", ".join(
            f"{k} x{v}" for k, v in sorted(m["collectives"].items())
        ) or "no collectives"
        peak = m.get("peak_bytes")
        print(
            f"dgenlint-prog: [mesh] {spec_id}: {colls} "
            f"(~{m['comm_bytes']} comm B"
            + (f", peak {peak / 2**20:.1f} MiB/device" if peak else "")
            + ")",
            file=sys.stderr,
        )
    j6 = report.get("j6") or {}
    j7 = report.get("j7") or {}
    # a downgraded gate (jax/platform/spec mismatch vs the committed
    # baseline, or no baseline at all) must be impossible to miss in a
    # check.sh or CI log — keyed on the structured status flag, not
    # the note's wording
    if j6.get("downgraded") or j7.get("downgraded"):
        _advisory_banner(j6.get("note") or j7.get("note") or "")
    if j6.get("updated"):
        print(
            f"dgenlint-prog: baseline written to {j6['updated']} "
            f"({len(j6['entries'])} entries"
            + (f", {len(j6.get('mesh_entries') or [])} mesh entries"
               if j6.get("mesh_entries") else "")
            + ")",
            file=sys.stderr,
        )
    return _findings_out(findings, False, "dgenlint-prog")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dgen_tpu.lint",
        description="dgenlint: JAX/TPU anti-pattern linter + program "
                    "auditor (rules documented in docs/lint.md)",
    )
    ap.add_argument(
        "paths", nargs="*",
        help=f"files/directories to lint (default: {PACKAGE_ROOT})",
    )
    ap.add_argument(
        "--select", metavar="RULES",
        help="comma-separated rule ids to run (default: all)",
    )
    ap.add_argument(
        "--json", action="store_true", help="emit findings as JSON",
    )
    ap.add_argument(
        "--list-rules", action="store_true",
        help="print the rule ids and summaries, then exit",
    )
    conc_group = ap.add_argument_group(
        "concurrency tier (--conc)",
    )
    conc_group.add_argument(
        "--conc", action="store_true",
        help="audit the threaded host-side modules with the "
             "thread-safety rules C1-C6 (default roots: serve/, "
             "io/hostio.py, resilience/, utils/timing.py, parallel/; "
             "positional paths override) instead of linting source",
    )
    prog_group = ap.add_argument_group(
        "program auditor (--programs)",
    )
    prog_group.add_argument(
        "--programs", action="store_true",
        help="audit the lowered jaxpr/StableHLO of every registered "
             "jitted entry point (rules J0-J6; --mesh adds J7-J10) "
             "instead of linting "
             "source",
    )
    prog_group.add_argument(
        "--entries", metavar="NAMES",
        help="comma-separated registry entries to audit (default: all; "
             "see --list-programs)",
    )
    prog_group.add_argument(
        "--grid", choices=("default", "fast"), default="default",
        help="static-config grid depth: 'fast' audits each entry's "
             "base point only",
    )
    prog_group.add_argument(
        "--list-programs", action="store_true",
        help="print the registered entry names, then exit",
    )
    prog_group.add_argument(
        "--mesh", action="store_true",
        help="additionally lower every entry under the multi-device "
             "CPU mesh grid (1x8 + 2x4 hosts-x-devices by default) "
             "with production shardings and enforce J7-J10",
    )
    prog_group.add_argument(
        "--mesh-shapes", metavar="SHAPES",
        help="comma-separated HxD mesh shapes for --mesh "
             "(e.g. 1x8,2x4); custom shapes gate without the "
             "stale-entry sweep",
    )
    prog_group.add_argument(
        "--hbm-gb", type=float, default=None,
        help="J9 per-device memory budget in GiB (default 16)",
    )
    prog_group.add_argument(
        "--explain", metavar="ENTRY",
        help="dump one entry's jaxpr, sharded HLO excerpt, collective "
             "table and per-device memory estimate, then exit "
             "(accepts entry or entry@variant; combine with --mesh)",
    )
    prog_group.add_argument(
        "--baseline", metavar="PATH",
        help="J6 cost-baseline JSON (default: tools/prog_baseline.json)",
    )
    prog_group.add_argument(
        "--update-baselines", action="store_true",
        help="rewrite the J6 baseline from the current programs "
             "instead of gating against it",
    )
    prog_group.add_argument(
        "--tolerance", type=float, default=None,
        help="override the J6 relative drift tolerance",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule_id, (summary, _impl) in RULES.items():
            print(f"{rule_id}  {summary}")
        # the J-rules live behind --programs but share the id space;
        # their id table is jax-free (the implementations are not)
        from dgen_tpu.lint.prog_ids import PROGRAM_RULE_SUMMARIES

        for rule_id, summary in PROGRAM_RULE_SUMMARIES.items():
            print(f"{rule_id}  {summary}  (--programs)")
        # the C-rules share the id space behind --conc; same
        # dependency-free id-table contract
        from dgen_tpu.lint.conc_ids import CONC_RULE_SUMMARIES

        for rule_id, summary in CONC_RULE_SUMMARIES.items():
            print(f"{rule_id}  {summary}  (--conc)")
        return 0

    if args.conc and (args.programs or args.list_programs or args.explain
                      or args.mesh or args.mesh_shapes):
        # two different audits answering under one exit code would be
        # unreadable in a CI log — one tier per invocation
        print("dgenlint: --conc cannot be combined with the program "
              "auditor flags", file=sys.stderr)
        return 2
    if args.conc:
        from dgen_tpu.lint.conc import lint_conc_paths

        select = None
        if args.select:
            select = [r.strip() for r in args.select.split(",")
                      if r.strip()]
        try:
            findings = lint_conc_paths(args.paths or None, select=select)
        except (ValueError, OSError, SyntaxError) as e:
            print(f"dgenlint: {e}", file=sys.stderr)
            return 2
        return _findings_out(findings, args.json, "dgenlint-conc")

    if args.programs or args.list_programs or args.explain:
        return _run_programs(args)
    if args.mesh or args.mesh_shapes or args.hbm_gb is not None:
        # program-auditor flags without --programs must not silently
        # fall through to the source linter (the operator would read
        # its 'clean' as the mesh audit passing)
        print(
            "dgenlint: --mesh/--mesh-shapes/--hbm-gb require "
            "--programs",
            file=sys.stderr,
        )
        return 2

    select = None
    if args.select:
        select = [r.strip() for r in args.select.split(",") if r.strip()]
    try:
        findings = lint_paths(args.paths or None, select=select)
    except (ValueError, OSError, SyntaxError) as e:
        print(f"dgenlint: {e}", file=sys.stderr)
        return 2
    return _findings_out(findings, args.json, "dgenlint")


if __name__ == "__main__":
    sys.exit(main())
