"""CLI: ``python -m dgen_tpu.lint [paths...]``.

Exit status: 0 clean, 1 findings, 2 usage error. ``--json`` emits a
machine-readable finding list (one object per finding); the default
text format is ``path:line: RULE message``, one per line.
"""

from __future__ import annotations

import argparse
import json
import sys

from dgen_tpu.lint import PACKAGE_ROOT, RULES, lint_paths


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dgen_tpu.lint",
        description="dgenlint: JAX/TPU anti-pattern linter "
                    "(rules documented in docs/lint.md)",
    )
    ap.add_argument(
        "paths", nargs="*",
        help=f"files/directories to lint (default: {PACKAGE_ROOT})",
    )
    ap.add_argument(
        "--select", metavar="RULES",
        help="comma-separated rule ids to run (default: all)",
    )
    ap.add_argument(
        "--json", action="store_true", help="emit findings as JSON",
    )
    ap.add_argument(
        "--list-rules", action="store_true",
        help="print the rule ids and summaries, then exit",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule_id, (summary, _impl) in RULES.items():
            print(f"{rule_id}  {summary}")
        return 0

    select = None
    if args.select:
        select = [r.strip() for r in args.select.split(",") if r.strip()]
    try:
        findings = lint_paths(args.paths or None, select=select)
    except (ValueError, OSError, SyntaxError) as e:
        print(f"dgenlint: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(
            [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "message": f.message}
                for f in findings
            ],
            indent=1,
        ))
    else:
        for f in findings:
            print(f)
        n = len(findings)
        print(
            f"dgenlint: {n} finding{'s' if n != 1 else ''}"
            if n else "dgenlint: clean",
            file=sys.stderr,
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
