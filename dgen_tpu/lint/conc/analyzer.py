"""dgenlint-conc analyzer: per-class concurrency models.

The C rules (:mod:`dgen_tpu.lint.conc.crules`) need, for every class in
the concurrent host modules, answers to four questions the plain AST
does not give directly:

* **which methods run on which thread** — the *thread-entry* set:
  methods handed to ``threading.Thread(target=...)``, executor
  ``.submit`` callbacks, ``http.server`` handler verbs (every request
  its own thread), plus the closure of plain ``self.*()`` calls from
  those entries.  Entries propagate one level across classes through
  typed attributes (``self._front = front`` with a ``FleetFront``
  annotation: the autoscaler's control thread *drives*
  ``FleetFront.pressure``, so ``pressure`` is a thread entry of
  ``FleetFront`` too).
* **which locks are held on which AST paths** — ``with self._lock:``
  dominance, tracked through nested withs, conditionals, loops and
  try blocks (``self._lock``/``self._cv``/... discovered from
  ``self.X = threading.Lock()|RLock()|Condition()`` assignments).
* **what each method acquires, transitively** — for the static
  lock-order graph (C3), including one level of cross-class calls
  through typed attributes (``self.pool.checkout()`` acquiring
  ``HTTPPool._lock`` while ``ReplicaSupervisor._lock`` is held is an
  order edge between two classes).
* **what each method may block on, transitively** — for C2
  (probe-under-lock), so ``with self._lock: self._probe()`` is flagged
  when ``_probe`` does the HTTP round-trip three frames down.

Everything here is an over-approximation in the same spirit as the jit
reachability closure in :mod:`dgen_tpu.lint.core`: a method *referenced*
from a thread entry counts as running on that thread.  The rules then
err strict, and intentional lock-free designs opt out per line
(``# dgenlint: disable=C1`` with a why-comment) or through the
documented :data:`dgen_tpu.lint.conc.crules.LOCKFREE_ALLOWLIST`.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from dgen_tpu.lint.core import ModuleInfo, dotted

#: ``self.X = <factory>()`` classifications (resolved through imports)
LOCK_FACTORIES: Dict[str, str] = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
}
SEM_FACTORIES = ("threading.Semaphore", "threading.BoundedSemaphore")
EVENT_FACTORIES = ("threading.Event",)
QUEUE_FACTORIES = (
    "queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
    "queue.SimpleQueue", "multiprocessing.Queue",
)

#: container-mutating method names: ``self.X.append(...)`` is a write
#: to ``X`` for rule purposes
MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "update", "setdefault", "pop", "popleft", "popitem", "remove",
    "discard", "clear", "sort", "reverse",
})


def resolve(m: ModuleInfo, target: str) -> str:
    """Expand the leading alias of a dotted name through the module's
    imports (``th.Lock`` -> ``threading.Lock``)."""
    head, _, rest = target.partition(".")
    base = m.imports.get(head, head)
    return f"{base}.{rest}" if rest else base


def _self_attr(node: ast.AST) -> Optional[str]:
    """``'x'`` for a ``self.x`` expression, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _self_root(node: ast.AST) -> Optional[str]:
    """Root attribute of a ``self.``-rooted chain: ``'a'`` for
    ``self.a``, ``self.a.b``, ``self.a[k]``, ``self.a[k].c`` ..."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        a = _self_attr(node)
        if a is not None:
            return a
        node = node.value
    return None


# ---------------------------------------------------------------------------
# per-method facts
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Access:
    attr: str
    line: int
    held: FrozenSet[str]
    kind: str                 # "read" | "write"
    assign: bool = False      # plain ``self.X = ...`` (vs container mutation)


@dataclasses.dataclass
class Acquire:
    lock: str
    line: int
    held_before: FrozenSet[str]


@dataclasses.dataclass
class CallSite:
    target: str               # raw dotted ("self._probe", "time.sleep")
    line: int
    held: FrozenSet[str]
    node: ast.Call


@dataclasses.dataclass
class CondEvent:
    """One ``if`` whose test inspects ``self.X`` (membership / truth /
    is-None) — the raw material for C4 check-then-act and C5 lazy
    init."""

    kind: str                 # "membership" | "truth" | "none"
    attr: str
    line: int
    held: FrozenSet[str]
    body_writes: List[Access]
    rechecked_under_lock: bool


@dataclasses.dataclass
class ThreadSpawn:
    line: int
    target: Optional[str]     # dotted target= ("self._loop"), if any
    daemon_set: bool
    assigned: Optional[str]   # "self:attr" | "local:name" | None


@dataclasses.dataclass
class MethodModel:
    name: str
    node: ast.AST
    accesses: List[Access] = dataclasses.field(default_factory=list)
    acquires: List[Acquire] = dataclasses.field(default_factory=list)
    calls: List[CallSite] = dataclasses.field(default_factory=list)
    conds: List[CondEvent] = dataclasses.field(default_factory=list)
    spawns: List[ThreadSpawn] = dataclasses.field(default_factory=list)
    joins: Set[str] = dataclasses.field(default_factory=set)
    daemon_marks: Set[str] = dataclasses.field(default_factory=set)


class ClassModel:
    """One class's concurrency-relevant facts."""

    def __init__(self, module: ModuleInfo, node: Optional[ast.ClassDef]):
        self.module = module
        self.node = node
        self.name = node.name if node is not None else "<module>"
        self.qualname = f"{module.modname}.{self.name}"
        self.bases: List[str] = (
            [d for d in (dotted(b) for b in node.bases) if d]
            if node is not None else []
        )
        self.lock_attrs: Dict[str, str] = {}     # attr -> Lock/RLock/Condition
        self.sem_attrs: Set[str] = set()
        self.attr_kinds: Dict[str, str] = {}     # attr -> Queue/Thread/Event
        self.attr_types_raw: Dict[str, str] = {} # attr -> resolved dotted class
        self.attr_types: Dict[str, "ClassModel"] = {}
        self.methods: Dict[str, MethodModel] = {}
        #: entry name -> concurrent? (True: several instances of this
        #: entry can run at once, e.g. per-request handler threads)
        self.entries: Dict[str, bool] = {}
        #: method -> frozenset of entry labels whose threads reach it
        #: (empty = only ever runs on the caller's thread)
        self.method_groups: Dict[str, FrozenSet[str]] = {}

    def is_handler_class(self) -> bool:
        return self.name.lower().endswith("handler") or any(
            b.split(".")[-1].lower().endswith("handler") for b in self.bases
        )

    def concurrent_entry_in(self, group: FrozenSet[str]) -> bool:
        return any(self.entries.get(e, False) for e in group)


# ---------------------------------------------------------------------------
# the walker
# ---------------------------------------------------------------------------

class _MethodWalker:
    """One pass over a method body tracking the ``with self.<lock>``
    held-set down every AST path (nested defs/classes excluded — they
    get their own model or none)."""

    def __init__(self, cls: ClassModel, mm: MethodModel) -> None:
        self.cls = cls
        self.m = cls.module
        self.mm = mm
        self._pending_assign: Optional[str] = None

    def run(self) -> None:
        self._stmts(self.mm.node.body, frozenset())

    # -- statements -----------------------------------------------------
    def _stmts(self, body, held: FrozenSet[str]) -> None:
        for st in body:
            self._stmt(st, held)

    def _stmt(self, node: ast.stmt, held: FrozenSet[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in node.items:
                la = _self_attr(item.context_expr)
                if la is not None and la in self.cls.lock_attrs:
                    self.mm.acquires.append(
                        Acquire(la, node.lineno, frozenset(inner)))
                    inner.add(la)
                else:
                    self._expr(item.context_expr, held)
            self._stmts(node.body, frozenset(inner))
            return
        if isinstance(node, ast.If):
            self._expr(node.test, held)
            ev = self._cond_event(node, held)
            if ev is not None:
                self.mm.conds.append(ev)
            self._stmts(node.body, held)
            self._stmts(node.orelse, held)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self._expr(node.iter, held)
            self._target_write(node.target, held)
            self._stmts(node.body, held)
            self._stmts(node.orelse, held)
            return
        if isinstance(node, ast.While):
            self._expr(node.test, held)
            self._stmts(node.body, held)
            self._stmts(node.orelse, held)
            return
        if isinstance(node, ast.Try):
            self._stmts(node.body, held)
            for h in node.handlers:
                self._stmts(h.body, held)
            self._stmts(node.orelse, held)
            self._stmts(node.finalbody, held)
            return
        if isinstance(node, ast.Assign):
            self._handle_assign(node, held)
            return
        if isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self._assign_one(node.target, node.value, held)
                self._expr(node.value, held)
            return
        if isinstance(node, ast.AugAssign):
            self._target_write(node.target, held)
            # ``self.x += 1`` reads x too
            root = _self_root(node.target)
            if root is not None:
                self.mm.accesses.append(
                    Access(root, node.lineno, held, "read"))
            self._expr(node.value, held)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                self._target_write(t, held)
            return
        # Expr/Return/Raise/Assert/...: walk child expressions; walk
        # child statements (shouldn't exist here) defensively
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child, held)
            elif isinstance(child, ast.stmt):
                self._stmt(child, held)

    # -- assignment targets ---------------------------------------------
    def _target_write(self, target: ast.AST, held: FrozenSet[str],
                      assign: bool = False) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._target_write(el, held, assign)
            return
        root = _self_root(target)
        if root is not None:
            # ``self.x = ...`` is a plain (re)bind; ``self.x[k] = ...``
            # and ``self.x.y = ...`` mutate the object x holds
            plain = assign and _self_attr(target) is not None
            self.mm.accesses.append(Access(
                root, target.lineno, held, "write", assign=plain))

    def _handle_assign(self, node: ast.Assign, held: FrozenSet[str]) -> None:
        for t in node.targets:
            self._assign_one(t, node.value, held)
        self._expr(node.value, held)
        self._pending_assign = None

    def _assign_one(self, target: ast.AST, value: ast.AST,
                    held: FrozenSet[str]) -> None:
        # ``t.daemon = True`` / ``self._thread.daemon = True``
        if isinstance(target, ast.Attribute) and target.attr == "daemon":
            recv = dotted(target.value)
            if recv:
                self.mm.daemon_marks.add(recv)
            return
        self._target_write(target, held, assign=True)
        attr = _self_attr(target)
        name = target.id if isinstance(target, ast.Name) else None
        if isinstance(value, ast.Call):
            d = dotted(value.func)
            r = resolve(self.m, d) if d else None
            if r is not None and attr is not None:
                if r in LOCK_FACTORIES:
                    self.cls.lock_attrs[attr] = LOCK_FACTORIES[r]
                elif r in SEM_FACTORIES:
                    self.cls.sem_attrs.add(attr)
                elif r in EVENT_FACTORIES:
                    self.cls.attr_kinds[attr] = "Event"
                elif r in QUEUE_FACTORIES:
                    self.cls.attr_kinds[attr] = "Queue"
                elif r == "threading.Thread":
                    self.cls.attr_kinds[attr] = "Thread"
                elif r.rpartition(".")[2][:1].isupper():
                    # ``self.pool = HTTPPool(...)``: a typed attribute
                    self.cls.attr_types_raw.setdefault(attr, r)
            if r == "threading.Thread":
                self._pending_assign = (
                    f"self:{attr}" if attr is not None
                    else (f"local:{name}" if name else None)
                )
        elif isinstance(value, ast.Name) and attr is not None:
            # ``self.sup = supervisor``: typed via the __init__
            # annotation (resolved by the class builder)
            self.cls.attr_types_raw.setdefault(
                attr, f"<param>{value.id}")

    # -- expressions ----------------------------------------------------
    def _expr(self, node: ast.expr, held: FrozenSet[str]) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and isinstance(
                    sub.ctx, ast.Load):
                a = _self_attr(sub)
                if a is not None:
                    self.mm.accesses.append(
                        Access(a, sub.lineno, held, "read"))
            elif isinstance(sub, ast.Call):
                self._call(sub, held)

    def _call(self, node: ast.Call, held: FrozenSet[str]) -> None:
        d = dotted(node.func)
        if not d:
            return
        self.mm.calls.append(CallSite(d, node.lineno, held, node))
        parts = d.split(".")
        # container mutation through a method: self.X.append(...)
        if len(parts) == 3 and parts[0] == "self" and parts[2] in MUTATORS:
            self.mm.accesses.append(
                Access(parts[1], node.lineno, held, "write"))
        # thread spawn
        r = resolve(self.m, d)
        if r == "threading.Thread":
            target = None
            daemon_set = False
            for kw in node.keywords:
                if kw.arg == "target":
                    target = dotted(kw.value)
                elif kw.arg == "daemon":
                    daemon_set = True
            self.mm.spawns.append(ThreadSpawn(
                node.lineno, target, daemon_set, self._pending_assign))
            if target and target.startswith("self."):
                self.cls.entries.setdefault(target[5:], False)
        # executor submit: first arg is the entry
        elif parts[-1] == "submit" and node.args:
            ref = dotted(node.args[0])
            if ref and ref.startswith("self."):
                self.cls.entries.setdefault(ref[5:], False)
        # thread join bookkeeping (C6); exclude str.join by arg shape:
        # a real join takes no args or a single numeric/None timeout
        elif parts[-1] == "join":
            timeoutish = (
                not node.args
                or (len(node.args) == 1
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, (int, float,
                                                        type(None))))
            ) and all(kw.arg == "timeout" for kw in node.keywords)
            recv = ".".join(parts[:-1])
            if timeoutish and recv:
                self.mm.joins.add(recv)

    # -- if-test patterns (C4/C5) ---------------------------------------
    def _cond_test(self, test: ast.expr) -> Optional[Tuple[str, str]]:
        """(kind, attr) when the test inspects ``self.X``."""
        if isinstance(test, ast.Compare) and len(test.ops) == 1:
            op, comp = test.ops[0], test.comparators[0]
            if isinstance(op, (ast.In, ast.NotIn)):
                a = _self_attr(comp)
                if a is not None:
                    return ("membership", a)
            if isinstance(op, (ast.Is, ast.IsNot)) and isinstance(
                    comp, ast.Constant) and comp.value is None:
                a = _self_attr(test.left)
                if a is not None:
                    return ("none", a)
                # ``self.X.get(k) is None``
                if isinstance(test.left, ast.Call):
                    d = dotted(test.left.func)
                    if d and d.startswith("self.") and d.endswith(".get"):
                        return ("membership", d.split(".")[1])
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            a = _self_attr(test.operand)
            if a is not None:
                return ("truth", a)
        a = _self_attr(test)
        if a is not None:
            return ("truth", a)
        return None

    def _cond_event(self, node: ast.If,
                    held: FrozenSet[str]) -> Optional[CondEvent]:
        hit = self._cond_test(node.test)
        if hit is None:
            return None
        kind, attr = hit
        writes: List[Access] = []
        rechecked = False

        def scan(body, inner_held):
            nonlocal rechecked
            for st in body:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                    continue
                if isinstance(st, (ast.With, ast.AsyncWith)):
                    h2 = set(inner_held)
                    for item in st.items:
                        la = _self_attr(item.context_expr)
                        if la is not None and la in self.cls.lock_attrs:
                            h2.add(la)
                    scan(st.body, frozenset(h2))
                    continue
                if isinstance(st, ast.If):
                    # the double-checked-locking recheck: same attr
                    # re-tested under a lock before the assignment
                    h2 = self._cond_test(st.test)
                    if h2 is not None and h2[1] == attr and inner_held:
                        rechecked = True
                    scan(st.body, inner_held)
                    scan(st.orelse, inner_held)
                    continue
                for sub in ast.walk(st):
                    root = None
                    plain = False
                    if isinstance(sub, (ast.Assign, ast.AugAssign)):
                        targets = (sub.targets
                                   if isinstance(sub, ast.Assign)
                                   else [sub.target])
                        for t in targets:
                            root = _self_root(t)
                            if root == attr:
                                plain = _self_attr(t) is not None and \
                                    isinstance(sub, ast.Assign)
                                writes.append(Access(
                                    attr, sub.lineno, inner_held,
                                    "write", assign=plain))
                    elif isinstance(sub, ast.Delete):
                        for t in sub.targets:
                            if _self_root(t) == attr:
                                writes.append(Access(
                                    attr, sub.lineno, inner_held, "write"))
                    elif isinstance(sub, ast.Call):
                        d = dotted(sub.func)
                        if d:
                            p = d.split(".")
                            if (len(p) == 3 and p[0] == "self"
                                    and p[1] == attr and p[2] in MUTATORS):
                                writes.append(Access(
                                    attr, sub.lineno, inner_held, "write"))

        scan(node.body, held)
        if not writes:
            return None
        return CondEvent(kind, attr, node.lineno, held, writes, rechecked)


# ---------------------------------------------------------------------------
# the index
# ---------------------------------------------------------------------------

def _build_models(m: ModuleInfo) -> List[ClassModel]:
    out: List[ClassModel] = []
    # module-level functions get a pseudo-class (C6 needs their spawns)
    pseudo = ClassModel(m, None)
    for node in m.tree.body:
        if isinstance(node, ast.ClassDef):
            cls = ClassModel(m, node)
            # pass 1: attribute classification (locks must be known
            # before held-tracking makes sense)
            for meth in node.body:
                if not isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                mm = MethodModel(meth.name, meth)
                cls.methods[meth.name] = mm
            for mm in cls.methods.values():
                for sub in ast.walk(mm.node):
                    if isinstance(sub, ast.Assign) and isinstance(
                            sub.value, ast.Call):
                        d = dotted(sub.value.func)
                        r = resolve(m, d) if d else None
                        for t in sub.targets:
                            a = _self_attr(t)
                            if a is None or r is None:
                                continue
                            if r in LOCK_FACTORIES:
                                cls.lock_attrs[a] = LOCK_FACTORIES[r]
                            elif r in SEM_FACTORIES:
                                cls.sem_attrs.add(a)
            # pass 2: the full walk
            for mm in cls.methods.values():
                _MethodWalker(cls, mm).run()
            # handler classes: every request runs on its own thread
            if cls.is_handler_class():
                for name in cls.methods:
                    if not name.startswith("__"):
                        cls.entries[name] = True
            else:
                for name in cls.methods:
                    low = name.lower()
                    if low.startswith("do_") or "handle" in low:
                        cls.entries[name] = True
            # __init__ annotations type the params for attr_types
            init = cls.methods.get("__init__")
            ann: Dict[str, str] = {}
            if init is not None:
                for arg in list(init.node.args.args) + list(
                        init.node.args.kwonlyargs):
                    if arg.annotation is None:
                        continue
                    d = dotted(arg.annotation)
                    if d is None and isinstance(arg.annotation,
                                                ast.Constant) and \
                            isinstance(arg.annotation.value, str):
                        d = arg.annotation.value
                    if d:
                        ann[arg.arg] = resolve(m, d)
            for attr, raw in list(cls.attr_types_raw.items()):
                if raw.startswith("<param>"):
                    p = ann.get(raw[len("<param>"):])
                    if p:
                        cls.attr_types_raw[attr] = p
                    else:
                        del cls.attr_types_raw[attr]
            out.append(cls)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mm = MethodModel(node.name, node)
            pseudo.methods[node.name] = mm
            _MethodWalker(pseudo, mm).run()
    if pseudo.methods:
        out.append(pseudo)
    return out


class ConcIndex:
    """All class models plus the cross-class closures the rules use."""

    def __init__(self, modules) -> None:
        self.modules: List[ModuleInfo] = list(modules)
        self.classes: Dict[str, ClassModel] = {}
        for m in self.modules:
            for cls in _build_models(m):
                self.classes[cls.qualname] = cls
        self._resolve_attr_types()
        self._close_entries()
        self._close_acquires()
        self._close_blocking()
        self._infer_call_held()

    # -- helpers --------------------------------------------------------
    def classes_in(self, m: ModuleInfo) -> List[ClassModel]:
        return [c for c in self.classes.values() if c.module is m]

    def _lookup_class(self, resolved: str) -> Optional[ClassModel]:
        if resolved in self.classes:
            return self.classes[resolved]
        tail = resolved.rpartition(".")[2]
        hits = [c for q, c in self.classes.items()
                if q.rpartition(".")[2] == tail and c.node is not None]
        return hits[0] if len(hits) == 1 else None

    def _resolve_attr_types(self) -> None:
        for cls in self.classes.values():
            for attr, raw in cls.attr_types_raw.items():
                hit = self._lookup_class(raw)
                if hit is not None and hit is not cls:
                    cls.attr_types[attr] = hit

    # -- thread-entry closure -------------------------------------------
    def _groups_for(self, cls: ClassModel) -> Dict[str, FrozenSet[str]]:
        groups: Dict[str, Set[str]] = {n: set() for n in cls.methods}
        for entry in cls.entries:
            if entry not in cls.methods:
                continue
            seen: Set[str] = set()
            work = [entry]
            while work:
                name = work.pop()
                if name in seen or name not in cls.methods:
                    continue
                seen.add(name)
                groups[name].add(entry)
                for site in cls.methods[name].calls:
                    p = site.target.split(".")
                    if len(p) == 2 and p[0] == "self" and \
                            p[1] in cls.methods:
                        work.append(p[1])
        return {n: frozenset(g) for n, g in groups.items()}

    def _close_entries(self) -> None:
        """Entry groups per class, with one-level cross-class
        propagation through typed attributes, to a fixpoint."""
        for cls in self.classes.values():
            cls.method_groups = self._groups_for(cls)
        for _ in range(6):
            changed = False
            for cls in self.classes.values():
                for name, mm in cls.methods.items():
                    group = cls.method_groups.get(name, frozenset())
                    if not group:
                        continue
                    conc = cls.concurrent_entry_in(group)
                    for site in mm.calls:
                        p = site.target.split(".")
                        if len(p) != 3 or p[0] != "self":
                            continue
                        target_cls = cls.attr_types.get(p[1])
                        if target_cls is None or p[2] not in \
                                target_cls.methods:
                            continue
                        prev = target_cls.entries.get(p[2])
                        if prev is None:
                            target_cls.entries[p[2]] = conc
                            changed = True
                        elif conc and not prev:
                            target_cls.entries[p[2]] = True
                            changed = True
            if not changed:
                break
            for cls in self.classes.values():
                cls.method_groups = self._groups_for(cls)

    # -- transitive acquisitions (C3) -----------------------------------
    def _close_acquires(self) -> None:
        """``self.acquire_closure[(clsqual, meth)]`` = set of lock nodes
        (``Class.attr``) the method may acquire, transitively through
        self-calls and typed-attribute calls."""
        ta: Dict[Tuple[str, str], Set[str]] = {}
        for cls in self.classes.values():
            for name, mm in cls.methods.items():
                ta[(cls.qualname, name)] = {
                    f"{cls.name}.{a.lock}" for a in mm.acquires
                }
        for _ in range(8):
            changed = False
            for cls in self.classes.values():
                for name, mm in cls.methods.items():
                    cur = ta[(cls.qualname, name)]
                    before = len(cur)
                    for site in mm.calls:
                        p = site.target.split(".")
                        if len(p) == 2 and p[0] == "self" and \
                                p[1] in cls.methods:
                            cur |= ta.get((cls.qualname, p[1]), set())
                        elif len(p) == 3 and p[0] == "self":
                            tc = cls.attr_types.get(p[1])
                            if tc is not None and p[2] in tc.methods:
                                cur |= ta.get((tc.qualname, p[2]), set())
                    if len(cur) != before:
                        changed = True
            if not changed:
                break
        self.acquire_closure = ta

    # -- transitive blocking (C2) ---------------------------------------
    def classify_blocking(self, cls: ClassModel,
                          site: CallSite) -> Optional[str]:
        """Why this call may block (message fragment), or None."""
        d = site.target
        r = resolve(cls.module, d)
        if r == "time.sleep":
            return "time.sleep()"
        if r in ("subprocess.run", "subprocess.call",
                 "subprocess.check_call", "subprocess.check_output"):
            return f"{r}() subprocess wait"
        if r.endswith(".http_json") or r == "http_json" or r in (
                "urllib.request.urlopen", "socket.create_connection"):
            return "HTTP round-trip"
        parts = d.split(".")
        last = parts[-1]
        recv_attr = parts[1] if (len(parts) == 3 and
                                 parts[0] == "self") else None
        if last == "wait":
            if recv_attr is not None and \
                    cls.lock_attrs.get(recv_attr) == "Condition":
                # cv.wait releases its own lock; it blocks-under-lock
                # only w.r.t. OTHER locks — the caller check handles it
                return ("Condition.wait while holding another lock"
                        if site.held - {recv_attr} else None)
            return "blocking .wait()"
        if last == "join":
            timeoutish = (
                not site.node.args
                or (len(site.node.args) == 1
                    and isinstance(site.node.args[0], ast.Constant))
            )
            return "Thread/process join" if timeoutish else None
        if last in ("get", "put") and recv_attr is not None and \
                cls.attr_kinds.get(recv_attr) == "Queue":
            for kw in site.node.keywords:
                if kw.arg == "block" and isinstance(
                        kw.value, ast.Constant) and not kw.value.value:
                    return None
            return f"blocking Queue.{last}()"
        if last == "acquire" and recv_attr in cls.sem_attrs:
            return "semaphore acquire"
        if last == "result" and len(site.node.args) <= 1:
            return "Future.result() wait"
        return None

    def _close_blocking(self) -> None:
        """``self.blocking_closure[(clsqual, meth)]`` = (desc, line) of
        one blocking call the method may reach, else None."""
        bc: Dict[Tuple[str, str], Optional[Tuple[str, int]]] = {}
        for cls in self.classes.values():
            for name, mm in cls.methods.items():
                hit = None
                for site in mm.calls:
                    why = self.classify_blocking(cls, site)
                    if why is not None and "Condition.wait" not in why:
                        hit = (why, site.line)
                        break
                bc[(cls.qualname, name)] = hit
        for _ in range(8):
            changed = False
            for cls in self.classes.values():
                for name, mm in cls.methods.items():
                    if bc[(cls.qualname, name)] is not None:
                        continue
                    for site in mm.calls:
                        p = site.target.split(".")
                        sub = None
                        if len(p) == 2 and p[0] == "self" and \
                                p[1] in cls.methods:
                            sub = bc.get((cls.qualname, p[1]))
                        elif len(p) == 3 and p[0] == "self":
                            tc = cls.attr_types.get(p[1])
                            if tc is not None and p[2] in tc.methods:
                                sub = bc.get((tc.qualname, p[2]))
                        if sub is not None:
                            bc[(cls.qualname, name)] = (
                                f"{sub[0]} via {site.target}()", site.line)
                            changed = True
                            break
            if not changed:
                break
        self.blocking_closure = bc

    # -- call-site lock context -----------------------------------------
    def _infer_call_held(self) -> None:
        """``self.call_held[(clsqual, meth)]`` = locks held at EVERY
        intra-class call site of a private helper (the Microbatcher
        ``_take_batch`` pattern: documented "under _cv", never takes
        the lock itself).  The intersection is sound: an access in the
        helper is lock-protected iff all callers hold the lock."""
        ch: Dict[Tuple[str, str], Optional[FrozenSet[str]]] = {}
        for cls in self.classes.values():
            sites: Dict[str, List[FrozenSet[str]]] = {}
            for mm in cls.methods.values():
                for site in mm.calls:
                    p = site.target.split(".")
                    if len(p) == 2 and p[0] == "self" and \
                            p[1] in cls.methods:
                        sites.setdefault(p[1], []).append(site.held)
            for name in cls.methods:
                held_sets = sites.get(name)
                if (held_sets and name.startswith("_")
                        and not name.startswith("__")
                        and name not in cls.entries):
                    common = frozenset.intersection(*held_sets)
                    ch[(cls.qualname, name)] = common or None
                else:
                    ch[(cls.qualname, name)] = None
        self.call_held = ch

    def effective_held(self, cls: ClassModel, meth: str,
                       held: FrozenSet[str]) -> FrozenSet[str]:
        """A held-set widened by the caller-side lock context (private
        helpers whose every call site holds the lock)."""
        extra = self.call_held.get((cls.qualname, meth))
        return held | extra if extra else held

    def callee_of(self, cls: ClassModel,
                  site: CallSite) -> Optional[Tuple[str, str]]:
        """(class qualname, method) a self-rooted call resolves to."""
        p = site.target.split(".")
        if len(p) == 2 and p[0] == "self" and p[1] in cls.methods:
            return (cls.qualname, p[1])
        if len(p) == 3 and p[0] == "self":
            tc = cls.attr_types.get(p[1])
            if tc is not None and p[2] in tc.methods:
                return (tc.qualname, p[2])
        return None
