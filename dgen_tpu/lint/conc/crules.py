"""dgenlint-conc rules C1-C6 over :class:`~.analyzer.ConcIndex` models.

Every rule is a generator ``rule(module, cidx) -> (line, message)``,
registered in :data:`CONC_RULES` (ids + summaries come from the
dependency-free :mod:`dgen_tpu.lint.conc_ids` table, same contract as
the J rules).  :func:`run_conc_rules` applies the standard suppression
mechanics (``# dgenlint: disable=C1`` on the flagged line, with a
why-comment — docs/lint.md "The concurrency tier").
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Iterable, List, Optional, Tuple

from dgen_tpu.lint.conc.analyzer import (
    Access,
    ClassModel,
    ConcIndex,
)
from dgen_tpu.lint.conc_ids import CONC_RULE_SUMMARIES
from dgen_tpu.lint.core import Finding, ModuleInfo

RuleHit = Tuple[int, str]

#: Intentional lock-free shared state, ``"ClassName.attr" -> why``.
#: Every entry must carry the safety argument; docs/lint.md lists them
#: verbatim.  Prefer a line suppression with a why-comment for
#: one-off cases — the allowlist is for patterns the design DOCS
#: already promise (single-writer GIL-atomic snapshots).
LOCKFREE_ALLOWLIST: Dict[str, str] = {
    "FleetFront._metricz": (
        "single-writer design: only the scrape thread rebinds whole "
        "per-port tuples; readers take one dict() snapshot, which is "
        "one atomic C-level copy under the GIL (docs/serve.md)"
    ),
}


def _group(cls: ClassModel, meth: str) -> FrozenSet[str]:
    return cls.method_groups.get(meth, frozenset())


def _conflicting(cls: ClassModel, wg: FrozenSet[str],
                 rg: FrozenSet[str]) -> bool:
    """Two accesses race when their thread groups differ, or share a
    group whose entry admits concurrent instances (per-request handler
    threads)."""
    if wg != rg:
        return True
    if not wg:
        return False
    return cls.concurrent_entry_in(wg)


def _describe_group(g: FrozenSet[str]) -> str:
    return "/".join(sorted(g)) if g else "caller thread"


def _attr_accesses(cls: ClassModel) -> Dict[str, List[Tuple[str, Access]]]:
    out: Dict[str, List[Tuple[str, Access]]] = {}
    for meth, mm in cls.methods.items():
        for acc in mm.accesses:
            out.setdefault(acc.attr, []).append((meth, acc))
    return out


def _real_classes(m: ModuleInfo, cidx: ConcIndex) -> List[ClassModel]:
    return [c for c in cidx.classes_in(m) if c.node is not None]


# ---------------------------------------------------------------------------
# C1 — cross-thread write without the class lock
# ---------------------------------------------------------------------------

def rule_c1(m: ModuleInfo, cidx: ConcIndex) -> Iterable[RuleHit]:
    """A write to ``self.X`` outside any ``with self.<lock>`` when some
    *other* thread entry reads ``X``: the scrape/read dict-race class
    (PR 9).  ``__init__`` writes are exempt (happen-before the thread
    start); intentional single-writer designs go through
    :data:`LOCKFREE_ALLOWLIST` or a line suppression with a
    why-comment."""
    for cls in _real_classes(m, cidx):
        if not cls.entries:
            continue
        if cls.is_handler_class():
            # http.server builds one handler INSTANCE per connection:
            # self.* is per-thread by construction — shared state goes
            # through self.server.*, which belongs to the server's class
            continue
        for attr, accs in _attr_accesses(cls).items():
            if attr in cls.lock_attrs or attr in cls.sem_attrs:
                continue
            if cls.attr_kinds.get(attr) in ("Event", "Queue", "Thread"):
                continue  # internally synchronized / owner handles
            if f"{cls.name}.{attr}" in LOCKFREE_ALLOWLIST:
                continue
            reads = [(meth, a) for meth, a in accs
                     if a.kind == "read" and meth != "__init__"]
            seen_lines = set()
            for meth, w in accs:
                if w.kind != "write" or meth == "__init__":
                    continue
                if cidx.effective_held(cls, meth, w.held):
                    continue
                if w.line in seen_lines:
                    continue
                wg = _group(cls, meth)
                for rmeth, r in reads:
                    if r.line == w.line and rmeth == meth:
                        continue
                    rg = _group(cls, rmeth)
                    if _conflicting(cls, wg, rg):
                        seen_lines.add(w.line)
                        locks = ", ".join(
                            f"self.{k}" for k in sorted(cls.lock_attrs)
                        ) or "a lock (the class has none)"
                        yield w.line, (
                            f"`{cls.name}.{attr}` written on "
                            f"{_describe_group(wg)} without a lock but "
                            f"read from {_describe_group(rg)} "
                            f"(`{rmeth}`, line {r.line}) — guard both "
                            f"sides with {locks}, or document the "
                            "lock-free design (allowlist / suppression "
                            "with a why-comment)"
                        )
                        break


# ---------------------------------------------------------------------------
# C2 — blocking call while a lock is held
# ---------------------------------------------------------------------------

def rule_c2(m: ModuleInfo, cidx: ConcIndex) -> Iterable[RuleHit]:
    """A blocking call (HTTP round-trip, subprocess wait,
    ``time.sleep``, thread join, blocking queue op) on a path where a
    lock is held — the probe-under-the-supervisor-lock stall class
    fixed by hand in PR 11, now a gate.  One level of interprocedural
    closure: ``with self._lock: self._probe()`` is flagged when
    ``_probe`` blocks three frames down."""
    for cls in _real_classes(m, cidx):
        for meth, mm in cls.methods.items():
            seen = set()
            for site in mm.calls:
                if not site.held:
                    continue
                why = cidx.classify_blocking(cls, site)
                if why is None:
                    callee = cidx.callee_of(cls, site)
                    if callee is not None:
                        sub = cidx.blocking_closure.get(callee)
                        if sub is not None:
                            why = f"{sub[0]} inside `{site.target}`"
                if why is None or (site.line, why) in seen:
                    continue
                seen.add((site.line, why))
                held = ", ".join(f"self.{h}" for h in sorted(site.held))
                yield site.line, (
                    f"{why} while holding {held} in "
                    f"`{cls.name}.{meth}` — every other thread "
                    "contending on that lock stalls for the full "
                    "blocking latency; move the call outside the "
                    "critical section (snapshot under the lock, act "
                    "after release)"
                )


# ---------------------------------------------------------------------------
# C3 — lock-order cycles / non-reentrant re-acquire
# ---------------------------------------------------------------------------

def _order_edges(cidx: ConcIndex):
    """(from_node, to_node) -> (module, line) witness over all classes,
    plus direct self-deadlocks [(module, line, lockname)]."""
    edges: Dict[Tuple[str, str], Tuple[ModuleInfo, int]] = {}
    self_deadlocks: List[Tuple[ModuleInfo, int, str]] = []
    for cls in cidx.classes.values():
        if cls.node is None:
            continue
        for meth, mm in cls.methods.items():
            for acq in mm.acquires:
                node = f"{cls.name}.{acq.lock}"
                if acq.lock in acq.held_before:
                    # re-acquiring a held lock: an RLock cannot block
                    # here, so it orders nothing; a plain Lock is a
                    # single-thread self-deadlock
                    if cls.lock_attrs.get(acq.lock) == "Lock":
                        self_deadlocks.append(
                            (cls.module, acq.line, node))
                    continue
                for h in acq.held_before:
                    edges.setdefault(
                        (f"{cls.name}.{h}", node), (cls.module, acq.line))
            for site in mm.calls:
                if not site.held:
                    continue
                callee = cidx.callee_of(cls, site)
                if callee is None:
                    continue
                held_nodes = {f"{cls.name}.{h}" for h in site.held}
                for node in cidx.acquire_closure.get(callee, ()):
                    if node in held_nodes:
                        # the callee re-acquires a lock this call site
                        # already holds: an RLock cannot block (orders
                        # nothing); a non-reentrant Lock deadlocks
                        # against its own thread
                        if cls.lock_attrs.get(
                                node.rpartition(".")[2]) == "Lock":
                            self_deadlocks.append(
                                (cls.module, site.line, node))
                        continue
                    for h in site.held:
                        edges.setdefault(
                            (f"{cls.name}.{h}", node),
                            (cls.module, site.line))
    return edges, self_deadlocks


def _cycles(edges) -> List[List[str]]:
    graph: Dict[str, List[str]] = {}
    for a, b in edges:
        graph.setdefault(a, []).append(b)
    out: List[List[str]] = []
    seen_cycles = set()
    WHITE, GREY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}
    path: List[str] = []

    def dfs(n: str) -> None:
        color[n] = GREY
        path.append(n)
        for nxt in graph.get(n, ()):
            c = color.get(nxt, WHITE)
            if c == GREY:
                cyc = path[path.index(nxt):] + [nxt]
                key = frozenset(cyc)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    out.append(cyc)
            elif c == WHITE:
                dfs(nxt)
        path.pop()
        color[n] = BLACK

    for n in sorted(graph):
        if color.get(n, WHITE) == WHITE:
            dfs(n)
    return out


def rule_c3(m: ModuleInfo, cidx: ConcIndex) -> Iterable[RuleHit]:
    """A cycle in the static lock-acquisition order graph (two threads
    interleaving the witnessed paths deadlock), or a non-reentrant
    ``threading.Lock`` re-acquired on a path that already holds it
    (single-thread self-deadlock).  The runtime sentinel
    (:mod:`dgen_tpu.utils.locktrace`) checks the same property on the
    *observed* graph while the drills run."""
    edges, self_deadlocks = _order_edges(cidx)
    for mod, line, node in self_deadlocks:
        if mod is m:
            yield line, (
                f"non-reentrant lock `{node}` acquired on a path that "
                "already holds it — this thread deadlocks against "
                "itself; use RLock or restructure so the lock is "
                "taken once"
            )
    for cyc in _cycles(edges):
        for a, b in zip(cyc, cyc[1:]):
            mod, line = edges[(a, b)]
            if mod is m:
                yield line, (
                    "lock-order cycle "
                    + " -> ".join(cyc)
                    + f" (this line witnesses {a} -> {b}): two threads "
                    "taking the locks in opposing order deadlock — "
                    "pick one global order and acquire in it everywhere"
                )


# ---------------------------------------------------------------------------
# C4 — check-then-act outside a lock
# ---------------------------------------------------------------------------

def _attr_shared(cidx: ConcIndex, cls: ClassModel, attr: str) -> bool:
    """Shared iff accessed from two distinct thread groups, from one
    concurrent-entry group, or guarded by a lock anywhere (the author
    already believes it's shared)."""
    groups = set()
    locked = False
    for meth, mm in cls.methods.items():
        if meth == "__init__":
            continue
        for acc in mm.accesses:
            if acc.attr != attr:
                continue
            g = _group(cls, meth)
            groups.add(g)
            locked = locked or bool(
                cidx.effective_held(cls, meth, acc.held))
            if cls.concurrent_entry_in(g):
                return True
    return len(groups) > 1 or locked


def rule_c4(m: ModuleInfo, cidx: ConcIndex) -> Iterable[RuleHit]:
    """``if key in self.d: ... self.d[key] = / del self.d[key]`` (or the
    ``.get() is None`` / truthiness forms) with both the check and the
    mutation outside a lock, on a container another thread touches:
    the window between check and act admits interleavings the check
    was meant to exclude — take the class lock around the pair."""
    for cls in _real_classes(m, cidx):
        # same gate as C5: a thread entry OR a lock (the lock is the
        # author saying "this class is shared")
        if (not cls.entries and not cls.lock_attrs) or \
                cls.is_handler_class():
            continue
        for meth, mm in cls.methods.items():
            for ev in mm.conds:
                if cidx.effective_held(cls, meth, ev.held) or \
                        ev.kind == "none":
                    continue
                if f"{cls.name}.{ev.attr}" in LOCKFREE_ALLOWLIST:
                    continue
                writes = [
                    w for w in ev.body_writes
                    if not cidx.effective_held(cls, meth, w.held)
                    and not w.assign
                ]
                if not writes or not _attr_shared(cidx, cls, ev.attr):
                    continue
                yield ev.line, (
                    f"non-atomic check-then-act on shared "
                    f"`{cls.name}.{ev.attr}`: the test and the "
                    f"mutation (line {writes[0].line}) run outside "
                    "any lock — another thread can interleave between "
                    "them; hold the class lock across both"
                )


# ---------------------------------------------------------------------------
# C5 — unsafe lazy-init / double-checked locking
# ---------------------------------------------------------------------------

def rule_c5(m: ModuleInfo, cidx: ConcIndex) -> Iterable[RuleHit]:
    """``if self.x is None: self.x = build()`` (or the ``not self.x``
    form) with the check outside a lock in a class threads touch: two
    threads both see None and both build — double work at best, two
    live objects with split state at worst.  The safe shapes: init
    eagerly in ``__init__``; or check-lock-RECHECK (the recheck under
    the lock is what the walker looks for); or ``setdefault`` under
    the lock."""
    for cls in _real_classes(m, cidx):
        if (not cls.entries and not cls.lock_attrs) or \
                cls.is_handler_class():
            continue
        for meth, mm in cls.methods.items():
            if meth == "__init__":
                continue
            for ev in mm.conds:
                if cidx.effective_held(cls, meth, ev.held) or \
                        ev.rechecked_under_lock:
                    continue
                if ev.kind not in ("none", "truth"):
                    continue
                assigns = [w for w in ev.body_writes if w.assign]
                if not assigns:
                    continue
                # single-thread-private state (the autoscaler's
                # hysteresis windows live on the control thread alone)
                # cannot race with itself
                if not _attr_shared(cidx, cls, ev.attr):
                    continue
                yield ev.line, (
                    f"unsafe lazy init of `{cls.name}.{ev.attr}`: "
                    "checked outside a lock, assigned at line "
                    f"{assigns[0].line} — two threads can both pass "
                    "the check and both initialize; init eagerly in "
                    "__init__, or re-check under the lock "
                    "(check-lock-recheck), or setdefault under the lock"
                )


# ---------------------------------------------------------------------------
# C6 — orphan threads
# ---------------------------------------------------------------------------

def rule_c6(m: ModuleInfo, cidx: ConcIndex) -> Iterable[RuleHit]:
    """``threading.Thread(...)`` with ``daemon=`` unset and no ``join``
    (or ``.daemon = True``) reachable for the handle: library code that
    leaks a non-daemon thread keeps the interpreter alive at shutdown
    and hides teardown bugs.  Give every thread an owner: mark it
    daemon, or keep the handle and join it on the teardown path."""
    for cls in cidx.classes_in(m):
        all_joins: set = set()
        all_daemon_marks: set = set()
        for mm in cls.methods.values():
            all_joins |= mm.joins
            all_daemon_marks |= mm.daemon_marks
        for meth, mm in cls.methods.items():
            for sp in mm.spawns:
                if sp.daemon_set:
                    continue
                owned = False
                if sp.assigned is not None:
                    kind, _, name = sp.assigned.partition(":")
                    ref = f"self.{name}" if kind == "self" else name
                    if kind == "self":
                        owned = ref in all_joins or \
                            ref in all_daemon_marks
                    else:
                        owned = ref in mm.joins or ref in mm.daemon_marks
                where = (f"`{cls.name}.{meth}`" if cls.node is not None
                         else f"`{meth}`")
                if not owned:
                    yield sp.line, (
                        f"thread started in {where} without an owner: "
                        "daemon= unset and the handle is never joined "
                        "— pass daemon=True, or keep the handle and "
                        "join it in the teardown path"
                    )


# ---------------------------------------------------------------------------
# registry + driver
# ---------------------------------------------------------------------------

_IMPLS = {
    "C1": rule_c1, "C2": rule_c2, "C3": rule_c3,
    "C4": rule_c4, "C5": rule_c5, "C6": rule_c6,
}

#: rule id -> (summary, impl); summaries come from the shared id table
#: so the CLI's --list-rules and the implementations cannot drift
CONC_RULES: Dict[str, Tuple[str, object]] = {
    rid: (CONC_RULE_SUMMARIES[rid], impl) for rid, impl in _IMPLS.items()
}
assert set(CONC_RULES) == set(CONC_RULE_SUMMARIES)


def run_conc_rules(
    cidx: ConcIndex,
    modules: Optional[List[ModuleInfo]] = None,
    select: Optional[Iterable[str]] = None,
) -> List[Finding]:
    mods = modules if modules is not None else cidx.modules
    sel = {s for s in select} if select else None
    if sel is not None:
        unknown = sel - set(CONC_RULES)
        if unknown:
            raise ValueError(
                f"unknown conc rule id(s): {sorted(unknown)}")
    findings: List[Finding] = []
    for m in mods:
        for rid, (_summary, impl) in CONC_RULES.items():
            if sel is not None and rid not in sel:
                continue
            for line, msg in impl(m, cidx):
                if not m.is_suppressed(rid, line):
                    findings.append(Finding(rid, m.path, line, msg))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
