"""dgenlint-conc: the thread-safety tier (rules C1-C6).

``python -m dgen_tpu.lint --conc`` runs the static half over the
threaded host-side modules (the serving plane, host IO, resilience
supervisors, timing, parallel helpers — :data:`CONC_DEFAULT_ROOTS`):

    C1  cross-thread write to self.* state without the class lock
    C2  blocking call (sleep/HTTP/subprocess/join/queue) under a lock
    C3  lock-acquisition order cycle / non-reentrant re-acquire
    C4  non-atomic check-then-act on a shared container outside a lock
    C5  unsafe lazy-init / broken double-checked locking
    C6  thread started without an owner (no daemon=, no join)

The runtime half is :mod:`dgen_tpu.utils.locktrace` — the
instrumented-lock sentinel the fleet/gang/serve-scale drills run armed
(tools/check.sh) to verify the *observed* lock-order graph stays
acyclic.  Rules, suppression semantics and the lock-free allowlist are
documented in docs/lint.md "The concurrency tier".
"""

from __future__ import annotations

import os
from typing import Iterable, List, Optional

from dgen_tpu.lint.conc.analyzer import ConcIndex  # noqa: F401
from dgen_tpu.lint.conc.crules import (  # noqa: F401
    CONC_RULES,
    LOCKFREE_ALLOWLIST,
    run_conc_rules,
)
from dgen_tpu.lint.conc_ids import CONC_RULE_SUMMARIES  # noqa: F401
from dgen_tpu.lint.core import Finding, parse_file, parse_source

_PKG = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: the concurrent host surface the tier audits by default: everything
#: that starts threads or is called from them
CONC_DEFAULT_ROOTS = (
    os.path.join(_PKG, "serve"),
    os.path.join(_PKG, "io", "hostio.py"),
    os.path.join(_PKG, "resilience"),
    os.path.join(_PKG, "utils", "timing.py"),
    os.path.join(_PKG, "parallel"),
)


def lint_conc_paths(
    paths: Optional[Iterable[str]] = None,
    select: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Run C1-C6 over files/directories (default: the concurrent host
    modules).  The index always includes the default roots so
    cross-class edges (typed attributes, external thread entries)
    resolve even when only a subset is linted."""
    from dgen_tpu.lint import collect_files

    targets = collect_files(
        list(paths) if paths is not None else list(CONC_DEFAULT_ROOTS))
    index_files = sorted(
        set(targets) | set(collect_files(
            [p for p in CONC_DEFAULT_ROOTS if os.path.exists(p)])))
    by_path = {}
    for f in index_files:
        by_path[os.path.abspath(f)] = parse_file(f)
    cidx = ConcIndex(by_path.values())
    mods = [by_path[os.path.abspath(f)] for f in targets]
    return run_conc_rules(cidx, modules=mods, select=select)


def lint_conc_source(
    src: str,
    filename: str = "<snippet>",
    modname: str = "snippet",
    select: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Run C1-C6 over one source string (unit tests / fixtures)."""
    m = parse_source(src, filename=filename, modname=modname)
    return run_conc_rules(ConcIndex([m]), select=select)
