"""Program-auditor rule ids and one-line summaries — deliberately
jax-free: the default CLI half (``python -m dgen_tpu.lint``,
``--list-rules`` included) must stay importable without jax
(docs/lint.md). The rule *implementations* live in
:mod:`dgen_tpu.lint.prog.jrules`, whose import chain pulls jax; that
module builds its registry from this table so the two cannot drift.
"""

from __future__ import annotations

from typing import Dict

PROGRAM_RULE_SUMMARIES: Dict[str, str] = {
    "J0": "entry point fails to trace/lower",
    "J1": "oversized constants captured into the program",
    "J2": "dtype drift (f64 / low-precision accumulation)",
    "J3": "host callbacks/transfers inside compiled code",
    "J4": "carry donation verification (input_output_aliases)",
    "J5": "compile-group fingerprint invariants",
    "J6": "cost-fingerprint regression gate (baseline JSON)",
    "J7": "collective-communication fingerprint gate (mesh tier)",
    "J8": "sharding propagation: agent axis must stay partitioned",
    "J9": "static per-device memory vs HBM budget + planner model",
    "J10": "per-mesh-shape program fingerprint identity (baseline)",
    "J11": "gradient-killing ops inside a grad-marked entry",
}
