"""dgenlint: JAX/TPU anti-pattern linter + recompilation guard.

Static half (no jax import, safe anywhere):

    python -m dgen_tpu.lint                # lint the dgen_tpu package
    python -m dgen_tpu.lint path/ file.py  # lint specific paths

or programmatically::

    from dgen_tpu import lint
    findings = lint.lint_paths(["dgen_tpu"])      # [] when clean
    findings = lint.lint_source(src)              # one snippet

Program half (imports jax — pulled lazily, the static linter stays
import-light)::

    JAX_PLATFORMS=cpu python -m dgen_tpu.lint --programs

:mod:`dgen_tpu.lint.prog` traces + lowers every registered jitted
entry point over the static-config grid on CPU (no devices, no data)
and runs rules J0-J10 over the jaxprs/StableHLO (``--mesh`` adds
the multi-device J7-J10 tier), including the J6
cost-fingerprint gate against ``tools/prog_baseline.json``.

Runtime half: :class:`dgen_tpu.lint.guard.RetraceGuard` counts fresh
XLA compiles per simulation year and fails when a steady-state year
retraces (imported lazily — the static linter must not initialize a
backend just to parse files).

Concurrency tier (no jax import; the audited surface is the threaded
*host* side — serve/, host IO, resilience, timing, parallel)::

    python -m dgen_tpu.lint --conc

:mod:`dgen_tpu.lint.conc` runs rules C1-C6 over thread discipline
(unguarded cross-thread writes, blocking calls under a lock,
lock-order cycles, check-then-act, lazy init, orphan threads); its
runtime half, :mod:`dgen_tpu.utils.locktrace`, is armed with
``DGEN_TPU_LOCKTRACE=1`` during the check.sh drill legs.

Rules are documented in ``docs/lint.md``; suppress a finding with
``# dgenlint: disable=<rule>`` on the flagged line.
"""

from __future__ import annotations

import os
from typing import Iterable, List, Optional

from dgen_tpu.lint.core import (  # noqa: F401  (public API)
    Finding,
    ModuleInfo,
    ProjectIndex,
    parse_file,
    parse_source,
)
from dgen_tpu.lint.rules import RULES, run_rules  # noqa: F401

#: the default lint target: the dgen_tpu package itself
PACKAGE_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def collect_files(paths: Iterable[str]) -> List[str]:
    """Expand files/directories into a sorted .py file list."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [
                    d for d in dirnames
                    if d not in ("__pycache__", ".git", ".jax_cache")
                ]
                out.extend(
                    os.path.join(dirpath, f)
                    for f in filenames if f.endswith(".py")
                )
        else:
            out.append(p)
    return sorted(set(out))


def lint_paths(
    paths: Optional[Iterable[str]] = None,
    select: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Lint files/directories (default: the dgen_tpu package).

    The reachability index always includes the whole package so that
    cross-module jit edges resolve even when only a subset is linted.
    """
    targets = collect_files(paths if paths is not None else [PACKAGE_ROOT])
    index_files = sorted(set(targets) | set(collect_files([PACKAGE_ROOT])))
    by_path = {}
    for f in index_files:
        by_path[os.path.abspath(f)] = parse_file(f)
    index = ProjectIndex(by_path.values())
    lint_mods = [by_path[os.path.abspath(f)] for f in targets]
    return run_rules(index, modules=lint_mods, select=select)


def lint_source(
    src: str,
    filename: str = "<snippet>",
    modname: str = "snippet",
    select: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Lint one source string (unit tests / fixtures). ``modname``
    controls which layering rules apply (e.g. ``dgen_tpu.ops.bad``)."""
    m = parse_source(src, filename=filename, modname=modname)
    return run_rules(ProjectIndex([m]), select=select)
