"""Mesh-tier program introspection: the sharded-program artifacts
rules J7-J10 inspect.

The base auditor lowers every entry point single-device; this module
owns the extra analysis of the MESH tier (``--programs --mesh``): each
entry is lowered under forced multi-device CPU meshes with the
production shardings applied (``parallel.mesh.make_mesh`` +
``parallel.mesh.agent_spec`` — the same placement path
``Simulation.__init__`` runs), compiled (CPU, never executed), and the
compiled **per-device** HLO is parsed for:

* the collective fingerprint (J7): every all-reduce / all-gather /
  reduce-scatter / collective-permute / all-to-all with result and
  operand shapes, plus a deterministic comm-byte estimate;
* sharding propagation (J8): tensors materialized at GLOBAL agent-axis
  shape inside the per-device program (the partitioned module's shapes
  are per-shard, so a full-``[N, ...]`` tensor IS a replication /
  gather), and output leaves that lost their agent sharding;
* the per-device memory footprint (J9):
  ``compiled.memory_analysis()`` where the backend exposes it, an
  aval x sharding estimate where it does not.

Comm-byte convention (deterministic, ring-algorithm shaped — the gate
compares against a committed baseline, so only determinism matters,
not absolute calibration):

==================== =============================================
all-gather           result_bytes * (G-1)/G
reduce-scatter       result_bytes * (G-1)
all-reduce           2 * result_bytes * (G-1)/G
collective-permute   result_bytes
all-to-all           result_bytes * (G-1)/G
==================== =============================================

with G the collective's replica-group size (parsed from the HLO's
``replica_groups``).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Tuple

import numpy as np

#: collective HLO opcodes fingerprinted by J7 (async ``-start`` halves
#: are folded into their base opcode; ``-done`` is bookkeeping)
COLLECTIVE_KINDS = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)

#: J8 floor: a global-shaped agent-axis tensor smaller than this inside
#: the per-device program is tolerated — tiny [N] vectors are
#: legitimately gathered for whole-table host-order operations (the
#: integer battery-adopter allocation sorts the full table), and those
#: gathers are J7's (fingerprinted) business. A [N, 8760] stream or a
#: bank at global shape is orders of magnitude past this at audit
#: scale.
J8_MIN_TENSOR_BYTES = 16 * 1024

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

#: one typed shape token, e.g. ``f32[64,8760]`` (layout suffix ``{1,0}``
#: optional); ``f32[]`` is a scalar
_SHAPE_RE = re.compile(r"\b([a-z]+\d*)\[([\d,]*)\]")

_COLLECTIVE_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%[\w.-]+\s*=\s*(?P<result>\([^)]*\)|[^\s]+)\s+"
    r"(?P<kind>" + "|".join(COLLECTIVE_KINDS) + r")"
    r"(?P<suffix>-start|-done)?[\w.-]*\((?P<operands>[^)]*)\)",
    re.MULTILINE,
)

_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=")
_GROUPS_LIST_RE = re.compile(r"replica_groups=\{\{([^}]*)\}")


def _shape_bytes(dtype: str, dims: str) -> Tuple[Tuple[int, ...], int]:
    shape = tuple(int(d) for d in dims.split(",") if d)
    n = int(np.prod(shape)) if shape else 1
    return shape, n * _DTYPE_BYTES.get(dtype, 4)


def _shapes_in(text: str) -> List[Tuple[str, Tuple[int, ...], int]]:
    """(token, shape, nbytes) for every typed shape token in ``text``."""
    out = []
    for m in _SHAPE_RE.finditer(text):
        shape, nbytes = _shape_bytes(m.group(1), m.group(2))
        out.append((f"{m.group(1)}[{m.group(2)}]", shape, nbytes))
    return out


@dataclasses.dataclass(frozen=True)
class Collective:
    """One collective op in the compiled per-device program."""

    kind: str
    result_shapes: Tuple[str, ...]
    operand_shapes: Tuple[str, ...]
    result_bytes: int
    group_size: int
    comm_bytes: int


@dataclasses.dataclass
class MeshInfo:
    """Everything the mesh-tier rules read off one compiled program."""

    shape: Tuple[int, int]                   # (hosts, devices)
    n_devices: int
    global_n: int                            # padded global agent count
    collectives: List[Collective]
    #: J8: (shape token, HLO line excerpt, nbytes) of global-agent-axis
    #: tensors materialized inside the per-device program
    replicated_global: List[Tuple[str, str, int]]
    #: J8: descriptions of [N]-leading OUTPUT leaves that came back
    #: fully replicated
    outputs_unsharded: List[str]
    #: per-device bytes: temp / argument / output (+ "estimated" flag
    #: when memory_analysis was unavailable and avals were summed)
    memory: Dict[str, Optional[int]]
    #: the planner's _per_agent_step_bytes prediction for this entry's
    #: per-device working set (None where the model does not apply)
    model_bytes: Optional[int] = None

    @property
    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for c in self.collectives:
            out[c.kind] = out.get(c.kind, 0) + 1
        return out

    @property
    def comm_bytes(self) -> int:
        return sum(c.comm_bytes for c in self.collectives)

    @property
    def comm_bytes_by_kind(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for c in self.collectives:
            out[c.kind] = out.get(c.kind, 0) + c.comm_bytes
        return out

    @property
    def peak_bytes(self) -> Optional[int]:
        """Per-device peak: the sum of whatever byte accounting is
        available. When ``memory_analysis`` is absent (aval-estimate
        fallback: temp unknown) this is a LOWER BOUND — still gated by
        J9, since a lower bound over budget is over budget."""
        parts = [
            self.memory.get(k)
            for k in ("temp", "argument", "output")
        ]
        known = [p for p in parts if p is not None]
        if not known:
            return None
        return sum(known)

    @property
    def peak_is_lower_bound(self) -> bool:
        return any(
            self.memory.get(k) is None
            for k in ("temp", "argument", "output")
        )


def _comm_bytes(kind: str, result_bytes: int, g: int) -> int:
    if g <= 1:
        return 0
    if kind == "all-reduce":
        return int(2 * result_bytes * (g - 1) / g)
    if kind == "reduce-scatter":
        return int(result_bytes * (g - 1))
    if kind == "collective-permute":
        return int(result_bytes)
    # all-gather / all-to-all
    return int(result_bytes * (g - 1) / g)


def parse_collectives(hlo_text: str, n_devices: int) -> List[Collective]:
    """Every collective in a compiled HLO module, with shapes and the
    deterministic comm-byte estimate. ``-done`` halves of async pairs
    are skipped (the ``-start`` op carries the shapes)."""
    out: List[Collective] = []
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        if m.group("suffix") == "-done":
            continue
        res = _shapes_in(m.group("result"))
        ops = _shapes_in(m.group("operands"))
        line = hlo_text[m.start():hlo_text.find("\n", m.start())]
        g = n_devices
        gm = _GROUPS_IOTA_RE.search(line)
        if gm:
            g = int(gm.group(2))
        else:
            gm = _GROUPS_LIST_RE.search(line)
            if gm:
                g = len([t for t in gm.group(1).split(",") if t.strip()])
        result_bytes = sum(nb for _, _, nb in res)
        out.append(Collective(
            kind=m.group("kind"),
            result_shapes=tuple(tok for tok, _, _ in res),
            operand_shapes=tuple(tok for tok, _, _ in ops),
            result_bytes=result_bytes,
            group_size=g,
            comm_bytes=_comm_bytes(m.group("kind"), result_bytes, g),
        ))
    return out


def scan_replicated_global(
    hlo_text: str, global_n: int,
    min_bytes: int = J8_MIN_TENSOR_BYTES,
) -> List[Tuple[str, str, int]]:
    """Global-agent-axis tensors materialized in the PER-DEVICE
    program: the partitioned module's shapes are per-shard, so any
    tensor whose leading dim equals the global padded agent count (and
    which is big enough to matter, see :data:`J8_MIN_TENSOR_BYTES`)
    was gathered or replicated. Returns (shape token, defining line
    excerpt, nbytes), deduplicated by shape."""
    found: Dict[str, Tuple[str, str, int]] = {}
    for line in hlo_text.splitlines():
        s = line.strip()
        # only defining instructions ("%name = type op(...)"): shape
        # tokens in operand lists repeat their definition
        if not (s.startswith("%") or s.startswith("ROOT")):
            continue
        eq = s.find(" = ")
        if eq < 0:
            continue
        rhs = s[eq + 3:]
        paren = rhs.find("(")
        head = rhs if paren < 0 else (
            rhs[:rhs.find("(", paren + 1)] if rhs.startswith("(")
            else rhs[:paren + 1]
        )
        for tok, shape, nbytes in _shapes_in(head):
            # the agent dim leads ([N, ...]) except under a batched
            # scenario axis ([S, N, ...] — the sweep's vmap layout)
            hit = bool(shape) and (
                shape[0] == global_n
                or (len(shape) >= 3 and shape[1] == global_n)
            )
            if not hit or nbytes < min_bytes:
                continue
            if tok not in found:
                found[tok] = (tok, s[:160], nbytes)
    return sorted(found.values(), key=lambda t: -t[2])


def _is_replicated(sharding) -> Optional[bool]:
    try:
        return bool(sharding.is_fully_replicated)
    except Exception:  # noqa: BLE001 — backend-specific sharding types
        return None


def scan_output_shardings(
    out_avals, out_shardings, global_n: int,
) -> List[str]:
    """[N]-leading output leaves whose compiled sharding is fully
    replicated — state that stayed agent-sharded all run would come
    back replicated only through a (wasteful) gather."""
    import jax

    flat_sh = jax.tree.leaves(out_shardings)
    bad: List[str] = []
    if len(flat_sh) != len(out_avals):
        return bad
    for aval, sh in zip(out_avals, flat_sh):
        shape = tuple(getattr(aval, "shape", ()) or ())
        if not shape or shape[0] != global_n or 0 in shape:
            # zero-element leaves (keep_hourly=False placeholders) are
            # trivially replicated — there is nothing to shard
            continue
        if _is_replicated(sh):
            bad.append(
                f"{getattr(aval, 'dtype', '?')}{list(shape)}"
            )
    return bad


def read_memory_analysis(compiled) -> Dict[str, Optional[int]]:
    """Per-device byte accounting from ``compiled.memory_analysis()``,
    or ``{"available": False}`` when the backend exposes none."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — optional backend surface
        ma = None
    if ma is None:
        return {"available": False, "temp": None, "argument": None,
                "output": None}
    def _get(name):
        v = getattr(ma, name, None)
        return int(v) if v is not None else None
    return {
        "available": True,
        "temp": _get("temp_size_in_bytes"),
        "argument": _get("argument_size_in_bytes"),
        "output": _get("output_size_in_bytes"),
    }


def estimate_memory_from_avals(
    in_avals, in_shardings, out_avals, n_devices: int,
) -> Dict[str, Optional[int]]:
    """Aval x sharding fallback for backends without
    ``memory_analysis``: per-device argument/output residency (sharded
    leaves divided by their shard count, replicated leaves full size);
    temp stays unknown."""
    import jax

    def _local_bytes(aval, sharding) -> int:
        shape = tuple(getattr(aval, "shape", ()) or ())
        nbytes = int(np.prod(shape)) if shape else 1
        nbytes *= np.dtype(getattr(aval, "dtype", np.float32)).itemsize
        rep = _is_replicated(sharding) if sharding is not None else True
        return nbytes if rep else max(nbytes // max(n_devices, 1), 1)

    flat_in_sh = jax.tree.leaves(in_shardings) if in_shardings else []
    arg = 0
    for i, aval in enumerate(in_avals):
        sh = flat_in_sh[i] if i < len(flat_in_sh) else None
        arg += _local_bytes(aval, sh)
    out = sum(_local_bytes(a, None) for a in out_avals)
    return {"available": False, "estimated": True, "temp": None,
            "argument": int(arg), "output": int(out)}


def analyze_mesh_program(
    compiled,
    jaxpr,
    *,
    shape: Tuple[int, int],
    global_n: int,
    model_bytes: Optional[int] = None,
) -> MeshInfo:
    """Build the :class:`MeshInfo` for one compiled mesh-tier program:
    parse collectives and global-shape leaks out of the per-device HLO,
    read the memory analysis (aval-estimate fallback), and check the
    output shardings."""
    n_devices = int(shape[0]) * int(shape[1])
    text = compiled.as_text()
    memory = read_memory_analysis(compiled)
    out_avals = list(jaxpr.out_avals)
    if not memory.get("available"):
        try:
            in_sh = compiled.input_shardings
        except Exception:  # noqa: BLE001
            in_sh = None
        memory = estimate_memory_from_avals(
            list(jaxpr.in_avals), in_sh, out_avals, n_devices,
        )
    try:
        out_sh = compiled.output_shardings
    except Exception:  # noqa: BLE001
        out_sh = None
    outputs_unsharded = (
        scan_output_shardings(out_avals, out_sh, global_n)
        if out_sh is not None else []
    )
    return MeshInfo(
        shape=(int(shape[0]), int(shape[1])),
        n_devices=n_devices,
        global_n=global_n,
        collectives=parse_collectives(text, n_devices),
        replicated_global=scan_replicated_global(text, global_n),
        outputs_unsharded=outputs_unsharded,
        memory=memory,
        model_bytes=model_bytes,
    )


def collective_table(info: MeshInfo) -> List[str]:
    """Human-readable per-collective lines (the ``--explain`` view)."""
    lines = []
    for c in info.collectives:
        lines.append(
            f"{c.kind:<20} {' '.join(c.result_shapes) or '()':<24} "
            f"group={c.group_size}  ~{c.comm_bytes} B"
        )
    if not lines:
        lines.append("(no collectives)")
    return lines
