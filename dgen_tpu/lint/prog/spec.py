"""Program-audit specs: lowered-program bundles the J-rules run over.

The AST half of dgenlint (rules L1-L11) sees *source shapes*; this
module sees *compiled-program shapes*. A :class:`ProgramSpec` names one
jitted entry point at one static-config grid point and knows how to
build a TINY abstract invocation of it — a synthetic 64-agent
population, 4 model years, 8 economics years — purely to TRACE and
LOWER the program (``jax.jit(...).trace(...).lower()``): no device
execution, no real data, CPU-only. The resulting
:class:`ProgramAudit` carries everything the J-rules inspect:

* the closed jaxpr (captured constants, primitive/aval walk — J1/J2/J3),
* ``lowered.args_info`` (per-leaf donation flags — J4),
* a location-stripped StableHLO fingerprint (compile-group identity —
  J5),
* and, for cost entries, ``compiled.cost_analysis()`` (flops /
  bytes-accessed — the J6 baseline gate).

The spec scale is deliberately fixed (:data:`AUDIT_N_AGENTS` etc.):
cost fingerprints are only comparable against a baseline computed at
the same shapes, so these constants are part of the baseline contract
(bump :data:`AUDIT_SPEC_VERSION` when changing them).
"""

from __future__ import annotations

import dataclasses
import hashlib
import inspect
import re
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax
import numpy as np

#: bump when the abstract-spec shapes/config change — baselines are
#: only comparable within one spec version
AUDIT_SPEC_VERSION = "prog-audit-v1"

AUDIT_N_AGENTS = 64
AUDIT_STATES = ("DE", "CA")
AUDIT_END_YEAR = 2020          # 2014..2020 step 2 -> 4 model years
AUDIT_ECON_YEARS = 8
AUDIT_SIZING_ITERS = 4
AUDIT_CHUNK = 16               # streaming-scan variant: 64 agents / 16
AUDIT_QUERY_BUCKET = 4         # serve bucket width audited
AUDIT_SWEEP_S = 2              # scenario-axis width audited
#: streaming chunk of the MESH-tier chunked variant: 64 agents over 8
#: devices is 8 local rows, so chunk 4 engages a real 2-step scan
AUDIT_MESH_CHUNK = 4

#: J1 default ceiling for any single constant captured into a program
#: at audit scale. The sanctioned shared constants (month one-hots,
#: daylight gather indices) stay well under it; a baked-in profile bank
#: or agent-table leaf lands far over it. Per-spec overridable.
MAX_CONST_BYTES = 1 << 20      # 1 MiB

_LOC_RE = re.compile(r"loc\(.*?\)|#loc\d*(?: = .*)?$", re.MULTILINE)


@dataclasses.dataclass(frozen=True)
class Bound:
    """One concrete invocation to lower: ``fn.trace(*args, **kwargs)``.

    ``fn`` must be a jit-wrapped callable; ``kwargs`` carries the
    static arguments (hashable compile-time values)."""

    fn: Any
    args: tuple
    kwargs: dict


@dataclasses.dataclass
class ProgramSpec:
    """One (entry point, static-config grid point) to audit.

    ``spec_id`` is ``entry@variant`` — stable across runs, used for J5
    cross-references and J6 baseline keys. ``anchor`` is the (path,
    line) findings attach to, which is where ``# dgenlint:
    disable=J<n>`` suppression comments are honored (same mechanics as
    the L-rules). ``donate_args``: positional indices of the traced
    argument pytrees that MUST be donated (J4) — every leaf under them
    donated, no leaf outside them donated. ``steady`` builds a second
    invocation that models the next steady-state step (a later year
    index); J5 requires it to lower to the identical program.
    ``expect_same_as``: spec_id whose fingerprint this one must equal
    (the loop-mode sweep's zero-extra-compile invariant). ``cost``
    marks the J6 baseline entries. ``grad`` marks entries whose bound
    IS a differentiated program (a ``value_and_grad``/``jvp``-of-grad
    wrapper) — J11 walks them for gradient-killing primitives.
    """

    entry: str
    variant: str
    build: Callable[[], Bound]
    anchor: Tuple[str, int]
    donate_args: Tuple[int, ...] = ()
    steady: Optional[Callable[[], Bound]] = None
    expect_same_as: Optional[str] = None
    cost: bool = False
    grad: bool = False
    max_const_bytes: int = MAX_CONST_BYTES
    #: mesh-tier specs (``--programs --mesh``): the (hosts, devices)
    #: grid this spec lowers under — the bound's world is built over
    #: ``parallel.mesh.make_mesh(shape=...)`` with production placement.
    #: Non-None routes the spec through compile + J7-J10 analysis.
    mesh_shape: Optional[Tuple[int, int]] = None
    #: padded GLOBAL agent count of the spec's world (J8 scans the
    #: per-device HLO for tensors materialized at this leading dim)
    global_n: int = 0
    #: the sweep planner's ``_per_agent_step_bytes`` prediction of this
    #: entry's per-device step working set (J9 cross-checks it against
    #: ``compiled.memory_analysis()``; None = model does not apply).
    #: May be a zero-arg callable so registry construction stays lazy
    #: (resolved at lower time, alongside the world the builder makes).
    model_bytes: Optional[Any] = None

    @property
    def spec_id(self) -> str:
        return f"{self.entry}@{self.variant}" if self.variant else self.entry


@dataclasses.dataclass
class ProgramAudit:
    """A lowered :class:`ProgramSpec` plus everything the rules read."""

    spec: ProgramSpec
    jaxpr: Any                     # jax.core.ClosedJaxpr
    args_info: Any                 # lowered.args_info (donation flags)
    fingerprint: str               # sha256 of location-stripped StableHLO
    steady_fingerprint: Optional[str]
    const_bytes: int
    oversized_consts: List[Tuple[tuple, str, int]]   # (shape, dtype, nbytes)
    cost_analysis: Optional[Dict[str, float]]        # cost entries only
    #: total bytes of the program's traced PARAMETERS (sum of invar
    #: aval bytes) — the static kernel-input-traffic term: int8
    #: quantized banks shrink it ~4x per stream and packed streams
    #: swap the raw + gathered pair for the gathered lanes alone.
    #: XLA:CPU's bytes_accessed cannot see either (its cost model is
    #: dominated by the f32 VMEM-resident intermediates that never
    #: touch HBM on TPU), so J6 gates this alongside it.
    input_bytes: int = 0
    error: Optional[str] = None    # build/lower failure (itself a finding)
    #: mesh-tier analysis (meshaudit.MeshInfo) — J7-J10 inputs; None on
    #: single-device audits and on identity-only mesh cross-checks
    mesh: Optional[Any] = None
    #: the lowered StableHLO text — kept only when lower_spec ran with
    #: ``keep_text`` (the --explain path), else None (big programs)
    hlo_text: Optional[str] = None


def anchor_for(fn: Any) -> Tuple[str, int]:
    """(source path, def line) of a (possibly jit-wrapped) callable —
    the line J-findings attach to and where suppressions are read."""
    target = inspect.unwrap(fn, stop=lambda f: False)
    for cand in (target, getattr(fn, "__wrapped__", None), fn):
        if cand is None:
            continue
        try:
            path = inspect.getsourcefile(cand)
            _, line = inspect.getsourcelines(cand)
            if path:
                return path, line
        except (TypeError, OSError):
            continue
    return "<unknown>", 0


def program_fingerprint(text: str) -> str:
    """sha256 of the StableHLO module with location metadata stripped
    (loc() spans carry source line numbers, which would make the
    fingerprint churn on every unrelated edit above the entry)."""
    return hashlib.sha256(_LOC_RE.sub("", text).encode()).hexdigest()


def walk_jaxpr(closed) -> Iterator[Any]:
    """Yield every eqn of a ClosedJaxpr, descending into sub-jaxprs
    (pjit bodies, scan/cond/while branches, custom_* calls)."""
    stack = [closed.jaxpr]
    seen = set()
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        for eqn in j.eqns:
            yield eqn
            for p in eqn.params.values():
                stack.extend(_subjaxprs(p))


def _subjaxprs(p) -> List[Any]:
    out = []
    if hasattr(p, "jaxpr"):           # ClosedJaxpr
        out.append(p.jaxpr)
    elif hasattr(p, "eqns"):          # raw Jaxpr
        out.append(p)
    elif isinstance(p, (tuple, list)):
        for q in p:
            out.extend(_subjaxprs(q))
    return out


def eqn_avals(eqn) -> Iterator[Any]:
    """All in/out avals of one eqn (literals included)."""
    for v in list(eqn.invars) + list(eqn.outvars):
        aval = getattr(v, "aval", None)
        if aval is not None:
            yield aval


def _const_nbytes(c) -> int:
    try:
        return int(np.asarray(c).nbytes)
    except (TypeError, ValueError):
        return 0


def lower_spec(
    spec: ProgramSpec, with_cost: bool = False, keep_text: bool = False,
) -> ProgramAudit:
    """Trace + lower one spec (and its steady probe); compile only when
    ``with_cost`` and the spec is a cost entry, or when the spec is a
    mesh-tier entry (J7-J9 read the compiled per-device program).
    ``keep_text`` retains the StableHLO text on the audit (--explain).
    Never executes."""
    try:
        bound = spec.build()
        traced = bound.fn.trace(*bound.args, **bound.kwargs)
        lowered = traced.lower()
        text = lowered.as_text()
        fp = program_fingerprint(text)
        closed = traced.jaxpr
        oversized = []
        total = 0
        for c in getattr(closed, "consts", ()):
            nb = _const_nbytes(c)
            total += nb
            if nb > spec.max_const_bytes:
                arr = np.asarray(c)
                oversized.append((tuple(arr.shape), str(arr.dtype), nb))
        steady_fp = None
        if spec.steady is not None:
            sb = spec.steady()
            steady_fp = program_fingerprint(
                sb.fn.trace(*sb.args, **sb.kwargs).lower().as_text()
            )
        cost = None
        input_bytes = 0
        if with_cost and spec.cost:
            ca = lowered.compile().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            cost = {
                "flops": float(ca.get("flops", 0.0)),
                "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
                "transcendentals": float(ca.get("transcendentals", 0.0)),
            }
            for v in closed.jaxpr.invars:
                aval = getattr(v, "aval", None)
                if aval is not None and hasattr(aval, "shape"):
                    input_bytes += int(
                        np.prod(aval.shape, dtype=np.int64)
                        * np.dtype(aval.dtype).itemsize
                    )
        mesh_info = None
        if spec.mesh_shape is not None and spec.expect_same_as is None:
            # identity-only mesh cross-checks (expect_same_as) are J5's
            # business and skip the compile; everything else in the mesh
            # tier compiles so J7-J9 can read the per-device program
            from dgen_tpu.lint.prog.meshaudit import analyze_mesh_program

            model = spec.model_bytes
            if callable(model):
                model = model()
            mesh_info = analyze_mesh_program(
                lowered.compile(), closed,
                shape=spec.mesh_shape, global_n=spec.global_n,
                model_bytes=model,
            )
        return ProgramAudit(
            spec=spec, jaxpr=closed, args_info=lowered.args_info,
            fingerprint=fp, steady_fingerprint=steady_fp,
            const_bytes=total, oversized_consts=oversized,
            cost_analysis=cost, mesh=mesh_info,
            input_bytes=input_bytes,
            hlo_text=text if keep_text else None,
        )
    except Exception as e:  # noqa: BLE001 — a spec that cannot even
        # lower is itself a finding (J0), not an auditor crash
        return ProgramAudit(
            spec=spec, jaxpr=None, args_info=None, fingerprint="",
            steady_fingerprint=None, const_bytes=0, oversized_consts=[],
            cost_analysis=None,
            error=f"{type(e).__name__}: {e}",
        )


def donated_partition(audit: ProgramAudit) -> Tuple[int, int, int]:
    """(donated-in-expected, undonated-in-expected, donated-elsewhere)
    leaf counts, per the spec's ``donate_args`` positions.

    ``args_info`` mirrors the traced ``(args, kwargs)`` call tree with
    per-leaf ``ArgInfo(aval, donated)``; static arguments do not
    appear. The J4 contract is positional: every leaf under a declared
    carry position donated, and nothing else (donating the resident
    table would hand XLA the banks' buffers every year)."""
    args, _kwargs = audit.args_info
    expected = set(audit.spec.donate_args)
    in_ok = in_bad = out_bad = 0
    for i, sub in enumerate(args):
        leaves = jax.tree.leaves(
            sub, is_leaf=lambda x: hasattr(x, "donated")
        )
        for leaf in leaves:
            if i in expected:
                if getattr(leaf, "donated", False):
                    in_ok += 1
                else:
                    in_bad += 1
            elif getattr(leaf, "donated", False):
                out_bad += 1
    return in_ok, in_bad, out_bad
