"""J6: the cost-fingerprint regression gate.

For each cost-marked registry entry the auditor compiles the tiny
abstract program (CPU, no execution) and reads
``compiled.cost_analysis()`` — flops, bytes accessed, transcendentals —
plus the captured-constant byte total and the location-stripped program
hash. Those numbers are *deterministic functions of the compiled
program* at the fixed audit shapes: zero timing noise, zero hardware
dependence within a backend. They are committed to
``tools/prog_baseline.json``; any PR whose lowered programs grow
(or shrink) a fingerprint beyond the tolerance fails the gate until it
explicitly refreshes the baseline (``python -m dgen_tpu.lint --programs
--update-baselines``) — making "this change made the compiled year
step 2x more expensive" a reviewable diff line instead of a TPU-day.

Cost numbers are only comparable within one (jax version, platform,
audit-spec version) triple, so the baseline records all three and the
gate downgrades to an advisory note when they differ. The CI lint
step pins its jax to the baseline's recorded version so the gate
ENFORCES there; a jax upgrade re-baselines in its own PR.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from dgen_tpu.lint.core import Finding
from dgen_tpu.lint.prog.spec import AUDIT_SPEC_VERSION, ProgramAudit

#: default relative tolerance on flops / bytes-accessed drift
DEFAULT_TOLERANCE = 0.02
#: absolute slack on captured-constant bytes (tiny shared constants —
#: month one-hots, daylight gather indices — may legitimately move)
CONST_BYTES_SLACK = 64 * 1024

#: the gated metrics (relative tolerance); program_hash and
#: transcendentals are recorded but informational
GATED_METRICS = ("flops", "bytes_accessed")


def default_baseline_path() -> str:
    """``tools/prog_baseline.json`` at the repo root (next to the
    ``dgen_tpu`` package)."""
    pkg = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(os.path.dirname(pkg), "tools",
                        "prog_baseline.json")


def collect_fingerprints(audits: List[ProgramAudit]) -> Dict[str, dict]:
    """Cost fingerprints of the cost-marked, successfully-compiled
    audits, keyed by spec id."""
    out: Dict[str, dict] = {}
    for a in audits:
        if a.cost_analysis is None or a.error:
            continue
        out[a.spec.spec_id] = {
            "flops": a.cost_analysis["flops"],
            "bytes_accessed": a.cost_analysis["bytes_accessed"],
            "transcendentals": a.cost_analysis["transcendentals"],
            "const_bytes": a.const_bytes,
            "program_hash": a.fingerprint,
        }
    return out


def load_baseline(path: str) -> Optional[dict]:
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def _environment() -> dict:
    import jax

    return {
        "jax": jax.__version__,
        "platform": jax.default_backend(),
        "spec": AUDIT_SPEC_VERSION,
    }


def baseline_applicable(baseline: Optional[dict]) -> Tuple[bool, str]:
    """Whether the committed baseline is comparable in THIS
    environment; the reason string becomes the advisory note when it
    is not."""
    if baseline is None:
        return False, "no baseline file (run --update-baselines to seed it)"
    env = _environment()
    for key in ("jax", "platform", "spec"):
        if baseline.get(key) != env[key]:
            return False, (
                f"baseline {key}={baseline.get(key)!r} != "
                f"{env[key]!r}; cost fingerprints are only comparable "
                "within one (jax, platform, spec) triple — re-baseline "
                "with --update-baselines"
            )
    return True, ""


def compare_to_baseline(
    audits: List[ProgramAudit],
    baseline: Optional[dict],
    tolerance: Optional[float] = None,
    partial: bool = False,
) -> Tuple[List[Finding], dict]:
    """The J6 gate: (findings, status). Status carries the advisory
    note (inapplicable baseline), the per-entry deltas, and the fresh
    fingerprints (for --json consumers and bench stamping).

    ``partial``: the audits cover an ``--entries`` subset of the
    registry — baseline entries absent from the subset are someone
    else's programs, not stale, so the stale-entry sweep is skipped."""
    current = collect_fingerprints(audits)
    status: dict = {
        "environment": _environment(),
        "fingerprints": current,
        "deltas": {},
        "note": None,
    }
    ok, why = baseline_applicable(baseline)
    if not ok:
        status["note"] = f"J6 gate skipped: {why}"
        return [], status

    tol = tolerance if tolerance is not None \
        else float(baseline.get("tolerance", DEFAULT_TOLERANCE))
    status["tolerance"] = tol
    entries = baseline.get("entries", {})
    anchors = {a.spec.spec_id: a.spec.anchor for a in audits}
    findings: List[Finding] = []

    def emit(spec_id: str, msg: str) -> None:
        path, line = anchors.get(spec_id, ("<unknown>", 0))
        findings.append(Finding("J6", path, line, f"[{spec_id}] {msg}"))

    for spec_id, cur in sorted(current.items()):
        base = entries.get(spec_id)
        if base is None:
            emit(spec_id, (
                "no committed cost baseline for this entry — run "
                "`python -m dgen_tpu.lint --programs "
                "--update-baselines` and commit tools/prog_baseline.json"
            ))
            continue
        deltas = {}
        for metric in GATED_METRICS:
            old = float(base.get(metric, 0.0))
            new = float(cur[metric])
            rel = (new - old) / old if old else (0.0 if not new else 1.0)
            deltas[metric] = round(rel, 6)
            if abs(rel) > tol:
                direction = "grew" if rel > 0 else "shrank"
                hint = (
                    "grew without a baseline update — a perf "
                    "regression gate with zero timing noise; if the "
                    "growth is intended, refresh the baseline "
                    "(--update-baselines) so the cost change is an "
                    "explicit, reviewable diff"
                    if rel > 0 else
                    "shrank — lock the improvement in with "
                    "--update-baselines so a later regression back to "
                    "the old cost cannot pass unnoticed"
                )
                emit(spec_id, (
                    f"compiled {metric} {direction} {abs(rel) * 100:.1f}% "
                    f"({old:.6g} -> {new:.6g}, tolerance "
                    f"{tol * 100:.1f}%): {hint}"
                ))
        old_cb = int(base.get("const_bytes", 0))
        if cur["const_bytes"] > old_cb + CONST_BYTES_SLACK:
            emit(spec_id, (
                f"captured-constant bytes grew {old_cb} -> "
                f"{cur['const_bytes']} (> {CONST_BYTES_SLACK} B slack): "
                "something new is baked into the program — pass it as "
                "a traced argument, or re-baseline if deliberate"
            ))
        status["deltas"][spec_id] = deltas

    if not partial:
        # an entry the registry still PRODUCES but which failed to
        # lower is a J0 finding, not a stale baseline — deleting its
        # committed gate would be exactly wrong
        produced = {a.spec.spec_id for a in audits}
        for spec_id in sorted(set(entries) - set(current) - produced):
            emit(spec_id, (
                "baseline entry no longer produced by the registry — "
                "remove it via --update-baselines so the baseline "
                "stays in lockstep with the audited entry set"
            ))
    return findings, status


def update_baseline(
    path: str,
    audits: List[ProgramAudit],
    tolerance: float = DEFAULT_TOLERANCE,
    partial: bool = False,
) -> dict:
    """Rewrite the baseline from the current audits (atomic publish:
    a killed writer cannot truncate the committed gate).

    ``partial`` (an ``--entries`` subset): MERGE into the existing
    baseline instead of replacing it — a targeted refresh must not
    delete the committed gate for every other program. Refused when
    the existing baseline was recorded under a different environment
    (the untouched entries would be incomparable with the fresh ones).
    """
    from dgen_tpu.resilience.atomic import atomic_write_json

    entries = collect_fingerprints(audits)
    if partial:
        existing = load_baseline(path)
        if existing is not None:
            ok, why = baseline_applicable(existing)
            if not ok:
                raise ValueError(
                    "refusing a partial baseline update: " + why
                )
            entries = dict(existing.get("entries", {}), **entries)
    doc = dict(
        _environment(),
        tolerance=tolerance,
        entries=entries,
    )
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    atomic_write_json(path, doc, indent=1, sort_keys=True)
    return doc
