"""J6: the cost-fingerprint regression gate.

For each cost-marked registry entry the auditor compiles the tiny
abstract program (CPU, no execution) and reads
``compiled.cost_analysis()`` — flops, bytes accessed, transcendentals —
plus the captured-constant byte total and the location-stripped program
hash. Those numbers are *deterministic functions of the compiled
program* at the fixed audit shapes: zero timing noise, zero hardware
dependence within a backend. They are committed to
``tools/prog_baseline.json``; any PR whose lowered programs grow
(or shrink) a fingerprint beyond the tolerance fails the gate until it
explicitly refreshes the baseline (``python -m dgen_tpu.lint --programs
--update-baselines``) — making "this change made the compiled year
step 2x more expensive" a reviewable diff line instead of a TPU-day.

Cost numbers are only comparable within one (jax version, platform,
audit-spec version) triple, so the baseline records all three and the
gate downgrades to an advisory note when they differ. The CI lint
step pins its jax to the baseline's recorded version so the gate
ENFORCES there; a jax upgrade re-baselines in its own PR.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from dgen_tpu.lint.core import Finding
from dgen_tpu.lint.prog.spec import AUDIT_SPEC_VERSION, ProgramAudit

#: default relative tolerance on flops / bytes-accessed drift
DEFAULT_TOLERANCE = 0.02
#: absolute slack on captured-constant bytes (tiny shared constants —
#: month one-hots, daylight gather indices — may legitimately move)
CONST_BYTES_SLACK = 64 * 1024

#: the gated metrics (relative tolerance); program_hash and
#: transcendentals are recorded but informational. ``input_bytes``
#: (sum of the program's parameter aval bytes) is the static
#: kernel-input-traffic term — the one int8 quantized banks and
#: packed streams shrink, which XLA:CPU's bytes_accessed cannot see
#: (its cost model is dominated by f32 intermediates that stay in
#: VMEM on TPU).
GATED_METRICS = ("flops", "bytes_accessed", "input_bytes")

#: mesh-tier re-seed command quoted in J7/J10 findings
MESH_RESEED = (
    "python -m dgen_tpu.lint --programs --mesh --update-baselines"
)


def default_baseline_path() -> str:
    """``tools/prog_baseline.json`` at the repo root (next to the
    ``dgen_tpu`` package)."""
    pkg = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(os.path.dirname(pkg), "tools",
                        "prog_baseline.json")


def collect_fingerprints(audits: List[ProgramAudit]) -> Dict[str, dict]:
    """Cost fingerprints of the cost-marked, successfully-compiled
    audits, keyed by spec id."""
    out: Dict[str, dict] = {}
    for a in audits:
        if a.cost_analysis is None or a.error:
            continue
        out[a.spec.spec_id] = {
            "flops": a.cost_analysis["flops"],
            "bytes_accessed": a.cost_analysis["bytes_accessed"],
            "transcendentals": a.cost_analysis["transcendentals"],
            "input_bytes": a.input_bytes,
            "const_bytes": a.const_bytes,
            "program_hash": a.fingerprint,
        }
    return out


def load_baseline(path: str) -> Optional[dict]:
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def _environment() -> dict:
    import jax

    return {
        "jax": jax.__version__,
        "platform": jax.default_backend(),
        "spec": AUDIT_SPEC_VERSION,
    }


def baseline_applicable(baseline: Optional[dict]) -> Tuple[bool, str]:
    """Whether the committed baseline is comparable in THIS
    environment; the reason string becomes the advisory note when it
    is not."""
    if baseline is None:
        return False, "no baseline file (run --update-baselines to seed it)"
    env = _environment()
    for key in ("jax", "platform", "spec"):
        if baseline.get(key) != env[key]:
            return False, (
                f"baseline {key}={baseline.get(key)!r} != "
                f"{env[key]!r}; cost fingerprints are only comparable "
                "within one (jax, platform, spec) triple — re-baseline "
                "with --update-baselines"
            )
    return True, ""


def _rel_drift(old: float, new: float) -> float:
    """Relative drift with the zero-baseline edge handled once for
    every gated metric (J6 flops/bytes AND J7 counts/comm bytes):
    0 -> 0 is no drift, 0 -> anything is 100% growth."""
    if old:
        return (new - old) / old
    return 0.0 if not new else 1.0


def compare_to_baseline(
    audits: List[ProgramAudit],
    baseline: Optional[dict],
    tolerance: Optional[float] = None,
    partial: bool = False,
) -> Tuple[List[Finding], dict]:
    """The J6 gate: (findings, status). Status carries the advisory
    note (inapplicable baseline), the per-entry deltas, and the fresh
    fingerprints (for --json consumers and bench stamping).

    ``partial``: the audits cover an ``--entries`` subset of the
    registry — baseline entries absent from the subset are someone
    else's programs, not stale, so the stale-entry sweep is skipped."""
    current = collect_fingerprints(audits)
    status: dict = {
        "environment": _environment(),
        "fingerprints": current,
        "deltas": {},
        "note": None,
        # structured downgrade marker — the CLI's loud advisory banner
        # keys on THIS, never on the note's wording
        "downgraded": False,
    }
    ok, why = baseline_applicable(baseline)
    if not ok:
        status["note"] = f"J6 gate skipped: {why}"
        status["downgraded"] = True
        return [], status

    tol = tolerance if tolerance is not None \
        else float(baseline.get("tolerance", DEFAULT_TOLERANCE))
    status["tolerance"] = tol
    entries = baseline.get("entries", {})
    anchors = {a.spec.spec_id: a.spec.anchor for a in audits}
    findings: List[Finding] = []

    def emit(spec_id: str, msg: str) -> None:
        path, line = anchors.get(spec_id, ("<unknown>", 0))
        findings.append(Finding("J6", path, line, f"[{spec_id}] {msg}"))

    for spec_id, cur in sorted(current.items()):
        base = entries.get(spec_id)
        if base is None:
            emit(spec_id, (
                "no committed cost baseline for this entry — run "
                "`python -m dgen_tpu.lint --programs "
                "--update-baselines` and commit tools/prog_baseline.json"
            ))
            continue
        deltas = {}
        for metric in GATED_METRICS:
            if metric not in base:
                # a baseline seeded before this metric existed gates
                # the metrics it has — flagging a freshly-introduced
                # metric as "100% growth" would hard-fail every older
                # committed baseline with a misleading message;
                # --update-baselines brings the new metric under gate
                continue
            old = float(base.get(metric, 0.0))
            new = float(cur[metric])
            rel = _rel_drift(old, new)
            deltas[metric] = round(rel, 6)
            if abs(rel) > tol:
                direction = "grew" if rel > 0 else "shrank"
                hint = (
                    "grew without a baseline update — a perf "
                    "regression gate with zero timing noise; if the "
                    "growth is intended, refresh the baseline "
                    "(--update-baselines) so the cost change is an "
                    "explicit, reviewable diff"
                    if rel > 0 else
                    "shrank — lock the improvement in with "
                    "--update-baselines so a later regression back to "
                    "the old cost cannot pass unnoticed"
                )
                emit(spec_id, (
                    f"compiled {metric} {direction} {abs(rel) * 100:.1f}% "
                    f"({old:.6g} -> {new:.6g}, tolerance "
                    f"{tol * 100:.1f}%): {hint}"
                ))
        old_cb = int(base.get("const_bytes", 0))
        if cur["const_bytes"] > old_cb + CONST_BYTES_SLACK:
            emit(spec_id, (
                f"captured-constant bytes grew {old_cb} -> "
                f"{cur['const_bytes']} (> {CONST_BYTES_SLACK} B slack): "
                "something new is baked into the program — pass it as "
                "a traced argument, or re-baseline if deliberate"
            ))
        status["deltas"][spec_id] = deltas

    if not partial:
        # an entry the registry still PRODUCES but which failed to
        # lower is a J0 finding, not a stale baseline — deleting its
        # committed gate would be exactly wrong
        produced = {a.spec.spec_id for a in audits}
        for spec_id in sorted(set(entries) - set(current) - produced):
            emit(spec_id, (
                "baseline entry no longer produced by the registry — "
                "remove it via --update-baselines so the baseline "
                "stays in lockstep with the audited entry set"
            ))
    return findings, status


def collect_mesh_fingerprints(
    audits: List[ProgramAudit],
) -> Dict[str, dict]:
    """The J7/J10 fingerprints of the mesh-tier audits, keyed by spec
    id (``entry@meshHxD``): loc-stripped sharded-program hash,
    per-collective counts + estimated comm bytes, and the per-device
    peak (informational — J9 gates it against the budget, not the
    baseline)."""
    out: Dict[str, dict] = {}
    for a in audits:
        if a.mesh is None or a.error:
            continue
        info = a.mesh
        out[a.spec.spec_id] = {
            "mesh_shape": list(info.shape),
            "program_hash": a.fingerprint,
            "collectives": {
                kind: {
                    "count": info.counts[kind],
                    "comm_bytes": info.comm_bytes_by_kind[kind],
                }
                for kind in sorted(info.counts)
            },
            "comm_bytes": info.comm_bytes,
            "peak_bytes": info.peak_bytes,
        }
    return out


def compare_mesh_to_baseline(
    audits: List[ProgramAudit],
    baseline: Optional[dict],
    tolerance: Optional[float] = None,
    partial: bool = False,
) -> Tuple[List[Finding], dict]:
    """The J7 (collective fingerprint) + J10 (per-mesh-shape program
    hash) gates over the mesh-tier audits. Same environment contract
    as J6: inapplicable baselines downgrade to an advisory note.

    J7 fails on any NEW collective kind (the offending op and its
    operand shape named), on a collective kind that vanished (lock the
    improvement in), and on count / comm-byte drift beyond the
    tolerance. J10 fails on any sharded-program hash change — a
    topology-sensitive program change must land as a reviewable
    baseline diff.
    """
    current = collect_mesh_fingerprints(audits)
    status: dict = {
        "environment": _environment(),
        "fingerprints": current,
        "note": None,
        "downgraded": False,
    }
    ok, why = baseline_applicable(baseline)
    if not ok:
        status["note"] = f"J7/J10 gate skipped: {why}"
        status["downgraded"] = True
        return [], status
    tol = tolerance if tolerance is not None \
        else float(baseline.get("tolerance", DEFAULT_TOLERANCE))
    status["tolerance"] = tol
    entries = baseline.get("mesh", {})
    by_id = {a.spec.spec_id: a for a in audits if a.mesh is not None}
    findings: List[Finding] = []

    def emit(rule: str, spec_id: str, msg: str) -> None:
        a = by_id.get(spec_id)
        path, line = a.spec.anchor if a else ("<unknown>", 0)
        findings.append(Finding(rule, path, line, f"[{spec_id}] {msg}"))

    for spec_id, cur in sorted(current.items()):
        base = entries.get(spec_id)
        if base is None:
            emit("J7", spec_id, (
                "no committed mesh baseline for this entry — run "
                f"`{MESH_RESEED}` and commit tools/prog_baseline.json"
            ))
            continue
        if cur["program_hash"] != base.get("program_hash"):
            emit("J10", spec_id, (
                "sharded program fingerprint changed for mesh shape "
                f"{'x'.join(str(s) for s in cur['mesh_shape'])} — a "
                "topology-sensitive program change must land as an "
                "explicit baseline diff (review the collective/memory "
                f"deltas, then `{MESH_RESEED}`)"
            ))
        base_coll = base.get("collectives", {})
        info = by_id[spec_id].mesh
        for kind in sorted(set(cur["collectives"]) | set(base_coll)):
            c = cur["collectives"].get(kind)
            b = base_coll.get(kind)
            if b is None:
                ops = [
                    x for x in info.collectives if x.kind == kind
                ]
                shapes = "; ".join(
                    f"{' '.join(o.result_shapes)} <- "
                    f"{', '.join(o.operand_shapes[:3])}"
                    for o in ops[:3]
                )
                emit("J7", spec_id, (
                    f"NEW collective `{kind}` x{c['count']} on the hot "
                    f"path (~{c['comm_bytes']} comm bytes): {shapes} — "
                    "an op gathered/resharded data that previously "
                    "stayed device-local; if intended, re-baseline "
                    f"with `{MESH_RESEED}`"
                ))
                continue
            if c is None:
                emit("J7", spec_id, (
                    f"collective `{kind}` (was x{b.get('count')}) no "
                    "longer appears — lock the comms improvement in "
                    f"with `{MESH_RESEED}` so a regression back cannot "
                    "pass unnoticed"
                ))
                continue
            for metric in ("count", "comm_bytes"):
                old = float(b.get(metric, 0.0))
                new = float(c[metric])
                rel = _rel_drift(old, new)
                if abs(rel) > tol:
                    direction = "grew" if rel > 0 else "shrank"
                    emit("J7", spec_id, (
                        f"`{kind}` {metric} {direction} "
                        f"{abs(rel) * 100:.1f}% ({old:.6g} -> "
                        f"{new:.6g}, tolerance {tol * 100:.1f}%) — "
                        "static comm cost is a zero-noise regression "
                        "gate; re-baseline if the change is intended"
                    ))

    if not partial:
        produced = {a.spec.spec_id for a in audits}
        # keys recorded for mesh shapes OUTSIDE the audited grid are a
        # deliberately merged custom-shape seed (--mesh-shapes ...
        # --update-baselines), not staleness — flagging them would
        # wedge every default run after a documented custom seed
        audited_shapes = {
            tuple(c["mesh_shape"]) for c in current.values()
        }
        for spec_id in sorted(set(entries) - set(current) - produced):
            shape = entries[spec_id].get("mesh_shape")
            if shape is not None and tuple(shape) not in audited_shapes:
                continue
            emit("J7", spec_id, (
                "mesh baseline entry no longer produced by the mesh "
                f"registry — remove it via `{MESH_RESEED}` so the "
                "baseline stays in lockstep with the audited grid"
            ))
    return findings, status


def update_baseline(
    path: str,
    audits: List[ProgramAudit],
    tolerance: float = DEFAULT_TOLERANCE,
    partial: bool = False,
    mesh_audits: Optional[List[ProgramAudit]] = None,
    mesh_partial: bool = False,
) -> dict:
    """Rewrite the baseline from the current audits (atomic publish:
    a killed writer cannot truncate the committed gate).

    ``partial`` (an ``--entries`` subset): MERGE into the existing
    baseline instead of replacing it — a targeted refresh must not
    delete the committed gate for every other program. Refused when
    the existing baseline was recorded under a different environment
    (the untouched entries would be incomparable with the fresh ones).

    ``mesh_audits``: the mesh-tier audits (``--mesh`` ran) — their
    J7/J10 fingerprints land in the document's ``mesh`` section (merged
    under ``partial``, replaced otherwise). When the mesh tier did NOT
    run, an existing comparable ``mesh`` section is carried over
    verbatim — a cost-only refresh must not delete the committed
    collective gates (an incomparable one is dropped: stale-environment
    hashes would gate wrongly under the new triple).
    """
    from dgen_tpu.resilience.atomic import atomic_write_json

    entries = collect_fingerprints(audits)
    try:
        existing = load_baseline(path)
    except (OSError, ValueError) as e:
        # a truncated/conflict-markered baseline must not break the
        # documented repair command: a FULL update re-seeds over it; a
        # partial update cannot (there is nothing valid to merge into)
        if partial:
            raise ValueError(
                f"cannot merge a partial update into an unreadable "
                f"baseline ({e}); run a full --update-baselines"
            ) from e
        existing = None
    existing_ok = False
    if existing is not None:
        existing_ok, why = baseline_applicable(existing)
        if partial and not existing_ok:
            raise ValueError(
                "refusing a partial baseline update: " + why
            )
    if partial and existing is not None:
        entries = dict(existing.get("entries", {}), **entries)

    mesh: Dict[str, dict] = {}
    if mesh_audits is not None:
        mesh = collect_mesh_fingerprints(mesh_audits)
        if mesh_partial and existing is not None:
            if not existing_ok and existing.get("mesh"):
                raise ValueError(
                    "refusing a partial mesh-baseline update: " + why
                )
            mesh = dict(existing.get("mesh", {}), **mesh)
        elif existing_ok:
            # a FULL re-seed replaces the audited grid's keys but
            # preserves deliberately seeded custom-shape gates
            # (--mesh-shapes ... --update-baselines merges them in;
            # the compare-side stale sweep exempts them for the same
            # reason) — only keys in the freshly audited shapes are
            # authoritative here
            shapes = {tuple(v["mesh_shape"]) for v in mesh.values()}
            for k, v in existing.get("mesh", {}).items():
                sh = v.get("mesh_shape")
                if (
                    sh is not None and tuple(sh) not in shapes
                    and k not in mesh
                ):
                    mesh[k] = v
    elif existing_ok:
        mesh = dict(existing.get("mesh", {}))

    doc = dict(
        _environment(),
        tolerance=tolerance,
        entries=entries,
    )
    if mesh:
        doc["mesh"] = mesh
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    atomic_write_json(path, doc, indent=1, sort_keys=True)
    return doc
