"""The audited entry-point registry.

Every jitted program the repo ships is named here with an abstract-spec
builder: the tiny synthetic world it lowers against and the
static-config grid points it must stay clean on. The grid axes mirror
the REAL compile vocabulary (``RunConfig.daylight_compact`` x
``RunConfig.bf16_banks`` x the host-decided ``net_billing`` flag, plus
the sweep's vmap/loop split and the streaming ``agent_chunk`` scan) —
the audited programs are built through the SAME kwarg paths production
uses (:meth:`Simulation.step_kwargs`, the sweep driver's group
overrides, the serve engine's static set), so a knob that silently
changes the compiled program changes an audited fingerprint here
first.

Entries (see ``docs/lint.md`` for the operator-facing table):

====================  =====================================================
``year_step``         the jitted one-year program, full (dl x bf16 x nb)
                      cartesian grid; first-year/steady pair + steady
                      repeat probe at the base point
``year_step_chunked`` the streaming lax.scan variant (``agent_chunk``)
``sweep_year_step``   vmap-mode sweep (S=2 scenario axis)
``sweep_loop``        loop-mode sweep — must fingerprint-match
                      ``year_step`` (zero extra compiles, PR 3 contract)
``serve_query``       the serve engine's bucket program
``size_agents``       the standalone sizing engine
``import_sums``       the candidate bucket-sums bill kernel (+ daylight
                      layout and bf16-bank input variants)
``import_sums_pair``  the rate-switch fused twin
``bucket_sums``       the full-reduction engine (battery forward runs)
``size_agents_soft``  the smooth sizing twin (``soft_tau`` set)
``newton_step``       one damped Newton step on the smooth NPV
                      objective (grad-marked: J11 audits it)
``calib_loss``        value_and_grad of the calibration loss through
                      the rollout (grad-marked; reduced audit scale)
====================  =====================================================

Grid depth: ``grid="fast"`` audits each entry's base point only (test
tier); the default audits every declared variant.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dgen_tpu.lint.prog.spec import (
    AUDIT_CHUNK,
    AUDIT_ECON_YEARS,
    AUDIT_END_YEAR,
    AUDIT_MESH_CHUNK,
    AUDIT_N_AGENTS,
    AUDIT_QUERY_BUCKET,
    AUDIT_SIZING_ITERS,
    AUDIT_STATES,
    AUDIT_SWEEP_S,
    Bound,
    ProgramSpec,
    anchor_for,
)

#: the mesh-audit grid (``--programs --mesh``): the production 1-D
#: agent mesh over 8 virtual CPU devices plus the 2-D hosts x devices
#: grid (SNIPPETS.md [1]/[3] placement) — ≥ 2 shapes incl. one 2-D, the
#: pre-silicon vocabulary the pod-scale runs compile under
MESH_GRID_DEFAULT = ((1, 8), (2, 4))
#: test-tier mesh grid: the one 2-D shape only (tier-1 budget) — a
#: DEFAULT-grid shape, so the fast tier gates against the same
#: committed mesh baseline keys the full grid does
MESH_GRID_FAST = ((2, 4),)

# -- tiny worlds (memoized per compile-relevant flag set) -------------------

_WORLDS: Dict[tuple, object] = {}


def _build_world(daylight: bool, bf16: bool, chunk: int,
                 mesh_shape: Optional[tuple], quant: bool = False,
                 pack: bool = False, cluster: bool = False):
    """ONE construction path for every audit world — single-device
    grid AND mesh tier — over the fixed tiny synthetic population, so
    the two tiers cannot silently audit divergent worlds. Simulation's
    __init__ is where the daylight layout, bank dtype conversion,
    padding, placement and the static run flags are decided, so going
    through it keeps the audited programs on the production path.

    Mesh worlds turn ``partition_by_state`` off: the state->device bin
    packing is a host-side row permutation (the compiled program is
    identical), and with only :data:`AUDIT_STATES` states it would
    leave most of an 8-device mesh empty — even row sharding keeps
    every audited shard populated.
    """
    from dgen_tpu.config import RunConfig, ScenarioConfig
    from dgen_tpu.io import synth
    from dgen_tpu.models import scenario as scen
    from dgen_tpu.models.simulation import Simulation

    cfg = ScenarioConfig(
        name="prog-audit" + ("-mesh" if mesh_shape else ""),
        start_year=2014, end_year=AUDIT_END_YEAR,
    )
    pop = synth.generate_population(
        AUDIT_N_AGENTS, states=list(AUDIT_STATES), seed=7,
        pad_multiple=32,
    )
    inputs = scen.uniform_inputs(
        cfg, n_groups=pop.table.n_groups, n_regions=pop.n_regions,
        overrides={
            "attachment_rate": jnp.full((pop.table.n_groups,), 0.4)
        },
    )
    rc = RunConfig(
        sizing_iters=AUDIT_SIZING_ITERS, agent_chunk=chunk,
        agent_pad_multiple=32, daylight_compact=daylight,
        bf16_banks=bf16, quant_banks=quant, pack_once=pack,
        partition_by_state=mesh_shape is None,
        cluster_tariffs=cluster,
    )
    mesh = None
    if mesh_shape is not None:
        from dgen_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(shape=mesh_shape)
    return Simulation(
        pop.table, pop.profiles, pop.tariffs, inputs, cfg, rc,
        econ_years=AUDIT_ECON_YEARS, mesh=mesh,
    )


def _world(daylight: bool = False, bf16: bool = False, chunk: int = 0,
           quant: bool = False, pack: bool = False,
           cluster: bool = False):
    """The memoized single-device audit world per (daylight, bf16,
    chunk, quant, pack, cluster) grid point."""
    key = (daylight, bf16, chunk, quant, pack, cluster)
    if key not in _WORLDS:
        _WORLDS[key] = _build_world(daylight, bf16, chunk, None,
                                    quant=quant, pack=pack,
                                    cluster=cluster)
    return _WORLDS[key]


def _yi(i: int):
    return jnp.asarray(i, dtype=jnp.int32)


# -- per-entry bound builders ----------------------------------------------
#
# Each entry has ONE body parameterized on the built world (`sim`), so
# the single-device grid and the mesh tier lower the SAME construction
# path — a kwarg added to a base builder cannot silently miss its mesh
# twin (the audit/production-drift these builders exist to prevent).

def _year_step_bound_for(sim, net_billing, first_year,
                         year: int) -> Bound:
    from dgen_tpu.models.simulation import SimCarry, year_step

    kwargs = sim.step_kwargs(first_year)
    kwargs["net_billing"] = net_billing
    # clustered worlds carry traced operands (compact banks + local
    # indices) alongside their static layout; empty otherwise
    kwargs.update(sim.step_operands())
    carry = SimCarry.zeros(sim.table.n_agents)
    return Bound(
        fn=year_step,
        args=(sim.table, sim.profiles, sim.tariffs, sim.inputs, carry,
              _yi(year)),
        kwargs=kwargs,
    )


def _year_step_bound(daylight, bf16, net_billing, first_year,
                     year: int, chunk: int = 0) -> Bound:
    return _year_step_bound_for(
        _world(daylight, bf16, chunk), net_billing, first_year, year
    )


def _year_step_qp_bound(year: int) -> Bound:
    """The quant_banks + pack_once year step (steady, net-billing) —
    the J6 entry whose committed bytes_accessed proves the per-year
    kernel-input traffic shrank (ISSUE 12)."""
    return _year_step_bound_for(
        _world(quant=True, pack=True), True, False, year
    )


def _year_step_cluster_bound(first_year: bool, year: int) -> Bound:
    """The tariff-clustered year step (ISSUE 19, ops.tariffcluster):
    the production program of a mixed-tariff national run — sizing
    runs once per tariff cluster at the cluster's tight pad widths
    against its compact shared bank. Default-grid-only (like the
    quant+pack entry); single-device covers the per-cluster program
    structure, the mesh tier's GSPMD propagation is unchanged by the
    host-side row permutation."""
    return _year_step_bound_for(_world(cluster=True), True, first_year,
                                year)


def _sweep_bound_for(sim, net_billing, first_year, year: int) -> Bound:
    from dgen_tpu.models.scenario import stack_scenarios
    from dgen_tpu.models.simulation import SimCarry
    from dgen_tpu.sweep.driver import sweep_year_step

    members = [
        sim.inputs,
        dataclasses.replace(
            sim.inputs, itc_fraction=sim.inputs.itc_fraction * 0.8
        ),
    ][:AUDIT_SWEEP_S]
    inputs_s = stack_scenarios(members).inputs
    # the sweep driver's group kwargs: step_kwargs + per-group
    # net-billing override, mesh dropped inside the vmapped body (the
    # TABLE operands keep the world's placement, so under a mesh world
    # GSPMD propagation is what the mesh tier audits)
    kwargs = sim.step_kwargs(first_year)
    kwargs["net_billing"] = net_billing
    kwargs["mesh"] = None
    zeros = SimCarry.zeros(sim.table.n_agents)
    carry = jax.tree.map(
        lambda x: jnp.zeros((AUDIT_SWEEP_S,) + x.shape, x.dtype), zeros
    )
    return Bound(
        fn=sweep_year_step,
        args=(sim.table, sim.profiles, sim.tariffs, inputs_s, carry,
              _yi(year)),
        kwargs=kwargs,
    )


def _sweep_bound(net_billing, bf16, first_year, year: int) -> Bound:
    return _sweep_bound_for(
        _world(False, bf16), net_billing, first_year, year
    )


def _sweep_loop_bound_for(sim, year: int) -> Bound:
    """Loop-mode sweep: the sweep driver runs each scenario through a
    :meth:`Simulation.with_inputs` sibling of the base sim — build the
    bound through that REAL path so a drift in how siblings construct
    their step kwargs (vs the base program J5 compares against) lowers
    a different program here and fails the identity check."""
    from dgen_tpu.models.simulation import SimCarry, year_step

    variant = dataclasses.replace(
        sim.inputs, itc_fraction=sim.inputs.itc_fraction * 0.8
    )
    # the planner pins net_billing per scenario group (driver.py)
    sib = sim.with_inputs(variant, net_billing=True)
    carry = SimCarry.zeros(sib.table.n_agents)
    return Bound(
        fn=year_step,
        args=(sib.table, sib.profiles, sib.tariffs, sib.inputs, carry,
              _yi(year)),
        kwargs=sib.step_kwargs(False),
    )


def _sweep_loop_bound(year: int) -> Bound:
    return _sweep_loop_bound_for(_world(False, False), year)


def _ensemble_bound_for(sim, net_billing, first_year, year: int,
                        cohorts: bool) -> Bound:
    """The ensemble driver's vmap-mode program at E=2 members, built
    through the SAME kwarg path the driver uses (step_kwargs + the
    per-run net-billing pin + step_operands); the cohort variant adds
    the entry-year/year operands, so the fused shared-mask compute is
    part of the audited program."""
    from dgen_tpu.ensemble.driver import ensemble_year_step
    from dgen_tpu.models.scenario import stack_scenarios
    from dgen_tpu.models.simulation import SimCarry

    members = [
        sim.inputs,
        dataclasses.replace(sim.inputs, bass_p=sim.inputs.bass_p * 1.2),
    ]
    inputs_e = stack_scenarios(members).inputs
    kwargs = sim.step_kwargs(first_year)
    kwargs["net_billing"] = net_billing
    kwargs["mesh"] = None
    kwargs.update(sim.step_operands())
    zeros = SimCarry.zeros(sim.table.n_agents)
    carry = jax.tree.map(
        lambda x: jnp.zeros((2,) + x.shape, x.dtype), zeros
    )
    entry_dev = year_f = None
    if cohorts:
        entry = np.zeros(sim.table.n_agents, np.float32)
        entry[-32:] = 2016.0
        entry_dev = jnp.asarray(entry)
        year_f = jnp.asarray(2015.0, jnp.float32)
    return Bound(
        fn=ensemble_year_step,
        args=(sim.table, sim.profiles, sim.tariffs, inputs_e,
              entry_dev, year_f, carry, _yi(year)),
        kwargs=kwargs,
    )


def _ensemble_bound(first_year, year: int, cohorts: bool = False) -> Bound:
    return _ensemble_bound_for(
        _world(False, False), True, first_year, year, cohorts
    )


def _cohort_mask_bound() -> Bound:
    """The per-year population-dynamics program: the whole of it — one
    compare and one multiply over [N] (dgen_tpu.ensemble.cohorts)."""
    from dgen_tpu.ensemble.cohorts import cohort_alive_mask

    sim = _world()
    entry = np.zeros(sim.table.n_agents, np.float32)
    entry[-32:] = 2016.0
    return Bound(
        fn=cohort_alive_mask,
        args=(sim.table.mask, jnp.asarray(entry),
              jnp.asarray(2015.0, jnp.float32)),
        kwargs={},
    )


def _serve_bound_for(sim, year: int) -> Bound:
    from dgen_tpu.serve.engine import query_program, query_static_kwargs

    # the ServeEngine static set, via the SAME constructor the engine
    # uses — an engine-side change to the set changes the audited
    # program here, not just production
    statics = query_static_kwargs(sim)
    idx = jnp.zeros(AUDIT_QUERY_BUCKET, dtype=jnp.int32)
    return Bound(
        fn=query_program,
        args=(sim.table, sim.profiles, sim.tariffs, sim.inputs, idx,
              _yi(year)),
        kwargs=statics,
    )


def _serve_bound(daylight, year: int) -> Bound:
    return _serve_bound_for(_world(daylight, False), year)


def _audit_envs_for(sim):
    """The first-year econ envs of an audit world — the envs build runs
    eagerly on tiny arrays (host-side spec construction, not part of
    the audited program)."""
    from dgen_tpu.models.scenario import apply_year
    from dgen_tpu.models.simulation import (
        build_econ_inputs,
        compute_nem_allowed,
        starting_state_kw,
    )

    ya = apply_year(sim.table, sim.inputs, _yi(0))
    state_kw = starting_state_kw(sim.table, sim.inputs)
    nem = compute_nem_allowed(sim.table, sim.inputs, _yi(0), state_kw)
    return build_econ_inputs(
        sim.table, sim.profiles, sim.tariffs, ya, nem,
        sim.table.incentives, rate_switch=sim._rate_switch,
    )


def _size_agents_bound_for(sim, net_billing, soft_tau=None) -> Bound:
    from dgen_tpu.ops import sizing as sizing_ops

    envs = _audit_envs_for(sim)
    fn = jax.jit(partial(
        sizing_ops.size_agents,
        n_periods=sim.tariffs.max_periods, n_years=sim.econ_years,
        n_iters=AUDIT_SIZING_ITERS, keep_hourly=False, impl="xla",
        net_billing=net_billing, daylight=sim._daylight, mesh=sim.mesh,
        pack_once=sim.run_config.pack_once, soft_tau=soft_tau,
    ))
    return Bound(fn=fn, args=(envs,), kwargs={})


def _size_agents_bound(net_billing, daylight, bf16, quant=False,
                       pack=False, soft_tau=None) -> Bound:
    return _size_agents_bound_for(
        _world(daylight, bf16, quant=quant, pack=pack), net_billing,
        soft_tau=soft_tau,
    )


#: the audited smoothing temperature — matches the grad stack's
#: DEFAULT_TAU (dgen_tpu.grad); part of the baseline contract like the
#: AUDIT_* shape constants
AUDIT_SOFT_TAU = 0.1


def _newton_step_bound() -> Bound:
    """One damped Newton sizing step over the smooth NPV objective —
    the jvp-of-grad program dgen_tpu.grad.newton dispatches per
    refinement iteration (the J11 subject: its backward path must stay
    free of undeclared gradient-killers). The envs are TRACED (the
    objective's precomputed bill summaries rebuild inside the program)
    so the audited program carries no baked-in streams, mirroring how
    a jitted production caller would wrap newton_size."""
    from dgen_tpu.grad import newton
    from dgen_tpu.ops import sizing as sizing_ops

    sim = _world()
    n_periods = sim.tariffs.max_periods
    n_years = sim.econ_years

    def step(envs):
        npv_fn, lo, hi = sizing_ops.make_npv_objective(
            envs, n_periods, n_years,
            net_billing=True, soft_tau=AUDIT_SOFT_TAU,
        )
        kw0 = 0.5 * (lo + hi)
        return newton.newton_refine(npv_fn, kw0, lo, hi, n_steps=1)

    return Bound(fn=jax.jit(step), args=(_audit_envs_for(sim),),
                 kwargs={})


def _calib_loss_bound() -> Bound:
    """value_and_grad of the calibration loss through the full
    checkpointed rollout — audited at a REDUCED scale (2 model years,
    4 econ years, 2 sizing iters): the backward of the full rollout is
    the most expensive program in the registry to compile, and the
    J5/J6/J11 properties being gated are scale-independent."""
    from dgen_tpu.grad import calibrate

    vg, params = calibrate.calib_loss_entry(
        AUDIT_N_AGENTS, soft_tau=AUDIT_SOFT_TAU,
        end_year=2016, econ_years=4, sizing_iters=2,
    )
    return Bound(fn=jax.jit(vg), args=(params,), kwargs={})


def _kernel_arrays(bf16: bool):
    """Tiny deterministic bill-kernel operands: [8, 8760] streams with
    a 2-period TOU bucket map (n_buckets = 24)."""
    n, h, r = 8, 8760, 5
    rng = np.random.default_rng(11)
    dt = jnp.bfloat16 if bf16 else jnp.float32
    load = jnp.asarray(rng.random((n, h), dtype=np.float32), dtype=dt)
    gen = jnp.asarray(rng.random((n, h), dtype=np.float32), dtype=dt)
    sell = jnp.asarray(
        np.full((n, h), 0.05, dtype=np.float32), dtype=dt
    )
    hour = np.arange(h)
    month = np.minimum(hour // 730, 11)
    period = (hour % 24 >= 17).astype(np.int64)
    bucket = jnp.asarray(
        np.broadcast_to(month * 2 + period, (n, h)), dtype=jnp.int32
    )
    scales = jnp.asarray(
        np.linspace(0.0, 2.0, n * r, dtype=np.float32).reshape(n, r)
    )
    return load, gen, sell, bucket, scales


def _import_sums_bound(layout_on: bool, bf16: bool) -> Bound:
    from dgen_tpu.ops import billpallas

    layout = None
    if layout_on:
        sim = _world(True, False)
        layout = sim._daylight
    load, gen, sell, bucket, scales = _kernel_arrays(bf16)
    return Bound(
        fn=billpallas.import_sums,
        args=(load, gen, sell, bucket, scales),
        kwargs=dict(n_buckets=24, impl="xla", bf16=False, mesh=None,
                    layout=layout),
    )


def _import_sums_packed_bound() -> Bound:
    """import_sums consuming a pre-built PackedStreams over the
    daylight layout — the audited program then contains NO repack
    gather and NO night-sums pass (they ran once at pack time), so its
    committed bytes_accessed diff vs the unpacked daylight entry IS
    the per-engine-call saving pack-once buys (x the up-to-3 calls
    per sizing year)."""
    from dgen_tpu.ops import billpallas

    layout = _world(True, False)._daylight
    load, gen, sell, bucket, scales = _kernel_arrays(False)
    pk = billpallas.pack_streams(
        load, gen, sell, bucket, 24, layout=layout)
    return Bound(
        fn=billpallas.import_sums,
        args=(None, None, None, None, scales),
        kwargs=dict(n_buckets=24, impl="xla", bf16=False, mesh=None,
                    layout=layout, packed=pk),
    )


def _import_sums_quant_bound() -> Bound:
    from dgen_tpu.models.agents import quantize_rows
    from dgen_tpu.ops import billpallas

    load, gen, sell, bucket, scales = _kernel_arrays(False)
    lq, ls = quantize_rows(np.asarray(load))
    gq, gs = quantize_rows(np.asarray(gen))
    return Bound(
        fn=billpallas.import_sums,
        args=(jnp.asarray(lq), jnp.asarray(gq), sell, bucket, scales),
        kwargs=dict(n_buckets=24, impl="xla", bf16=False, mesh=None,
                    layout=None, load_scale=jnp.asarray(ls),
                    gen_scale=jnp.asarray(gs)),
    )


def _import_sums_pair_bound() -> Bound:
    from dgen_tpu.ops import billpallas

    load, gen, sell, bucket, scales = _kernel_arrays(False)
    return Bound(
        fn=billpallas.import_sums_pair,
        args=(load, gen, sell, bucket, sell * 0.5, bucket, scales),
        kwargs=dict(n_buckets=24, impl="xla", mesh=None, layout=None),
    )


def _bucket_sums_bound() -> Bound:
    from dgen_tpu.ops import billpallas

    load, gen, sell, bucket, scales = _kernel_arrays(False)
    return Bound(
        fn=billpallas.bucket_sums,
        args=(load, gen, sell, bucket, scales),
        kwargs=dict(n_buckets=24, impl="xla", mesh=None),
    )


# -- registry ---------------------------------------------------------------

def _v(dl, bf, nb, fy=None, extra: str = "") -> str:
    out = f"dl{int(dl)}-bf{int(bf)}-nb{int(nb)}"
    if fy is not None:
        out += f"-fy{int(fy)}"
    return out + extra


def build_registry(grid: str = "default") -> List[ProgramSpec]:
    """All program specs, deterministic order. ``grid="fast"`` keeps
    each entry's base point only (the probes J4/J5/J6 need)."""
    if grid not in ("default", "fast"):
        raise ValueError(f"unknown grid '{grid}' (default|fast)")
    from dgen_tpu.models.simulation import year_step
    from dgen_tpu.ops import billpallas
    from dgen_tpu.ops.sizing import size_agents
    from dgen_tpu.serve.engine import query_program
    from dgen_tpu.sweep.driver import sweep_year_step

    ys_anchor = anchor_for(year_step)
    specs: List[ProgramSpec] = []

    # year_step: full cartesian over the static-config grid. The base
    # point carries the first-year probe, the steady-repeat probe
    # (year 1 vs year 2 must be the SAME program — the one-compile-
    # per-group invariant RetraceGuard enforces at runtime) and the
    # J6 cost fingerprint.
    base = (False, False, True)
    points = (
        [(dl, bf, nb)
         for dl in (False, True) for bf in (False, True)
         for nb in (True, False)]
        if grid == "default" else [base]
    )
    for dl, bf, nb in points:
        is_base = (dl, bf, nb) == base
        specs.append(ProgramSpec(
            entry="year_step", variant=_v(dl, bf, nb, fy=False),
            build=partial(_year_step_bound, dl, bf, nb, False, 1),
            steady=(
                partial(_year_step_bound, dl, bf, nb, False, 2)
                if is_base else None
            ),
            anchor=ys_anchor, donate_args=(4,), cost=is_base,
        ))
        if is_base:
            specs.append(ProgramSpec(
                entry="year_step", variant=_v(dl, bf, nb, fy=True),
                build=partial(_year_step_bound, dl, bf, nb, True, 0),
                anchor=ys_anchor, donate_args=(4,),
            ))

    # streaming-scan variant (agent_chunk): the program national runs
    # actually compile
    specs.append(ProgramSpec(
        entry="year_step_chunked", variant="dl0-bf0-nb1-fy0",
        build=partial(
            _year_step_bound, False, False, True, False, 1, AUDIT_CHUNK
        ),
        anchor=ys_anchor, donate_args=(4,), cost=True,
    ))

    # int8 quantized banks + pack-once (ISSUE 12): a committed J6
    # bytes_accessed entry to diff against the base point — the static
    # proof that the per-year kernel-input traffic shrank (the fast
    # grid skips it; tests/test_lint_prog.py asserts the committed
    # relation instead of re-lowering)
    if grid == "default":
        specs.append(ProgramSpec(
            entry="year_step", variant="dl0-bf0-nb1-q1-pk1-fy0",
            build=partial(_year_step_qp_bound, 1),
            steady=partial(_year_step_qp_bound, 2),
            anchor=ys_anchor, donate_args=(4,), cost=True,
        ))

    # tariff-clustered year step (ISSUE 19): one sizing program per
    # tariff cluster at tight pad widths — the committed J6 entry
    # proves the per-cluster specialization is what actually lowers
    # (flat/NEM clusters carry no bucket-sums kernel), and the steady
    # pair proves one-compile-per-signature across years
    if grid == "default":
        specs.append(ProgramSpec(
            entry="year_step", variant="dl0-bf0-nb1-cl1-fy0",
            build=partial(_year_step_cluster_bound, False, 1),
            steady=partial(_year_step_cluster_bound, False, 2),
            anchor=ys_anchor, donate_args=(4,), cost=True,
        ))
        specs.append(ProgramSpec(
            entry="year_step", variant="dl0-bf0-nb1-cl1-fy1",
            build=partial(_year_step_cluster_bound, True, 0),
            anchor=ys_anchor, donate_args=(4,),
        ))

    # sweep vmap mode (scenario axis S=2)
    sw_anchor = anchor_for(sweep_year_step)
    sweep_points = (
        [(True, False), (False, False), (True, True)]
        if grid == "default" else [(True, False)]
    )
    for nb, bf in sweep_points:
        is_base = (nb, bf) == (True, False)
        specs.append(ProgramSpec(
            entry="sweep_year_step", variant=_v(False, bf, nb, fy=False),
            build=partial(_sweep_bound, nb, bf, False, 1),
            steady=(
                partial(_sweep_bound, nb, bf, False, 2)
                if is_base else None
            ),
            anchor=sw_anchor, donate_args=(4,), cost=is_base,
        ))

    # sweep loop mode: scenario-major over the SAME compiled
    # single-scenario year_step — audited as a fingerprint-identity
    # cross-check through the REAL with_inputs sibling path (a drift
    # in how siblings construct their step kwargs would compile one
    # extra program PER SCENARIO, which J5 reports here)
    specs.append(ProgramSpec(
        entry="sweep_loop", variant="dl0-bf0-nb1-fy0",
        build=partial(_sweep_loop_bound, 1),
        anchor=sw_anchor, donate_args=(4,),
        expect_same_as="year_step@dl0-bf0-nb1-fy0",
    ))

    # ensemble vmap mode (ISSUE 20, member axis E=2) + the cohort
    # mask-update program: the base point carries the steady pair and
    # the J6 cost fingerprint; the cohort variant (default grid) lowers
    # the entry-year data plane fused ahead of the member vmap
    from dgen_tpu.ensemble.cohorts import cohort_alive_mask
    from dgen_tpu.ensemble.driver import ensemble_year_step

    en_anchor = anchor_for(ensemble_year_step)
    specs.append(ProgramSpec(
        entry="ensemble_year_step", variant="dl0-bf0-nb1-fy0",
        build=partial(_ensemble_bound, False, 1),
        steady=partial(_ensemble_bound, False, 2),
        anchor=en_anchor, donate_args=(6,), cost=True,
    ))
    if grid == "default":
        specs.append(ProgramSpec(
            entry="ensemble_year_step", variant="dl0-bf0-nb1-co1-fy0",
            build=partial(_ensemble_bound, False, 1, True),
            steady=partial(_ensemble_bound, False, 2, True),
            anchor=en_anchor, donate_args=(6,), cost=True,
        ))
    specs.append(ProgramSpec(
        entry="cohort_mask_update", variant="base",
        build=_cohort_mask_bound,
        anchor=anchor_for(cohort_alive_mask), cost=True,
    ))

    # serve query program (net_billing pinned True by the engine)
    q_anchor = anchor_for(query_program)
    serve_points = (
        [False, True] if grid == "default" else [False]
    )
    for dl in serve_points:
        is_base = not dl
        specs.append(ProgramSpec(
            entry="serve_query", variant=_v(dl, False, True),
            build=partial(_serve_bound, dl, 0),
            steady=partial(_serve_bound, dl, 1) if is_base else None,
            anchor=q_anchor, cost=is_base,
        ))

    # standalone sizing engine
    sz_anchor = anchor_for(size_agents)
    size_points = (
        [(True, False, False), (False, False, False),
         (True, True, False), (True, False, True)]
        if grid == "default" else [(True, False, False)]
    )
    for nb, dl, bf in size_points:
        is_base = (nb, dl, bf) == (True, False, False)
        # the daylight point carries a cost fingerprint too: the
        # pack-once entry below diffs against it (fewer gather bytes)
        specs.append(ProgramSpec(
            entry="size_agents", variant=_v(dl, bf, nb),
            build=partial(_size_agents_bound, nb, dl, bf),
            anchor=sz_anchor,
            cost=is_base or (nb, dl, bf) == (True, True, False),
        ))
    if grid == "default":
        # ISSUE 12 J6 proofs: int8 quantized banks must shrink the
        # sizing entry's bytes_accessed >= 1.8x vs the committed base
        # point, and pack-once must shrink the daylight entry's bytes
        # (one gather + night pass instead of one per engine call) —
        # tests/test_lint_prog.py gates both relations on the
        # committed tools/prog_baseline.json
        for variant, quant, dl, bf, pack in (
            ("dl0-bf0-nb1-q1", True, False, False, False),
            # quant + bf16 compose: int8 load/gen codes, bf16
            # wholesale/sell — the recommended national-scale setting
            # and the >= 1.8x input-bytes point
            ("dl0-bf1-nb1-q1", True, False, True, False),
            ("dl1-bf0-nb1-pk1", False, True, False, True),
            ("dl0-bf0-nb1-q1-pk1", True, False, False, True),
        ):
            specs.append(ProgramSpec(
                entry="size_agents", variant=variant,
                build=partial(_size_agents_bound, True, dl, bf,
                              quant, pack),
                anchor=sz_anchor, cost=True,
            ))

    # the differentiable twin (ISSUE 18): the smooth sizing program,
    # the Newton refinement step and the calibration loss are
    # committed J5/J6 entries like any other production program — a
    # change to the smoothing primitives or the rollout's AD
    # structure lands as a reviewable baseline diff. newton_step and
    # calib_loss are grad-marked: J11 walks their (differentiated)
    # programs for undeclared gradient-killers. Default grid only:
    # the calibration backward is the most expensive compile in the
    # registry, outside the fast tier's budget.
    if grid == "default":
        from dgen_tpu.grad import calibrate as grad_calibrate
        from dgen_tpu.grad import newton as grad_newton

        specs.append(ProgramSpec(
            entry="size_agents_soft", variant="dl0-bf0-nb1-tau01",
            build=partial(_size_agents_bound, True, False, False,
                          soft_tau=AUDIT_SOFT_TAU),
            anchor=sz_anchor, cost=True,
        ))
        specs.append(ProgramSpec(
            entry="newton_step", variant="tau01",
            build=_newton_step_bound,
            anchor=anchor_for(grad_newton.newton_refine),
            cost=True, grad=True,
        ))
        specs.append(ProgramSpec(
            entry="calib_loss", variant="tau01-small",
            build=_calib_loss_bound,
            anchor=anchor_for(grad_calibrate.calib_loss_entry),
            cost=True, grad=True,
        ))

    # bill kernels (XLA engine pinned: the audit fingerprints must not
    # depend on which backend happens to trace them)
    k_anchor = anchor_for(billpallas.import_sums)
    kernel_points = (
        [(False, False), (True, False), (False, True)]
        if grid == "default" else [(False, False)]
    )
    for layout_on, bf in kernel_points:
        is_base = (layout_on, bf) == (False, False)
        # the daylight point carries a cost fingerprint too: the
        # packed entry below diffs against it (the gather + night pass
        # leave the per-call program)
        specs.append(ProgramSpec(
            entry="import_sums",
            variant=f"layout{int(layout_on)}-bf{int(bf)}",
            build=partial(_import_sums_bound, layout_on, bf),
            anchor=k_anchor,
            cost=is_base or (layout_on, bf) == (True, False),
        ))
    if grid == "default":
        specs.append(ProgramSpec(
            entry="import_sums", variant="layout0-bf0-q1",
            build=_import_sums_quant_bound,
            anchor=k_anchor, cost=True,
        ))
        specs.append(ProgramSpec(
            entry="import_sums", variant="layout1-bf0-pk1",
            build=_import_sums_packed_bound,
            anchor=k_anchor, cost=True,
        ))
        specs.append(ProgramSpec(
            entry="import_sums_pair", variant="layout0-bf0",
            build=_import_sums_pair_bound,
            anchor=anchor_for(billpallas.import_sums_pair),
        ))
    specs.append(ProgramSpec(
        entry="bucket_sums", variant="layout0-bf0",
        build=_bucket_sums_bound,
        anchor=anchor_for(billpallas.bucket_sums), cost=True,
    ))
    return specs


# -- the mesh tier (rules J7-J10) -------------------------------------------

_MESH_WORLDS: Dict[tuple, object] = {}


def _mesh_world(shape: tuple, chunk: int = 0):
    """The memoized mesh-tier audit world per (shape, chunk): the SAME
    :func:`_build_world` construction as the single-device grid, placed
    on a forced multi-device CPU mesh via the production placement path
    (``Simulation.__init__`` + ``parallel.mesh.agent_spec``)."""
    key = (tuple(shape), chunk)
    if key not in _MESH_WORLDS:
        _MESH_WORLDS[key] = _build_world(
            False, False, chunk, tuple(shape)
        )
    return _MESH_WORLDS[key]


def _mesh_model_bytes(shape: tuple, chunk: int) -> int:
    """The sweep planner's per-device working-set prediction for this
    world — the J9 cross-check anchor (``_per_agent_step_bytes`` is the
    SAME model ``auto_agent_chunk`` and ``plan_sweep`` budget with).
    Lazy (resolved at lower time via the spec's model_bytes thunk): the
    world is memoized and shared with the entry's bound builder, so
    entries an ``--entries`` subset never audits never build one."""
    from dgen_tpu.models.simulation import _per_agent_step_bytes

    sim = _mesh_world(shape, chunk)
    n_dev = int(sim.mesh.devices.size)
    n_local = sim.table.n_agents // n_dev
    rows = chunk if chunk and n_local > chunk else n_local
    per_agent = _per_agent_step_bytes(
        sizing_iters=sim.run_config.sizing_iters,
        econ_years=sim.econ_years,
        with_hourly=sim.with_hourly,
        net_billing=True,
        rate_switch=sim._rate_switch,
        bank_bf16=sim.run_config.bf16_banks,
    )
    return rows * per_agent + n_local * 50 * 4


# mesh-tier wrappers: the SAME bound bodies over a mesh world at the
# audited base point (net_billing=True, steady year)

def _mesh_year_step_bound(shape, year: int, chunk: int = 0) -> Bound:
    return _year_step_bound_for(
        _mesh_world(shape, chunk), True, False, year
    )


def _mesh_sweep_bound(shape, year: int) -> Bound:
    return _sweep_bound_for(_mesh_world(shape), True, False, year)


def _mesh_sweep_loop_bound(shape, year: int) -> Bound:
    return _sweep_loop_bound_for(_mesh_world(shape), year)


def _mesh_serve_bound(shape, year: int) -> Bound:
    return _serve_bound_for(_mesh_world(shape), year)


def _mesh_size_agents_bound(shape) -> Bound:
    return _size_agents_bound_for(_mesh_world(shape), True)


def _mesh_kernel_bound(shape, entry: str) -> Bound:
    from jax.sharding import NamedSharding

    from dgen_tpu.ops import billpallas
    from dgen_tpu.parallel.mesh import agent_spec, make_mesh

    mesh = make_mesh(shape=shape)
    load, gen, sell, bucket, scales = _kernel_arrays(False)

    def place(x):
        return jax.device_put(
            x, NamedSharding(mesh, agent_spec(mesh, x.ndim))
        )

    load, gen, sell, bucket, scales = map(
        place, (load, gen, sell, bucket, scales)
    )
    if entry == "import_sums":
        return Bound(
            fn=billpallas.import_sums,
            args=(load, gen, sell, bucket, scales),
            kwargs=dict(n_buckets=24, impl="xla", bf16=False, mesh=mesh,
                        layout=None),
        )
    return Bound(
        fn=billpallas.bucket_sums,
        args=(load, gen, sell, bucket, scales),
        kwargs=dict(n_buckets=24, impl="xla", mesh=mesh),
    )


def mesh_label(shape: tuple) -> str:
    return f"mesh{int(shape[0])}x{int(shape[1])}"


def build_mesh_registry(
    shapes: Optional[List[tuple]] = None,
    grid: str = "default",
) -> List[ProgramSpec]:
    """The mesh-tier specs: every entry point lowered at its base
    static-config point under each (hosts, devices) grid in ``shapes``
    (default: :data:`MESH_GRID_DEFAULT`, or :data:`MESH_GRID_FAST`
    under ``grid="fast"``), compiled, and routed through J7-J10.

    Raises ValueError when the running backend exposes fewer devices
    than the widest shape needs (the CLI forces the virtual CPU device
    count before the backend initializes; see ``--programs --mesh``).
    """
    if shapes is None:
        shapes = list(
            MESH_GRID_DEFAULT if grid == "default" else MESH_GRID_FAST
        )
    from dgen_tpu.models.simulation import year_step
    from dgen_tpu.ops import billpallas
    from dgen_tpu.ops.sizing import size_agents
    from dgen_tpu.serve.engine import query_program
    from dgen_tpu.sweep.driver import sweep_year_step

    need = max(int(s[0]) * int(s[1]) for s in shapes)
    n_dev = len(jax.devices())
    if n_dev < need:
        raise ValueError(
            f"mesh audit needs {need} devices but the backend exposes "
            f"{n_dev} — run via `python -m dgen_tpu.lint --programs "
            "--mesh` (which forces the virtual CPU device count before "
            "jax initializes), or set it up-front with "
            "utils.compat.set_cpu_device_count"
        )

    ys_anchor = anchor_for(year_step)
    specs: List[ProgramSpec] = []
    for shape in shapes:
        lab = mesh_label(shape)
        n_agents = AUDIT_N_AGENTS    # pad multiple 32 divides the grid
        whole = partial(_mesh_model_bytes, tuple(shape), 0)
        chunked = partial(
            _mesh_model_bytes, tuple(shape), AUDIT_MESH_CHUNK
        )
        specs.append(ProgramSpec(
            entry="year_step", variant=lab,
            build=partial(_mesh_year_step_bound, tuple(shape), 1),
            steady=partial(_mesh_year_step_bound, tuple(shape), 2),
            anchor=ys_anchor, donate_args=(4,),
            mesh_shape=tuple(shape), global_n=n_agents,
            model_bytes=whole,
        ))
        specs.append(ProgramSpec(
            entry="year_step_chunked", variant=lab,
            build=partial(
                _mesh_year_step_bound, tuple(shape), 1, AUDIT_MESH_CHUNK
            ),
            anchor=ys_anchor, donate_args=(4,),
            mesh_shape=tuple(shape), global_n=n_agents,
            model_bytes=chunked,
        ))
        specs.append(ProgramSpec(
            entry="sweep_year_step", variant=lab,
            build=partial(_mesh_sweep_bound, tuple(shape), 1),
            anchor=anchor_for(sweep_year_step), donate_args=(4,),
            mesh_shape=tuple(shape), global_n=n_agents,
        ))
        specs.append(ProgramSpec(
            entry="sweep_loop", variant=lab,
            build=partial(_mesh_sweep_loop_bound, tuple(shape), 1),
            anchor=anchor_for(sweep_year_step), donate_args=(4,),
            mesh_shape=tuple(shape), global_n=n_agents,
            expect_same_as=f"year_step@{lab}",
        ))
        specs.append(ProgramSpec(
            entry="serve_query", variant=lab,
            build=partial(_mesh_serve_bound, tuple(shape), 0),
            anchor=anchor_for(query_program),
            mesh_shape=tuple(shape), global_n=n_agents,
        ))
        specs.append(ProgramSpec(
            entry="size_agents", variant=lab,
            build=partial(_mesh_size_agents_bound, tuple(shape)),
            anchor=anchor_for(size_agents),
            mesh_shape=tuple(shape), global_n=n_agents,
        ))
        specs.append(ProgramSpec(
            entry="import_sums", variant=lab,
            build=partial(_mesh_kernel_bound, tuple(shape), "import_sums"),
            anchor=anchor_for(billpallas.import_sums),
            mesh_shape=tuple(shape), global_n=8,
        ))
        specs.append(ProgramSpec(
            entry="bucket_sums", variant=lab,
            build=partial(_mesh_kernel_bound, tuple(shape), "bucket_sums"),
            anchor=anchor_for(billpallas.bucket_sums),
            mesh_shape=tuple(shape), global_n=8,
        ))
    return specs


def entry_names(grid: str = "default") -> List[str]:
    seen: List[str] = []
    for s in build_registry(grid):
        if s.entry not in seen:
            seen.append(s.entry)
    return seen


def select_entries(
    specs: List[ProgramSpec], entries: Optional[List[str]]
) -> List[ProgramSpec]:
    if not entries:
        return specs
    known = {s.entry for s in specs}
    unknown = [e for e in entries if e not in known]
    if unknown:
        raise ValueError(
            f"unknown program entries: {', '.join(unknown)} "
            f"(known: {', '.join(sorted(known))})"
        )
    chosen = [s for s in specs if s.entry in entries]
    # keep J5 cross-references resolvable — but a pulled-in spec the
    # user did not select is audited for fingerprint identity ONLY:
    # stripping its cost flag keeps it out of the J6 gate and out of
    # any --update-baselines merge (docs/lint.md: a subset audit gates
    # only the selected programs)
    ids = {s.spec_id for s in chosen}
    for s in specs:
        if any(
            c.expect_same_as == s.spec_id and s.spec_id not in ids
            for c in chosen
        ):
            chosen.append(dataclasses.replace(s, cost=False))
            ids.add(s.spec_id)
    return chosen
