"""Program-level rules J1-J6 (plus J0, the lower-failure backstop).

Where the L-rules pattern-match source, these inspect the artifact the
performance contract is actually about: the traced jaxpr and lowered
StableHLO of every registered entry point. Each rule is a generator
``rule(audit) -> message`` over one :class:`~dgen_tpu.lint.prog.spec.
ProgramAudit`; J5/J6 additionally see the whole audit set (compile-
group identity is a cross-program property, and the cost gate compares
against a committed baseline). Findings anchor at the entry point's
``def`` line, where the L-rule suppression mechanics
(``# dgenlint: disable=J2``) apply unchanged.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from dgen_tpu.lint.core import Finding, ModuleInfo, parse_file
from dgen_tpu.lint.prog_ids import PROGRAM_RULE_SUMMARIES
from dgen_tpu.lint.prog.spec import (
    ProgramAudit,
    donated_partition,
    eqn_avals,
    walk_jaxpr,
)

# J3: primitives that embed a host round-trip / callback in compiled
# code. ``device_put`` is NOT here — inside jit it is a placement
# annotation, not a transfer.
_J3_PRIMITIVES = {
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "infeed", "outfeed", "copy_to_host_async",
}

# J2: accumulation primitives whose OUTPUT must be f32 — the PR 2 bf16
# contract is "bf16 streams, f32 accumulate, bank-precision store",
# which lowers as f32-output reductions followed by an explicit
# convert; a reduction that OUTPUTS bf16/f16 accumulated at low
# precision.
_J2_ACCUM_PRIMITIVES = {
    "reduce_sum", "reduce_prod", "dot_general", "cumsum",
    "reduce_window_sum", "conv_general_dilated",
}

#: the GENERAL reduce/reduce_window primitives accumulate only when
#: their computation adds/multiplies (a bf16 max/min is lossless)
_J2_GENERAL_REDUCE = {"reduce", "reduce_window"}
_J2_ACCUM_OPS = {"add", "mul"}


def _accumulating_reduce(eqn) -> bool:
    from dgen_tpu.lint.prog.spec import _subjaxprs

    stack = []
    for p in eqn.params.values():
        stack.extend(_subjaxprs(p))
    while stack:
        j = stack.pop()
        for sub in j.eqns:
            if sub.primitive.name in _J2_ACCUM_OPS:
                return True
            for p in sub.params.values():
                stack.extend(_subjaxprs(p))
    return False

_WIDE_DTYPES = ("float64", "complex128")
_NARROW_ACCUM_DTYPES = ("bfloat16", "float16")


def rule_j1(audit: ProgramAudit) -> Iterable[str]:
    """Oversized constants captured into the program: each one is
    re-uploaded per executable, bloats HBM alongside the real banks,
    and (being baked into the computation) defeats the compile cache
    whenever its VALUE changes. Banks belong in traced arguments."""
    for shape, dtype, nbytes in audit.oversized_consts:
        yield (
            f"captured constant {dtype}{list(shape)} "
            f"({nbytes / 1024:.0f} KiB) exceeds the "
            f"{audit.spec.max_const_bytes // 1024} KiB audit ceiling — "
            "pass it as a traced argument instead of baking it into "
            "the program"
        )


def rule_j2(audit: ProgramAudit) -> Iterable[str]:
    """Dtype drift: f64 anywhere in the program (TPU-emulated, doubles
    HBM), and low-precision accumulation — reductions/contractions
    whose output aval is bf16/f16 (the bf16-banks contract accumulates
    in f32 and only STORES at bank precision)."""
    seen: set = set()
    for eqn in walk_jaxpr(audit.jaxpr):
        prim = eqn.primitive.name
        for aval in eqn_avals(eqn):
            dt = str(getattr(aval, "dtype", ""))
            if dt in _WIDE_DTYPES:
                key = ("wide", prim, dt)
                if key not in seen:
                    seen.add(key)
                    yield (
                        f"{dt} value flows through `{prim}` — f64 must "
                        "not reach the device path (L3's runtime twin)"
                    )
        if prim in _J2_ACCUM_PRIMITIVES or (
            prim in _J2_GENERAL_REDUCE and _accumulating_reduce(eqn)
        ):
            for v in eqn.outvars:
                dt = str(getattr(getattr(v, "aval", None), "dtype", ""))
                if dt in _NARROW_ACCUM_DTYPES:
                    key = ("accum", prim, dt)
                    if key not in seen:
                        seen.add(key)
                        yield (
                            f"`{prim}` accumulates at {dt}: the bf16-"
                            "banks contract is f32 accumulation with a "
                            "bank-precision STORE (accumulate f32, then "
                            "convert) — an 8760-term bf16 sum loses "
                            "~3 digits"
                        )


def rule_j3(audit: ProgramAudit) -> Iterable[str]:
    """Host callbacks / transfers inside compiled code: every one
    fences the device pipeline on a host round-trip per dispatch."""
    seen: set = set()
    for eqn in walk_jaxpr(audit.jaxpr):
        prim = eqn.primitive.name
        if prim in _J3_PRIMITIVES and prim not in seen:
            seen.add(prim)
            yield (
                f"`{prim}` embedded in the compiled program stalls "
                "every dispatch on a host callback — hoist it to the "
                "driver (or io.hostio) outside the jit boundary"
            )


def rule_j4(audit: ProgramAudit) -> Iterable[str]:
    """Donation verification: every leaf of a declared donated carry
    must actually be marked donated in the lowered program, and
    NOTHING else may be (donating the resident table/banks would let
    XLA reuse buffers that every later year still reads)."""
    if audit.args_info is None:
        return
    in_ok, in_bad, out_bad = donated_partition(audit)
    if audit.spec.donate_args and in_bad:
        yield (
            f"{in_bad} of {in_ok + in_bad} carry leaves are NOT "
            "donated — the cross-year carry must ride "
            "donate_argnames=('carry',) so XLA aliases the update in "
            "place (two live copies per in-flight year otherwise)"
        )
    if out_bad:
        yield (
            f"{out_bad} leaves OUTSIDE the declared carry are donated "
            "— donating resident table/bank buffers hands their HBM "
            "to XLA while later years still read them"
        )


def rule_j5(
    audit: ProgramAudit, by_id: Dict[str, ProgramAudit]
) -> Iterable[str]:
    """Compile-group fingerprinting: a steady-state probe (same entry,
    later year index) must lower to the IDENTICAL program — the static
    half of RetraceGuard's one-compile-per-group invariant — and
    entries declared program-sharing (loop-mode sweep vs year_step)
    must fingerprint-match, or every scenario pays a fresh compile."""
    if audit.steady_fingerprint is not None \
            and audit.steady_fingerprint != audit.fingerprint:
        yield (
            "steady-state probe lowers to a DIFFERENT program than the "
            "previous year's — something non-static (a shape, a baked "
            "value, a python branch on the year) varies per year, so "
            "every steady-state step would recompile (RetraceGuard "
            "would fail this run at year 3)"
        )
    ref_id = audit.spec.expect_same_as
    if ref_id is not None:
        ref = by_id.get(ref_id)
        if ref is None or ref.error:
            yield (
                f"cannot cross-check against '{ref_id}' (not audited "
                "or failed to lower)"
            )
        elif ref.fingerprint != audit.fingerprint:
            yield (
                f"program fingerprint differs from '{ref_id}' — these "
                "are declared to share ONE compiled executable (loop-"
                "mode sweeps reuse year_step's program; a kwargs drift "
                "between the sweep driver and Simulation.step_kwargs "
                "compiles one extra program PER SCENARIO)"
            )


#: J9: the per-device HBM budget the static memory gate defaults to
#: when the caller passes none (v5e/v6e-class; override with
#: ``--hbm-gb``)
J9_DEFAULT_BUDGET_BYTES = 16 * 1024**3
#: J9: how far the compiler's measured temp bytes may exceed the sweep
#: planner's ``_per_agent_step_bytes`` prediction before the model is
#: declared broken — mirrors Simulation._hbm_check's modeled-vs-actual
#: warning threshold (an under-counting model means auto_agent_chunk /
#: plan_sweep budget chunks that OOM at national scale)
J9_MODEL_SLACK = 3.0


def rule_j8(audit: ProgramAudit) -> Iterable[str]:
    """Sharding propagation: the agent axis must stay partitioned
    end-to-end. The compiled per-device module's shapes are per-shard,
    so any tensor materialized at the GLOBAL agent count was gathered
    or replicated — a silently all-gathered ``[N, 8760]`` stream turns
    the pod-scale table into per-device HBM copies — and an
    ``[N]``-leading output that comes back fully replicated lost its
    placement on the way out."""
    info = audit.mesh
    if info is None:
        return
    for tok, line, nbytes in info.replicated_global:
        yield (
            f"global-shaped tensor {tok} ({nbytes / 1024:.0f} KiB) "
            f"materialized UNSHARDED in the per-device program "
            f"(defining op: `{line.split('=')[0].strip()} = ...`) — an "
            "agent-axis array was gathered/replicated instead of "
            "staying partitioned (check with_sharding_constraint specs "
            "and parallel.mesh.agent_spec usage)"
        )
    for desc in info.outputs_unsharded:
        yield (
            f"[N]-leading output {desc} is fully REPLICATED in the "
            "compiled output shardings — agent-axis results must come "
            "back partitioned (a replicated output implies a gather "
            "every step and breaks multi-host addressability)"
        )


def rule_j9(
    audit: ProgramAudit, budget_bytes: Optional[int] = None
) -> Iterable[str]:
    """Static per-device memory gate: argument + temp + output bytes of
    the compiled per-device program against the HBM budget, plus the
    planner cross-check — the compiler's own temp measurement validates
    ``_per_agent_step_bytes`` (the model auto_agent_chunk and
    plan_sweep budget with) BEFORE a pod run is launched."""
    info = audit.mesh
    if info is None:
        return
    budget = budget_bytes or J9_DEFAULT_BUDGET_BYTES
    peak = info.peak_bytes
    if peak is not None and peak > budget:
        mem = info.memory
        bound_note = (
            "; a LOWER BOUND — the backend exposes no memory_analysis, "
            "so this is the aval x sharding estimate without temps"
            if info.peak_is_lower_bound else ""
        )
        yield (
            f"per-device memory {peak / 2**20:.1f} MiB (arg "
            f"{(mem.get('argument') or 0) / 2**20:.1f} + temp "
            f"{(mem.get('temp') or 0) / 2**20:.1f} + out "
            f"{(mem.get('output') or 0) / 2**20:.1f}) exceeds the "
            f"{budget / 2**30:.1f} GiB HBM budget{bound_note} — shrink "
            "agent_chunk / shard wider before launching this on "
            "hardware"
        )
    temp = info.memory.get("temp")
    if (
        info.model_bytes and temp
        and temp > info.model_bytes * J9_MODEL_SLACK
    ):
        yield (
            f"compiled temp bytes {temp} are "
            f"{temp / info.model_bytes:.1f}x the sweep planner's "
            f"_per_agent_step_bytes prediction ({info.model_bytes}) — "
            "the HBM footprint model under-counts this configuration, "
            "so auto_agent_chunk/plan_sweep would budget chunks that "
            "OOM at national scale (update the model's envelope "
            "constants in models/simulation.py)"
        )


# J11: primitives whose VJP/JVP rule is zero (or undefined) almost
# everywhere — inside a differentiated program each one silently
# zeroes every upstream parameter's gradient. ``convert_element_type``
# is flagged separately (only float -> int truncation kills gradients;
# widening/narrowing float casts are fine).
_J11_KILLERS = {
    "round", "floor", "ceil", "nearbyint",
    "argmax", "argmin", "stop_gradient",
}
#: custom-AD wrappers are the SANCTIONED escape hatch: a kink wrapped
#: in custom_jvp/custom_vjp declared its derivative explicitly (the
#: straight-through sites in dgen_tpu.grad.smooth), so J11 neither
#: descends into their rule bodies nor flags casts of their outputs
_J11_CUSTOM_AD = ("custom_jvp_call", "custom_vjp_call")


def _is_float_to_int(eqn) -> bool:
    import numpy as np

    if eqn.primitive.name != "convert_element_type":
        return False
    try:
        src = np.dtype(eqn.invars[0].aval.dtype)
        dst = np.dtype(eqn.outvars[0].aval.dtype)
    except (AttributeError, TypeError):
        return False
    return src.kind == "f" and dst.kind in ("i", "u")


def rule_j11(audit: ProgramAudit) -> Iterable[str]:
    """Gradient-killing ops reachable inside a grad-marked entry.

    A grad-marked spec's bound IS the differentiated program (a
    ``value_and_grad`` or jvp-of-grad wrapper), so every primitive in
    its jaxpr participates in differentiation: a ``round``/``floor``/
    ``argmax``/``stop_gradient`` or a float->int cast there has a
    zero-a.e. derivative and silently disconnects every parameter
    upstream of it — the smooth-twin bug class where a loss LOOKS
    differentiable but one table lookup zeroes the fit.

    Custom-AD call bodies are exempt (their derivative is declared, not
    derived — the deliberate straight-through sites in
    :mod:`dgen_tpu.grad.smooth`), as are float->int casts of a
    custom-AD output (the tangent was already explicitly routed).
    Remaining deliberate sites — e.g. a forward-hard argmax winner
    selection whose gradient flows through the gathered winner — carry
    ``# dgenlint: disable=J11`` at the entry anchor with a comment
    saying why.
    """
    if not audit.spec.grad:
        return
    seen: set = set()
    stack = [(audit.jaxpr.jaxpr, frozenset())]
    visited: set = set()
    while stack:
        j, sanctioned = stack.pop()
        if id(j) in visited:
            continue
        visited.add(id(j))
        local = set(sanctioned)
        for eqn in j.eqns:
            prim = eqn.primitive.name
            if any(prim.startswith(c) for c in _J11_CUSTOM_AD):
                local.update(map(id, eqn.outvars))
                continue
            if prim in _J11_KILLERS and prim not in seen:
                seen.add(prim)
                yield (
                    f"`{prim}` reachable inside this differentiated "
                    "program: its derivative is zero almost everywhere, "
                    "so every parameter upstream of it silently stops "
                    "receiving gradient — smooth it (dgen_tpu.grad."
                    "smooth), wrap it in a custom_jvp declaring the "
                    "intended derivative, or suppress here if the "
                    "straight-through behavior is deliberate"
                )
            if (
                _is_float_to_int(eqn)
                and id(eqn.invars[0]) not in local
                and "convert_f2i" not in seen
            ):
                seen.add("convert_f2i")
                yield (
                    "float->int `convert_element_type` reachable inside "
                    "this differentiated program truncates with a zero "
                    "derivative — if this is a deliberate index "
                    "computation (a lerp_lookup-style gather), route it "
                    "through a custom_jvp so the zero tangent is "
                    "declared, or suppress here"
                )
            for p in eqn.params.values():
                for sub in _subjaxprs_j11(p):
                    stack.append((sub, frozenset(local)))


def _subjaxprs_j11(p) -> List:
    from dgen_tpu.lint.prog.spec import _subjaxprs

    return _subjaxprs(p)


#: rule id -> (summary, per-audit impl); J5 takes the cross-audit map,
#: J9 takes the budget, J6/J7/J10 live in dgen_tpu.lint.prog.baseline
#: (they need the baseline file). Summaries come from the jax-free id
#: table (dgen_tpu.lint.prog_ids) so `--list-rules` needn't import jax.
_IMPLS = {
    "J0": None, "J1": rule_j1, "J2": rule_j2, "J3": rule_j3,
    "J4": rule_j4, "J5": rule_j5, "J6": None, "J7": None,
    "J8": rule_j8, "J9": rule_j9, "J10": None, "J11": rule_j11,
}
PROGRAM_RULES: Dict[str, Tuple[str, object]] = {
    rule_id: (summary, _IMPLS[rule_id])
    for rule_id, summary in PROGRAM_RULE_SUMMARIES.items()
}


def _suppressed(
    cache: Dict[str, Optional[ModuleInfo]], rule: str,
    path: str, line: int,
) -> bool:
    if path not in cache:
        try:
            cache[path] = parse_file(path)
        except (OSError, SyntaxError, ValueError):
            cache[path] = None
    m = cache[path]
    return m.is_suppressed(rule, line) if m is not None else False


def run_program_rules(
    audits: List[ProgramAudit],
    select: Optional[Iterable[str]] = None,
    j9_budget_bytes: Optional[int] = None,
) -> List[Finding]:
    """J0-J5, J11 + the per-audit mesh rules J8/J9 over a set of audits
    (J6/J7/J10 are applied by the baseline module, which owns the
    comparisons): suppression comments at each entry's anchor line are
    honored, L-rule style. Findings are prefixed with the
    ``entry@variant`` they were observed in. ``j9_budget_bytes``: the
    per-device HBM budget the J9 gate uses (default
    :data:`J9_DEFAULT_BUDGET_BYTES`)."""
    chosen = set(select) if select is not None else set(PROGRAM_RULES)
    unknown = chosen - set(PROGRAM_RULES)
    if unknown:
        raise ValueError(
            f"unknown program rule id(s): {', '.join(sorted(unknown))}"
        )
    by_id = {a.spec.spec_id: a for a in audits}
    mod_cache: Dict[str, Optional[ModuleInfo]] = {}
    findings: List[Finding] = []

    def emit(rule: str, audit: ProgramAudit, msg: str) -> None:
        path, line = audit.spec.anchor
        if not _suppressed(mod_cache, rule, path, line):
            findings.append(Finding(
                rule, path, line, f"[{audit.spec.spec_id}] {msg}"
            ))

    for audit in audits:
        if audit.error:
            if "J0" in chosen:
                emit("J0", audit, (
                    f"failed to trace/lower: {audit.error} — the entry "
                    "point or its abstract-spec builder is broken"
                ))
            continue
        for rule in ("J1", "J2", "J3", "J4", "J8", "J11"):
            if rule not in chosen:
                continue
            _summary, impl = PROGRAM_RULES[rule]
            for msg in impl(audit):
                emit(rule, audit, msg)
        if "J5" in chosen:
            for msg in rule_j5(audit, by_id):
                emit("J5", audit, msg)
        if "J9" in chosen:
            for msg in rule_j9(audit, budget_bytes=j9_budget_bytes):
                emit("J9", audit, msg)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings
