"""dgenlint-prog: the jaxpr/HLO-level program auditor.

The AST rules (L1-L11) and the runtime RetraceGuard bracket the repo's
performance contract from source and from execution; this package
checks the artifact in between — the *compiled program* — on a
CPU-only CI runner, no devices, no data:

    JAX_PLATFORMS=cpu python -m dgen_tpu.lint --programs

Every jitted entry point (year_step, the chunked scan variant,
sweep_year_step, the serve query program, size_agents, the bill
kernels) is abstract-interpreted over the supported static-config grid
(daylight_compact x bf16_banks x net_billing x sweep vmap/loop) via
``jax.jit(...).trace(...).lower()`` on a tiny synthetic world, and the
J-rules run over the resulting jaxprs/StableHLO:

  J1  oversized constants captured into the program
  J2  dtype drift (f64 anywhere; bf16/f16 accumulation)
  J3  host callbacks/transfers inside compiled code
  J4  donation verification (declared carries actually donated)
  J5  compile-group fingerprints (steady-state years must share ONE
      program; loop-mode sweeps must reuse year_step's)
  J6  cost fingerprints (compiled flops/bytes vs a committed baseline
      with a tolerance gate — a perf-regression gate with zero timing
      noise)

``--mesh`` adds the MULTI-DEVICE tier (``dgen_tpu.lint.prog.
meshaudit``): every entry is additionally lowered under forced
multi-device CPU meshes — the 1-D 1x8 agent mesh and the 2-D 2x4
hosts x devices grid — with the production shardings applied
(``parallel.mesh.agent_spec`` via the real ``Simulation.__init__``
placement), compiled (still CPU, still no execution), and gated:

  J7  collective fingerprints (all-reduce/all-gather/... counts +
      estimated comm bytes vs the committed baseline; a new all-gather
      on the hot path fails with the op and operand shape named)
  J8  sharding propagation (agent-axis arrays must stay partitioned:
      global-shaped tensors inside the per-device program and
      replicated [N]-leading outputs are flagged)
  J9  static per-device memory (compiled.memory_analysis vs the HBM
      budget, cross-checked against the sweep planner's
      _per_agent_step_bytes model)
  J10 per-mesh-shape program hashes (topology-sensitive changes land
      as reviewable baseline diffs)

Unlike the static L-half, this package imports jax (it must trace);
``dgen_tpu.lint`` itself stays import-light and pulls it lazily.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from dgen_tpu.lint.core import Finding
from dgen_tpu.lint.prog import baseline as baseline_mod
from dgen_tpu.lint.prog.jrules import PROGRAM_RULES, run_program_rules
from dgen_tpu.lint.prog.registry import (
    MESH_GRID_DEFAULT,
    MESH_GRID_FAST,
    build_mesh_registry,
    build_registry,
    entry_names,
    mesh_label,
    select_entries,
)
from dgen_tpu.lint.prog.spec import (  # noqa: F401  (public API)
    AUDIT_SPEC_VERSION,
    Bound,
    ProgramAudit,
    ProgramSpec,
    anchor_for,
    lower_spec,
)

__all__ = [
    "MESH_GRID_DEFAULT", "MESH_GRID_FAST", "PROGRAM_RULES",
    "ProgramAudit", "ProgramSpec", "Bound", "audit_programs",
    "build_mesh_registry", "build_registry", "entry_names",
    "explain_entry", "lower_spec", "mesh_label", "run_program_rules",
]

#: rules applied by the baseline module, not run_program_rules
_BASELINE_RULES = ("J6", "J7", "J10")


def audit_programs(
    entries: Optional[List[str]] = None,
    grid: str = "default",
    select: Optional[List[str]] = None,
    baseline_path: Optional[str] = None,
    update_baselines: bool = False,
    with_cost: bool = True,
    tolerance: Optional[float] = None,
    mesh: bool = False,
    mesh_shapes: Optional[List[tuple]] = None,
    hbm_budget_gb: Optional[float] = None,
) -> Tuple[List[Finding], dict]:
    """Audit the entry-point registry; returns (findings, report).

    ``entries``: subset of registry entry names (default: all).
    ``grid="fast"``: base grid points only (test tier).
    ``select``: subset of J-rule ids. ``with_cost=False`` skips the
    compile step entirely (J6 reports nothing). ``mesh``: additionally
    lower every entry under the forced multi-device CPU mesh grid
    (``mesh_shapes`` or the registry default) with production shardings
    applied and enforce J7-J10 (``hbm_budget_gb`` feeds the J9 gate).
    The report carries the per-spec fingerprints, predicted
    compile-group counts, the J6 (and mesh-tier J7) status and — with
    ``update_baselines`` — the freshly written baseline document.
    """
    from dgen_tpu.utils import compilecache

    compilecache.enable()   # amortize the J6 compiles across runs
    specs = select_entries(build_registry(grid), entries)
    run_j6 = with_cost and (select is None or "J6" in select)
    if update_baselines and not run_j6:
        # an explicitly requested baseline write must never be a
        # silent no-op (the operator would commit a stale gate)
        raise ValueError(
            "--update-baselines requires the J6 rule: drop --select, "
            "include J6 in it, and keep cost analysis enabled"
        )
    mesh_only = {"J7", "J8", "J9", "J10"} & set(select or ())
    if mesh_only and not mesh:
        # an explicitly selected mesh rule must never be a silent
        # no-op (the operator would believe the sharding was audited)
        raise ValueError(
            f"--select {','.join(sorted(mesh_only))} requires --mesh "
            "(the mesh tier is what those rules run over)"
        )
    mesh_specs: List[ProgramSpec] = []
    if mesh:
        shapes = [tuple(s) for s in mesh_shapes] if mesh_shapes else None
        mesh_specs = build_mesh_registry(shapes, grid=grid)
        if entries:
            # subset by entry name (an entry with no mesh variant —
            # e.g. import_sums_pair — simply contributes nothing here),
            # keeping J5 cross-references resolvable, identity-only
            import dataclasses as _dc

            chosen = [s for s in mesh_specs if s.entry in entries]
            ids = {s.spec_id for s in chosen}
            for s in mesh_specs:
                if any(
                    c.expect_same_as == s.spec_id
                    and s.spec_id not in ids
                    for c in chosen
                ):
                    # fingerprint-identity only: no mesh analysis, no
                    # gate, no baseline merge for a pulled-in spec
                    chosen.append(_dc.replace(
                        s, expect_same_as=None, mesh_shape=None,
                    ))
                    ids.add(s.spec_id)
            mesh_specs = chosen
    audits = [lower_spec(s, with_cost=run_j6) for s in specs]
    mesh_audits = [lower_spec(s) for s in mesh_specs]
    budget_bytes = (
        int(hbm_budget_gb * 1024**3) if hbm_budget_gb else None
    )
    findings = run_program_rules(
        audits + mesh_audits,
        select=None if select is None
        else [r for r in select if r not in _BASELINE_RULES],
        j9_budget_bytes=budget_bytes,
    )

    report: dict = {
        "grid": grid,
        "n_programs": len(audits) + len(mesh_audits),
        "entries": {},
        "j6": None,
        "mesh": None,
        "j7": None,
    }
    if mesh:
        report["mesh"] = {
            a.spec.spec_id: {
                "shape": list(a.mesh.shape),
                "collectives": a.mesh.counts,
                "comm_bytes": a.mesh.comm_bytes,
                "peak_bytes": a.mesh.peak_bytes,
                "model_bytes": a.mesh.model_bytes,
            }
            for a in mesh_audits if a.mesh is not None
        }
    by_entry: dict = {}
    for a in audits + mesh_audits:
        e = by_entry.setdefault(
            a.spec.entry, {"variants": 0, "programs": set(), "failed": 0}
        )
        e["variants"] += 1
        if a.error:
            e["failed"] += 1
        else:
            e["programs"].add(a.fingerprint)
    for name, e in by_entry.items():
        report["entries"][name] = {
            "variants": e["variants"],
            # the statically predicted compile count for this entry
            # across the audited grid (RetraceGuard's one-compile-per-
            # group invariant, measured before any hardware run)
            "predicted_compile_groups": len(e["programs"]),
            "failed": e["failed"],
        }

    path = baseline_path or baseline_mod.default_baseline_path()
    # an --entries subset must neither report the deselected
    # programs as stale nor delete them from the committed file
    partial = bool(entries)
    # the mesh stale sweep additionally requires the DEFAULT shape
    # grid: a fast-tier or custom-shape run produces a subset of the
    # committed mesh keys, which is not staleness
    mesh_partial = (
        partial or mesh_shapes is not None or grid != "default"
    )
    run_mesh_gate = mesh and (
        select is None or bool({"J7", "J10"} & set(select))
    )
    # ONE read of the committed baseline for both gates; an unreadable
    # file must name itself and the repair command, not die as a bare
    # JSON parse error deep in the gate
    baseline_doc = None
    if (run_j6 or run_mesh_gate) and not update_baselines:
        try:
            baseline_doc = baseline_mod.load_baseline(path)
        except (OSError, ValueError) as e:
            raise ValueError(
                f"unreadable baseline {path} ({e}) — re-seed it with "
                "`python -m dgen_tpu.lint --programs --mesh "
                "--update-baselines`"
            ) from e
    # update_baselines implies run_j6 (enforced above), so this branch
    # also covers every baseline write
    if run_j6:
        if update_baselines:
            doc = baseline_mod.update_baseline(
                path, audits,
                tolerance=(
                    tolerance if tolerance is not None
                    else baseline_mod.DEFAULT_TOLERANCE
                ),
                partial=partial,
                mesh_audits=mesh_audits if mesh else None,
                mesh_partial=mesh_partial,
            )
            report["j6"] = {
                "updated": path,
                "entries": sorted(doc["entries"]),
                "fingerprints": doc["entries"],
                "mesh_entries": sorted(doc.get("mesh", {})),
                "note": None,
            }
        else:
            j6_findings, status = baseline_mod.compare_to_baseline(
                audits, baseline_doc,
                tolerance=tolerance, partial=partial,
            )
            findings.extend(j6_findings)
            report["j6"] = status
    if run_mesh_gate and not update_baselines:
        j7_findings, j7_status = baseline_mod.compare_mesh_to_baseline(
            mesh_audits, baseline_doc,
            tolerance=tolerance, partial=mesh_partial,
        )
        if select is not None:
            j7_findings = [
                f for f in j7_findings if f.rule in select
            ]
        findings.extend(j7_findings)
        report["j7"] = j7_status
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings, report


def _clip(text: str, n: int) -> str:
    lines = text.splitlines()
    if len(lines) <= n:
        return text
    return "\n".join(lines[:n]) + f"\n... ({len(lines) - n} more lines)"


def explain_entry(
    name: str,
    mesh: bool = False,
    mesh_shapes: Optional[List[tuple]] = None,
    max_lines: int = 80,
) -> str:
    """The ``--explain`` dump for one registry entry: its jaxpr, a
    sharded-StableHLO excerpt, the collective table and the per-device
    memory estimate — the debugging view for a J6/J7/J10 baseline diff
    (``name`` is an entry name or a full ``entry@variant`` spec id).
    """
    specs = list(build_registry("fast"))
    if "@" in name:
        # a full spec id may name a default-grid variant (the id a
        # J-finding prints); pull the full grid in so copying an id
        # out of a finding always resolves
        have = {s.spec_id for s in specs}
        specs += [
            s for s in build_registry("default")
            if s.spec_id not in have
        ]
    if mesh:
        shapes = [tuple(s) for s in mesh_shapes] if mesh_shapes else None
        specs += build_mesh_registry(shapes)
    if "@" in name:
        chosen = [s for s in specs if s.spec_id == name]
    else:
        chosen = [s for s in specs if s.entry == name]
    if not chosen:
        known = sorted({s.entry for s in specs})
        raise ValueError(
            f"unknown entry '{name}' (known: {', '.join(known)}; "
            "add --mesh for the meshNxM variants)"
        )
    out: List[str] = []
    for spec in chosen:
        audit = lower_spec(spec, with_cost=spec.cost, keep_text=True)
        out.append(f"===== {spec.spec_id} =====")
        if audit.error:
            out.append(f"FAILED TO LOWER: {audit.error}")
            continue
        out.append(f"program fingerprint: {audit.fingerprint}")
        if audit.steady_fingerprint is not None:
            same = audit.steady_fingerprint == audit.fingerprint
            out.append(
                "steady-state probe: "
                + ("identical program" if same
                   else f"DIFFERENT program ({audit.steady_fingerprint})")
            )
        out.append(f"captured constants: {audit.const_bytes} bytes")
        if audit.cost_analysis:
            ca = audit.cost_analysis
            out.append(
                f"cost: flops={ca['flops']:.6g} "
                f"bytes_accessed={ca['bytes_accessed']:.6g}"
            )
        if audit.mesh is not None:
            from dgen_tpu.lint.prog.meshaudit import collective_table

            info = audit.mesh
            out.append(
                f"mesh {info.shape[0]}x{info.shape[1]} "
                f"({info.n_devices} devices, global N={info.global_n})"
            )
            out.append("collectives:")
            out.extend("  " + ln for ln in collective_table(info))
            mem = info.memory
            out.append(
                "per-device memory: "
                f"arg={mem.get('argument')} temp={mem.get('temp')} "
                f"out={mem.get('output')} (peak~{info.peak_bytes} B"
                + (f", planner model {info.model_bytes} B"
                   if info.model_bytes else "")
                + (", aval estimate" if mem.get("estimated") else "")
                + ")"
            )
            if info.replicated_global:
                out.append("global-shaped per-device tensors (J8):")
                out.extend(
                    f"  {tok} ({nb} B): {line}"
                    for tok, line, nb in info.replicated_global
                )
        out.append("--- jaxpr ---")
        out.append(_clip(str(audit.jaxpr), max_lines))
        out.append("--- StableHLO (sharded) ---")
        out.append(_clip(audit.hlo_text or "", max_lines))
    return "\n".join(out)
