"""dgenlint-prog: the jaxpr/HLO-level program auditor.

The AST rules (L1-L11) and the runtime RetraceGuard bracket the repo's
performance contract from source and from execution; this package
checks the artifact in between — the *compiled program* — on a
CPU-only CI runner, no devices, no data:

    JAX_PLATFORMS=cpu python -m dgen_tpu.lint --programs

Every jitted entry point (year_step, the chunked scan variant,
sweep_year_step, the serve query program, size_agents, the bill
kernels) is abstract-interpreted over the supported static-config grid
(daylight_compact x bf16_banks x net_billing x sweep vmap/loop) via
``jax.jit(...).trace(...).lower()`` on a tiny synthetic world, and the
J-rules run over the resulting jaxprs/StableHLO:

  J1  oversized constants captured into the program
  J2  dtype drift (f64 anywhere; bf16/f16 accumulation)
  J3  host callbacks/transfers inside compiled code
  J4  donation verification (declared carries actually donated)
  J5  compile-group fingerprints (steady-state years must share ONE
      program; loop-mode sweeps must reuse year_step's)
  J6  cost fingerprints (compiled flops/bytes vs a committed baseline
      with a tolerance gate — a perf-regression gate with zero timing
      noise)

Unlike the static L-half, this package imports jax (it must trace);
``dgen_tpu.lint`` itself stays import-light and pulls it lazily.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from dgen_tpu.lint.core import Finding
from dgen_tpu.lint.prog import baseline as baseline_mod
from dgen_tpu.lint.prog.jrules import PROGRAM_RULES, run_program_rules
from dgen_tpu.lint.prog.registry import (
    build_registry,
    entry_names,
    select_entries,
)
from dgen_tpu.lint.prog.spec import (  # noqa: F401  (public API)
    AUDIT_SPEC_VERSION,
    Bound,
    ProgramAudit,
    ProgramSpec,
    anchor_for,
    lower_spec,
)

__all__ = [
    "PROGRAM_RULES", "ProgramAudit", "ProgramSpec", "Bound",
    "audit_programs", "build_registry", "entry_names", "lower_spec",
    "run_program_rules",
]


def audit_programs(
    entries: Optional[List[str]] = None,
    grid: str = "default",
    select: Optional[List[str]] = None,
    baseline_path: Optional[str] = None,
    update_baselines: bool = False,
    with_cost: bool = True,
    tolerance: Optional[float] = None,
) -> Tuple[List[Finding], dict]:
    """Audit the entry-point registry; returns (findings, report).

    ``entries``: subset of registry entry names (default: all).
    ``grid="fast"``: base grid points only (test tier).
    ``select``: subset of J-rule ids. ``with_cost=False`` skips the
    compile step entirely (J6 reports nothing). The report carries the
    per-spec fingerprints, predicted compile-group counts, the J6
    status and — with ``update_baselines`` — the freshly written
    baseline document.
    """
    from dgen_tpu.utils import compilecache

    compilecache.enable()   # amortize the J6 compiles across runs
    specs = select_entries(build_registry(grid), entries)
    run_j6 = with_cost and (select is None or "J6" in select)
    if update_baselines and not run_j6:
        # an explicitly requested baseline write must never be a
        # silent no-op (the operator would commit a stale gate)
        raise ValueError(
            "--update-baselines requires the J6 rule: drop --select, "
            "include J6 in it, and keep cost analysis enabled"
        )
    audits = [lower_spec(s, with_cost=run_j6) for s in specs]
    findings = run_program_rules(
        audits,
        select=None if select is None
        else [r for r in select if r != "J6"],
    )

    report: dict = {
        "grid": grid,
        "n_programs": len(audits),
        "entries": {},
        "j6": None,
    }
    by_entry: dict = {}
    for a in audits:
        e = by_entry.setdefault(
            a.spec.entry, {"variants": 0, "programs": set(), "failed": 0}
        )
        e["variants"] += 1
        if a.error:
            e["failed"] += 1
        else:
            e["programs"].add(a.fingerprint)
    for name, e in by_entry.items():
        report["entries"][name] = {
            "variants": e["variants"],
            # the statically predicted compile count for this entry
            # across the audited grid (RetraceGuard's one-compile-per-
            # group invariant, measured before any hardware run)
            "predicted_compile_groups": len(e["programs"]),
            "failed": e["failed"],
        }

    if run_j6:
        path = baseline_path or baseline_mod.default_baseline_path()
        # an --entries subset must neither report the deselected
        # programs as stale nor delete them from the committed file
        partial = bool(entries)
        if update_baselines:
            doc = baseline_mod.update_baseline(
                path, audits,
                tolerance=(
                    tolerance if tolerance is not None
                    else baseline_mod.DEFAULT_TOLERANCE
                ),
                partial=partial,
            )
            report["j6"] = {
                "updated": path,
                "entries": sorted(doc["entries"]),
                "fingerprints": doc["entries"],
                "note": None,
            }
        else:
            j6_findings, status = baseline_mod.compare_to_baseline(
                audits, baseline_mod.load_baseline(path),
                tolerance=tolerance, partial=partial,
            )
            findings.extend(j6_findings)
            report["j6"] = status
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings, report
