"""Runtime retrace / transfer guard — the dynamic half of dgenlint.

The whole performance design of dgen-tpu is ONE compiled XLA program
per model year: a steady-state year that triggers a fresh compile means
a static argument is churning (a python float sneaking into
``static_argnames``, a shape changing with data, a host branch on a
traced value) and the 10-minute national-run budget is silently gone —
80-170 s per recompile on the TPU backend. The linter's static rules
catch the code shapes that cause this; :class:`RetraceGuard` catches
the fact itself, cheaply enough to stay on in tests.

Counting uses ``jax.monitoring`` duration events:

  * ``.../backend_compile_duration`` — one per fresh XLA compilation
    (persistent-cache hits do NOT fire it);
  * ``.../jaxpr_trace_duration``    — one per jaxpr trace (fires even
    when the persistent cache then serves the executable, so it also
    catches retrace storms hidden by a warm on-disk cache).

A steady-state simulation year must produce ZERO of both. Device-to-
host transfer policing rides along via ``jax.transfer_guard`` when
requested (effective on accelerator backends; the CPU test platform
does not model host transfers).

Usage::

    with RetraceGuard(context="year 2040") as g:
        carry, outs = sim.step(carry, yi, first_year=False)
    # raises RetraceError on exit if anything compiled

or imperative (the Simulation.run wiring)::

    g = RetraceGuard().start()
    ...per year: g.check(f"year {year}")...
    g.stop()
"""

from __future__ import annotations

import contextlib
from typing import Optional

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"


class RetraceError(AssertionError):
    """A guarded region compiled or traced when it must not have."""


class RetraceGuard:
    """Counts fresh XLA compiles / jaxpr traces while active and fails
    when a guarded region exceeds its budget (default: zero of both).

    Parameters
    ----------
    max_compiles : compile budget inside the guarded region (0 = any
        fresh XLA compilation fails).
    max_traces : trace budget; None disables trace enforcement (traces
        are still counted and reported).
    d2h : optional ``jax.transfer_guard_device_to_host`` level to apply
        while active (e.g. ``"disallow"`` or ``"log"``); None leaves
        the transfer policy untouched.
    context : label prefixed to failure messages.
    """

    def __init__(
        self,
        *,
        max_compiles: int = 0,
        max_traces: Optional[int] = 0,
        d2h: Optional[str] = None,
        context: str = "",
    ) -> None:
        self.max_compiles = max_compiles
        self.max_traces = max_traces
        self.d2h = d2h
        self.context = context
        self.n_compiles = 0
        self.n_traces = 0
        self._active = False
        self._stack: Optional[contextlib.ExitStack] = None

    # -- counting -------------------------------------------------------
    def _on_duration(self, event: str, duration, **kwargs) -> None:
        if not self._active:
            return
        if event == _COMPILE_EVENT:
            self.n_compiles += 1
        elif event == _TRACE_EVENT:
            self.n_traces += 1

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "RetraceGuard":
        if self._active:
            return self
        import jax
        from jax._src import monitoring

        monitoring.register_event_duration_secs_listener(self._on_duration)
        self._active = True
        self._stack = contextlib.ExitStack()
        if self.d2h is not None:
            self._stack.enter_context(
                jax.transfer_guard_device_to_host(self.d2h)
            )
        return self

    def stop(self) -> None:
        """Stop counting without checking (failure paths)."""
        if not self._active:
            return
        self._active = False
        if self._stack is not None:
            self._stack.close()
            self._stack = None
        from jax._src import monitoring

        try:
            monitoring._unregister_event_duration_listener_by_callback(
                self._on_duration
            )
        except (AttributeError, ValueError):  # pragma: no cover
            pass  # listener stays registered but self._active gates it

    def reset(self) -> None:
        self.n_compiles = 0
        self.n_traces = 0

    # -- enforcement ----------------------------------------------------
    def check(self, context: str = "") -> None:
        """Raise :class:`RetraceError` if the budget is exceeded; on
        success resets the counters so per-year checks compose."""
        label = ": ".join(x for x in (self.context, context) if x)
        if self.n_compiles > self.max_compiles:
            n = self.n_compiles
            self.stop()
            raise RetraceError(
                f"{label}: {n} fresh XLA compilation(s) in a guarded "
                f"steady-state region (budget {self.max_compiles}) — a "
                "static argument or shape is churning per step; rerun "
                "with JAX_LOG_COMPILES=1 to see which program"
            )
        if self.max_traces is not None and self.n_traces > self.max_traces:
            n = self.n_traces
            self.stop()
            raise RetraceError(
                f"{label}: {n} fresh jaxpr trace(s) in a guarded "
                f"steady-state region (budget {self.max_traces}) — the "
                "jit cache is missing (possibly masked by the persistent "
                "compile cache); rerun with JAX_LOG_COMPILES=1"
            )
        self.reset()

    # -- context manager -------------------------------------------------
    def __enter__(self) -> "RetraceGuard":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            try:
                self.check()
            finally:
                self.stop()
        else:
            self.stop()
