"""Gradient-based NPV-optimal sizing: damped Newton on the smooth twin.

The grid search (:func:`dgen_tpu.ops.sizing._size_agents_fast`) prices
two refine rounds of 16 candidates each — 2 import-sums kernel calls
with R = 16*Y packed scale rows. A Newton step on the differentiable
objective (:func:`dgen_tpu.ops.sizing.make_npv_objective`) costs ONE
kernel evaluation with R = Y rows per agent: ``value_and_grad`` shares
the forward pass with the VJP, and the curvature comes from a
forward-over-reverse JVP through the same program. A handful of steps
lands inside the reference bracket tolerance ``xatol = max(2 kW,
1e-3 * width)`` (reference financial_functions.py:444) wherever the
smooth surface is locally concave; agents whose curvature is degenerate
(flat NPV, bracket-edge optima, switch-window cliffs) are detected and
fall back to the coarse-grid winner, so the result NEVER leaves the
reference bracket.

The objective is separable per agent, so the [N]-batched Hessian is
diagonal and one JVP of the gradient with an all-ones tangent extracts
it exactly — no [N, N] materialization, no vmapped per-agent Hessians.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

from dgen_tpu.ops import sizing as sizing_ops

#: default smoothing temperature for the sizing objective (kW at the
#: hourly splits; see docs/grad.md for the unit discussion)
DEFAULT_TAU = 0.1
#: Newton iterations; phi^-14-equivalent accuracy needs far fewer
#: because the step is second-order
DEFAULT_STEPS = 8
#: coarse-grid columns used for the init (and the fallback answer)
DEFAULT_INIT_K = 6
#: curvature threshold: |h| below this (in $/kW^2) is treated as
#: degenerate and the agent keeps its grid fallback
CURV_EPS = 1e-4


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class NewtonSizeResult:
    """Per-agent outcome of :func:`newton_size`."""

    system_kw: jax.Array     #: [N] final (bracket-projected) size
    npv: jax.Array           #: [N] smooth-objective NPV at system_kw
    grad: jax.Array          #: [N] dNPV/dkw at system_kw
    hess: jax.Array          #: [N] diagonal d2NPV/dkw2 at system_kw
    fallback: jax.Array      #: [N] bool — True where the grid answer won
    lo: jax.Array            #: [N] sizing bracket (reference semantics)
    hi: jax.Array


def grad_and_diag_hess(
    f: Callable[[jax.Array], jax.Array], kw: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """(value [N], grad [N], diag-Hessian [N]) of a separable batched
    objective ``f: [N] -> [N]`` at ``kw``.

    ``sum(f)`` decouples over agents, so ``grad(sum(f))`` is the
    per-agent derivative and ONE forward-over-reverse JVP with an
    all-ones tangent reads off the Hessian diagonal (the off-diagonal
    blocks are identically zero, so the contraction loses nothing).
    """
    val = f(kw)
    g_fn = jax.grad(lambda x: jnp.sum(f(x)))
    g, h = jax.jvp(g_fn, (kw,), (jnp.ones_like(kw),))
    return val, g, h


def newton_refine(
    f: Callable[[jax.Array], jax.Array],
    kw0: jax.Array,
    lo: jax.Array,
    hi: jax.Array,
    n_steps: int = DEFAULT_STEPS,
    damping: float = 1.0,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Bracket-projected damped Newton ascent from ``kw0``.

    Where the surface is locally concave (``h < -CURV_EPS``) the step is
    ``-damping * g / h``; elsewhere a conservative sign-following step
    of 5% of the bracket keeps the iterate moving uphill instead of
    jumping toward a maximum of the convex fit. Every iterate projects
    back into [lo, hi]. Returns ``(kw, g, h)`` at the final iterate.
    """
    width = hi - lo

    def body(_, kw):
        _, g, h = grad_and_diag_hess(f, kw)
        newton = -damping * g / jnp.where(h < -CURV_EPS, h, -1.0)
        uphill = jnp.sign(g) * 0.05 * width
        step = jnp.where(h < -CURV_EPS, newton, uphill)
        # trust region: one step never crosses more than half the bracket
        step = jnp.clip(step, -0.5 * width, 0.5 * width)
        return jnp.clip(kw + step, lo, hi)

    kw = jax.lax.fori_loop(0, n_steps, body, kw0)
    _, g, h = grad_and_diag_hess(f, kw)
    return kw, g, h


def newton_size(
    envs: sizing_ops.AgentEconInputs,
    n_periods: int,
    n_years: int,
    *,
    soft_tau: float | None = DEFAULT_TAU,
    n_steps: int = DEFAULT_STEPS,
    init_k: int = DEFAULT_INIT_K,
    net_billing: bool = True,
    impl: str = "xla",
) -> NewtonSizeResult:
    """Size the whole agent table by gradient ascent on the smooth NPV.

    1. ONE coarse-grid kernel call (``init_k`` columns) seeds the
       iterate at the best candidate — Newton needs a start inside the
       right basin, and the grid also serves as the degenerate-curvature
       fallback answer.
    2. ``n_steps`` damped Newton steps, each one ``value_and_grad`` +
       JVP evaluation of the shared objective.
    3. Accept the Newton iterate only where it (a) stayed concave and
       (b) actually beats the grid seed on the smooth objective;
       everywhere else keep the seed. The reference's own tolerance is
       ``max(2 kW, 1e-3 * width)``, so a seed from an ``init_k``-column
       grid refined by Newton matches the bracketed oracle wherever the
       surface is unimodal — and degrades to grid accuracy, never worse,
       where it is not.
    """
    f, lo, hi = sizing_ops.make_npv_objective(
        envs, n_periods, n_years,
        net_billing=net_billing, soft_tau=soft_tau, impl=impl,
    )
    k = max(int(init_k), 2)
    t = jnp.linspace(0.0, 1.0, k, dtype=jnp.float32)[None, :]
    grid = lo[:, None] + (hi - lo)[:, None] * t                   # [N, K]
    npv_grid = f(grid)                                            # [N, K]
    i0 = jnp.argmax(npv_grid, axis=1)
    take = lambda a: jnp.take_along_axis(a, i0[:, None], axis=1)[:, 0]
    kw0 = take(grid)
    npv0 = take(npv_grid)

    kw_n, g, h = newton_refine(f, kw0, lo, hi, n_steps=n_steps)
    npv_n = f(kw_n)

    ok = (h < -CURV_EPS) & (npv_n >= npv0)
    kw_star = jnp.where(ok, kw_n, kw0)
    return NewtonSizeResult(
        system_kw=kw_star,
        npv=jnp.where(ok, npv_n, npv0),
        grad=g,
        hess=h,
        fallback=~ok,
        lo=lo,
        hi=hi,
    )


def reference_xatol(lo: jax.Array, hi: jax.Array) -> jax.Array:
    """The reference sizing tolerance: ``max(2 kW, 1e-3 * width)``
    (financial_functions.py:444) — the parity budget for Newton vs the
    bracketed oracle."""
    return jnp.maximum(2.0, 1e-3 * (hi - lo))
