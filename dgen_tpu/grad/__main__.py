"""CLI for the differentiable twin: ``python -m dgen_tpu.grad <cmd>``.

Four subcommands, all printing a single JSON document to stdout:

``size``
    Newton-vs-bracketed-oracle sizing parity on a synthetic world:
    reports the max |kw| deviation against the reference ``fast=False``
    golden-section oracle and whether it is inside the oracle's own
    ``xatol``.
``calibrate``
    Recover seeded Bass p/q scales from synthetic adoption targets by
    differentiating the multi-year rollout (Gauss-Newton by default).
``policy``
    Solve the capex-incentive fraction that hits an adoption-uplift
    target by Newton on the differentiable rollout.
``check``
    Fast CI gate (wired into tools/check.sh): finite-difference
    gradcheck of the smooth NPV objective plus a small calibration
    round that must recover seeded p/q to <= 5% relative error.
    Exits nonzero on failure.

Flag defaults read the ``DGEN_TPU_GRAD_*`` environment (same
conventions as ``RunConfig.from_env``): ``DGEN_TPU_GRAD_AGENTS``
(--n-agents), ``DGEN_TPU_GRAD_TAU`` (--tau), ``DGEN_TPU_GRAD_SEED``
(--seed), ``DGEN_TPU_GRAD_STEPS`` (--steps, every subcommand) — so the
check.sh gate and CI wrappers can rescale without editing call sites.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

from dgen_tpu.grad import calibrate, newton, policy
from dgen_tpu.models import simulation as sim
from dgen_tpu.models.scenario import apply_year
from dgen_tpu.ops import sizing as sizing_ops

#: acceptance bar for the calibration gate (relative error on each of
#: the recovered p/q scales)
CHECK_PQ_RTOL = 0.05
#: acceptance bar for the finite-difference gradcheck (relative error
#: vs central differences, away from STE gate edges)
CHECK_GRAD_RTOL = 2e-2


def _world_envs(n_agents: int, seed: int, soft_tau: float):
    """First-year ``AgentEconInputs`` (plus static flags) for the same
    synthetic world the calibration gate runs on."""
    pop, inputs, step_kw, _ = calibrate.build_world(
        n_agents, seed=seed, soft_tau=soft_tau,
    )
    table, profiles, tariffs = pop.table, pop.profiles, pop.tariffs
    ya = apply_year(table, inputs, 0)
    state_kw_last = sim.starting_state_kw(table, inputs)
    nem_allowed = sim.compute_nem_allowed(table, inputs, 0, state_kw_last)
    rate_switch = bool(step_kw.get("rate_switch", False))
    envs = sim.build_econ_inputs(
        table, profiles, tariffs, ya, nem_allowed, table.incentives,
        rate_switch=rate_switch,
    )
    return envs, {
        "n_periods": int(step_kw["n_periods"]),
        "n_years": int(step_kw["econ_years"]),
        "net_billing": bool(step_kw.get("net_billing", True)),
    }


def cmd_size(args) -> dict:
    envs, meta = _world_envs(args.n_agents, args.seed, args.tau)
    res = newton.newton_size(
        envs, meta["n_periods"], meta["n_years"],
        soft_tau=args.tau, n_steps=args.steps,
        net_billing=meta["net_billing"],
    )
    oracle = sizing_ops.size_agents(
        envs, n_periods=meta["n_periods"], n_years=meta["n_years"],
        fast=False, n_iters=20, net_billing=meta["net_billing"],
    )
    xatol = np.asarray(newton.reference_xatol(res.lo, res.hi))
    diff = np.abs(np.asarray(res.system_kw) - np.asarray(oracle.system_kw))
    return {
        "n_agents": args.n_agents,
        "newton_steps": args.steps,
        "soft_tau": args.tau,
        "max_abs_diff_kw": float(diff.max()),
        "xatol_kw": float(xatol.min()),
        "within_xatol": bool(np.all(diff <= xatol)),
        "n_fallback": int(np.asarray(res.fallback).sum()),
        "mean_kw_newton": float(np.asarray(res.system_kw).mean()),
        "mean_kw_oracle": float(np.asarray(oracle.system_kw).mean()),
    }


def cmd_calibrate(args) -> dict:
    out = calibrate.recover_pq(
        args.n_agents, steps=args.steps, soft_tau=args.tau,
        seed=args.seed, method=args.method,
    )
    return out


def cmd_policy(args) -> dict:
    return policy.solve_incentive(
        args.n_agents, target_uplift=args.uplift, steps=args.steps,
        soft_tau=args.tau, seed=args.seed,
    )


def gradcheck(n_agents: int = 8, seed: int = 7, tau: float = 0.1) -> dict:
    """Central-difference check of the smooth NPV objective's gradient.

    Evaluated at three points across the sizing bracket. Agents whose
    evaluation point sits within ``5 * tau`` of a rate-switch window
    edge are excluded from the max: the STE gates there are hard in the
    forward pass by design, so finite differences of the primal cannot
    (and should not) match the straight-through derivative.
    """
    envs, meta = _world_envs(n_agents, seed, tau)
    npv_fn, lo, hi = sizing_ops.make_npv_objective(
        envs, meta["n_periods"], meta["n_years"],
        net_billing=meta["net_billing"], soft_tau=tau,
    )
    total = lambda kw: jnp.sum(npv_fn(kw))
    grad_fn = jax.jit(jax.grad(total))
    f = jax.jit(npv_fn)

    h = tau / 4.0
    worst = 0.0
    per_point = []
    for frac in (0.3, 0.6, 0.9):
        kw = lo + frac * (hi - lo)
        g = np.asarray(grad_fn(kw))
        fd = np.asarray((f(kw + h) - f(kw - h)) / (2.0 * h))
        rel = np.abs(g - fd) / (np.abs(fd) + 1.0)
        near_gate = (
            (np.abs(np.asarray(kw - envs.switch_min_kw)) < 5 * tau)
            | (np.abs(np.asarray(kw - envs.switch_max_kw)) < 5 * tau)
        )
        rel_ok = np.where(near_gate, 0.0, rel)
        worst = max(worst, float(rel_ok.max()))
        per_point.append({
            "frac": frac,
            "max_rel_err": float(rel_ok.max()),
            "n_gate_excluded": int(near_gate.sum()),
        })
    return {
        "n_agents": n_agents,
        "fd_step": h,
        "max_rel_err": worst,
        "points": per_point,
        "ok": worst < CHECK_GRAD_RTOL,
    }


def cmd_check(args) -> dict:
    gc = gradcheck(n_agents=8, seed=args.seed, tau=args.tau)
    cal = calibrate.recover_pq(
        args.n_agents, steps=args.steps, soft_tau=args.tau,
        seed=args.seed, method="gn",
    )
    cal_ok = (
        cal["rel_err_p"] <= CHECK_PQ_RTOL
        and cal["rel_err_q"] <= CHECK_PQ_RTOL
    )
    out = {
        "gradcheck": gc,
        "calibration": {
            "rel_err_p": cal["rel_err_p"],
            "rel_err_q": cal["rel_err_q"],
            "loss_last": cal["loss_last"],
            "ok": cal_ok,
        },
        "ok": bool(gc["ok"] and cal_ok),
    }
    return out


def _env_num(name: str, default, cast):
    v = os.environ.get(name, "")
    return cast(v) if v else default


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dgen_tpu.grad",
        description="Differentiable-twin workloads: sizing, "
                    "calibration, policy search.",
    )
    p.add_argument(
        "--n-agents", type=int,
        default=_env_num("DGEN_TPU_GRAD_AGENTS",
                         calibrate.CHECK_N_AGENTS, int))
    p.add_argument(
        "--tau", type=float,
        default=_env_num("DGEN_TPU_GRAD_TAU", calibrate.DEFAULT_TAU, float),
        help="smoothing temperature (kW / native units)")
    p.add_argument(
        "--seed", type=int, default=_env_num("DGEN_TPU_GRAD_SEED", 7, int))
    sub = p.add_subparsers(dest="cmd", required=True)

    def steps(sp, default):
        sp.add_argument(
            "--steps", type=int,
            default=_env_num("DGEN_TPU_GRAD_STEPS", default, int))

    ps = sub.add_parser("size", help="Newton sizing vs bracketed oracle")
    steps(ps, newton.DEFAULT_STEPS)
    ps.set_defaults(fn=cmd_size)

    pc = sub.add_parser("calibrate", help="recover seeded Bass p/q")
    steps(pc, 6)
    pc.add_argument("--method", choices=("gn", "adam"), default="gn")
    pc.set_defaults(fn=cmd_calibrate)

    pp = sub.add_parser("policy", help="solve incentive for a target")
    steps(pp, 6)
    pp.add_argument("--uplift", type=float, default=1.25)
    pp.set_defaults(fn=cmd_policy)

    pk = sub.add_parser("check", help="CI gate: gradcheck + calibration")
    steps(pk, 5)
    pk.set_defaults(fn=cmd_check)

    args = p.parse_args(argv)
    out = args.fn(args)
    print(json.dumps(out, indent=1, default=float))
    ok = out.get("ok", True)
    if not ok:
        print("dgen_tpu.grad: FAILED", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
