"""Calibration as a differentiable workload: fit Bass diffusion
parameters and the adoption-propensity elasticity against observed
state-level adoption by differentiating the FULL multi-year rollout.

The reference calibrates d-gen by hand: run, compare state adoption to
historical observations, nudge p/q, repeat. Here the entire simulation
— sizing kernels, bill engine, market share, Bass diffusion, scanned
over model years — is one JAX program, so the sensitivity of the final
adoption trajectory to ``bass_p``/``bass_q``/the MMS elasticity is an
exact reverse-mode gradient, and calibration is a few dozen Adam steps
instead of a human bisection loop.

Memory: the year scan is wrapped in ``jax.checkpoint`` — the backward
pass rebuilds each year's sizing forward (FLOPs traded for the O(years
x agents x candidates) residency the naive VJP would hold). With the
smooth twin (``soft_tau``) active, payback stays unrounded and the
max-market-share lookup interpolates, so gradients flow through the
economics into the diffusion inputs; the Bass parameters themselves
enter after sizing and are differentiable even on the hard path.

All optimizers here are hand-rolled (no optax dependency): plain Adam
on a small parameter pytree.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable

import jax
import jax.numpy as jnp

from dgen_tpu.config import ScenarioConfig
from dgen_tpu.models import scenario as scen
from dgen_tpu.models.simulation import (
    SimCarry,
    table_static_cache,
    year_step_impl,
)

#: default smoothing temperature for the rollout twin
DEFAULT_TAU = 0.1
#: audit/check scale (mirrors lint.prog registry constants)
CHECK_N_AGENTS = 64
CHECK_STATES = ("DE", "CA")
CHECK_END_YEAR = 2020
CHECK_ECON_YEARS = 8
CHECK_SIZING_ITERS = 4


# ---------------------------------------------------------------------------
# Parameterization
# ---------------------------------------------------------------------------

def init_params(fit_mms: bool = True) -> dict:
    """Calibration parameters at the identity point: log-scale
    multipliers on the Bass innovation (p) and imitation (q) rates,
    and (optionally) a log-exponent elasticity on the max-market-share
    curve (``mms**exp(elast)`` — at 0 the curve is untouched, positive
    values flatten propensity, negative sharpen it, and the [0, 1]
    range is preserved for free).

    ``fit_mms=False`` drops the elasticity from the fit — with only a
    few observed years, p/q and the elasticity trade off along a loss
    ridge, so recovery gates (check.sh, tests) freeze it."""
    z = jnp.zeros((), jnp.float32)
    params = {"log_p": z, "log_q": z}
    if fit_mms:
        params["mms_elast"] = z
    return params


def apply_params(inputs: scen.ScenarioInputs, params: dict) -> scen.ScenarioInputs:
    """Scenario inputs with the calibration parameters applied — a pure
    ``dataclasses.replace`` on traced leaves, so the rollout signature
    (and its compiled program) never changes with the parameter values.
    Missing keys mean "leave that input untouched"."""
    mms = inputs.mms_table
    if "mms_elast" in params:
        # safe power: the table's exact zeros (payback beyond the
        # horizon) must not feed 0**s -> 0 * log(0) = nan into the
        # elasticity grad
        s = jnp.exp(params["mms_elast"])
        mms = jnp.where(mms > 0.0, jnp.maximum(mms, 1e-12) ** s, 0.0)
    return dataclasses.replace(
        inputs,
        bass_p=inputs.bass_p * jnp.exp(params["log_p"]),
        bass_q=inputs.bass_q * jnp.exp(params["log_q"]),
        mms_table=mms,
    )


# ---------------------------------------------------------------------------
# Differentiable rollout
# ---------------------------------------------------------------------------

def make_rollout(
    table, profiles, tariffs, *, n_years: int, step_kw: dict
) -> Callable[[scen.ScenarioInputs], jax.Array]:
    """Build ``rollout(inputs) -> adopters [T, n_states]``: the full
    multi-year simulation reduced to the state-level adopter trajectory
    the calibration loss compares against observations.

    ``step_kw`` is the :meth:`Simulation.step_kwargs` static set MINUS
    ``first_year`` (threaded per call below). Years after the first run
    under ``lax.scan`` with a rematerialized (``jax.checkpoint``) body.
    """
    kw = {k: v for k, v in step_kw.items() if k != "first_year"}
    n_states = table.n_states
    state_idx = table.state_idx

    def state_adopters(outputs) -> jax.Array:
        return jax.ops.segment_sum(
            outputs.number_of_adopters * table.mask, state_idx, n_states
        )

    def rollout(inputs: scen.ScenarioInputs) -> jax.Array:
        carry0 = SimCarry.zeros(table.n_agents)
        carry1, out0 = year_step_impl(
            table, profiles, tariffs, inputs, carry0, jnp.int32(0),
            first_year=True, **kw,
        )

        @jax.checkpoint
        def body(carry, year_idx):
            c, out = year_step_impl(
                table, profiles, tariffs, inputs, carry, year_idx,
                first_year=False, **kw,
            )
            return c, state_adopters(out)

        _, rest = jax.lax.scan(
            body, carry1, jnp.arange(1, n_years, dtype=jnp.int32)
        )
        return jnp.concatenate([state_adopters(out0)[None], rest], axis=0)

    return rollout


def make_residuals(
    rollout: Callable[[scen.ScenarioInputs], jax.Array],
    base_inputs: scen.ScenarioInputs,
    targets: jax.Array,
) -> Callable[[dict], jax.Array]:
    """Normalized residual vector ``r(params) [T * n_states]`` between
    the rollout's state-adopter trajectory and the observations."""
    scale = jnp.maximum(jnp.mean(jnp.abs(targets)), 1.0)

    def residuals(params: dict) -> jax.Array:
        pred = rollout(apply_params(base_inputs, params))
        return ((pred - targets) / scale).ravel()

    return residuals


def make_loss(
    rollout: Callable[[scen.ScenarioInputs], jax.Array],
    base_inputs: scen.ScenarioInputs,
    targets: jax.Array,
) -> Callable[[dict], jax.Array]:
    """Normalized MSE between the rollout's state-adopter trajectory
    under ``params`` and the observed ``targets`` [T, n_states]."""
    residuals = make_residuals(rollout, base_inputs, targets)

    def loss(params: dict) -> jax.Array:
        return jnp.mean(residuals(params) ** 2)

    return loss


# ---------------------------------------------------------------------------
# Hand-rolled Adam
# ---------------------------------------------------------------------------

def fit(
    loss_fn: Callable[[dict], jax.Array],
    params0: dict,
    *,
    steps: int = 60,
    lr: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
) -> tuple[dict, list[float]]:
    """Minimize ``loss_fn`` with Adam over a small parameter pytree.

    Returns ``(params, loss_history)``. The update is one jitted
    ``value_and_grad`` program; the Python loop only pumps step indices
    (a handful of scalars — compile once, run ``steps`` times).
    """
    vg = jax.jit(jax.value_and_grad(loss_fn))

    @jax.jit
    def update(params, m, v, g, i):
        m = jax.tree.map(lambda m_, g_: b1 * m_ + (1 - b1) * g_, m, g)
        v = jax.tree.map(lambda v_, g_: b2 * v_ + (1 - b2) * g_ * g_, v, g)
        t = i.astype(jnp.float32) + 1.0
        def step(p, m_, v_):
            mhat = m_ / (1.0 - b1 ** t)
            vhat = v_ / (1.0 - b2 ** t)
            return p - lr * mhat / (jnp.sqrt(vhat) + eps)
        return jax.tree.map(step, params, m, v), m, v

    params = params0
    m = jax.tree.map(jnp.zeros_like, params)
    v = jax.tree.map(jnp.zeros_like, params)
    history: list[float] = []
    for i in range(steps):
        val, g = vg(params)
        params, m, v = update(params, m, v, g, jnp.int32(i))
        history.append(float(val))
    return params, history


def fit_gauss_newton(
    residual_fn: Callable[[dict], jax.Array],
    params0: dict,
    *,
    steps: int = 8,
    damping: float = 1e-3,
) -> tuple[dict, list[float]]:
    """Levenberg–Marquardt for FEW-parameter fits (the p/q recovery
    gate has two): the Jacobian is a handful of forward-mode columns
    through the rollout, and the normal equations are a tiny dense
    solve, so each iteration costs ~(1 + n_params) rollouts and
    converges quadratically near the optimum — where Adam needs
    hundreds of first-order steps to walk the p/q trade-off ridge.

    Returns ``(params, loss_history)`` with the same loss convention
    as :func:`fit` (mean squared normalized residual).
    """
    from jax.flatten_util import ravel_pytree

    x0, unravel = ravel_pytree(params0)

    def r_vec(x):
        return residual_fn(unravel(x))

    @jax.jit
    def lm_step(x):
        r = r_vec(x)
        jac = jax.jacfwd(r_vec)(x)                        # [M, P]
        a = jac.T @ jac + damping * jnp.eye(x.size, dtype=x.dtype)
        dx = jnp.linalg.solve(a, jac.T @ r)
        return x - dx, jnp.mean(r * r)

    x = x0
    history: list[float] = []
    for _ in range(steps):
        x, val = lm_step(x)
        history.append(float(val))
    return unravel(x), history


# ---------------------------------------------------------------------------
# Synthetic-recovery workload (tests, check.sh, bench)
# ---------------------------------------------------------------------------

def build_world(
    n_agents: int = CHECK_N_AGENTS,
    states=CHECK_STATES,
    end_year: int = CHECK_END_YEAR,
    seed: int = 7,
    *,
    econ_years: int = CHECK_ECON_YEARS,
    sizing_iters: int = CHECK_SIZING_ITERS,
    soft_tau: float | None = DEFAULT_TAU,
):
    """A small synthetic world + the static step set for calibration
    runs — no anchoring (anchored years would blend away the Bass
    signal the fit needs), storage off (the integer battery allocation
    is piecewise-constant in the parameters), hourly export off."""
    from dgen_tpu.io import synth  # deferred: pulls profile synthesis

    cfg = ScenarioConfig(
        name="calibrate", start_year=2014, end_year=end_year,
        anchor_years=(),
    )
    pop = synth.generate_population(
        n_agents, states=list(states), seed=seed, pad_multiple=32
    )
    inputs = scen.uniform_inputs(
        cfg, n_groups=pop.table.n_groups, n_regions=pop.n_regions
    )
    cache = table_static_cache(pop.table, pop.tariffs)
    step_kw = dict(
        n_periods=pop.tariffs.max_periods,
        econ_years=econ_years,
        sizing_iters=sizing_iters,
        with_hourly=False,
        storage_enabled=False,
        year_step_len=float(cfg.year_step),
        sizing_impl="xla",
        rate_switch=cache["rate_switch"],
        mesh=None,
        agent_chunk=0,
        net_billing=cache["any_nb_tariff"],
        daylight=None,
        pack_once=False,
        soft_tau=soft_tau,
        # the anchor rescale would blend the Bass signal away AND its
        # tiny-denominator guards make 0/0 tangents under linearization
        anchor=False,
    )
    n_years = len(cfg.model_years)
    return pop, inputs, step_kw, n_years


def recover_pq(
    n_agents: int = CHECK_N_AGENTS,
    *,
    true_p_scale: float = 1.6,
    true_q_scale: float = 0.7,
    steps: int = 6,
    lr: float = 0.15,
    soft_tau: float | None = DEFAULT_TAU,
    seed: int = 7,
    states=CHECK_STATES,
    end_year: int = CHECK_END_YEAR,
    fit_mms: bool = False,
    method: str = "gn",
) -> dict:
    """End-to-end synthetic recovery: generate an adoption trajectory
    from KNOWN scaled Bass parameters, then fit the scales back from
    the identity initialization. Returns truth, estimates, relative
    errors, and the loss curve (the check.sh grad gate asserts the
    relative errors; bench plots the curve).

    ``method='gn'`` (default) runs Levenberg–Marquardt — a few
    iterations suffice for the 2-parameter gate; ``'adam'`` runs the
    first-order fitter (``steps``/``lr`` then mean what they do in
    :func:`fit` — use many more steps)."""
    pop, inputs, step_kw, n_years = build_world(
        n_agents, states=states, end_year=end_year, seed=seed,
        soft_tau=soft_tau,
    )
    rollout = make_rollout(
        pop.table, pop.profiles, pop.tariffs,
        n_years=n_years, step_kw=step_kw,
    )
    truth = {
        "log_p": jnp.float32(math.log(true_p_scale)),
        "log_q": jnp.float32(math.log(true_q_scale)),
    }
    targets = rollout(apply_params(inputs, truth))
    params0 = init_params(fit_mms=fit_mms)
    if method == "gn":
        residual_fn = make_residuals(rollout, inputs, targets)
        fitted, history = fit_gauss_newton(
            residual_fn, params0, steps=steps
        )
    else:
        loss_fn = make_loss(rollout, inputs, targets)
        fitted, history = fit(loss_fn, params0, steps=steps, lr=lr)

    p_hat = float(jnp.exp(fitted["log_p"]))
    q_hat = float(jnp.exp(fitted["log_q"]))
    return {
        "true_p_scale": true_p_scale,
        "true_q_scale": true_q_scale,
        "p_scale_hat": p_hat,
        "q_scale_hat": q_hat,
        "mms_elast_hat": float(fitted.get("mms_elast", 0.0)),
        "rel_err_p": abs(p_hat - true_p_scale) / true_p_scale,
        "rel_err_q": abs(q_hat - true_q_scale) / true_q_scale,
        "loss_first": history[0],
        "loss_last": history[-1],
        "loss_curve": history,
        "n_agents": n_agents,
        "n_years": n_years,
        "steps": steps,
        "soft_tau": soft_tau,
    }


# The sizing argmax winner selection and the mms lerp_lookup
# floor/int-cast below are DELIBERATE straight-through sites: gradient
# flows through the gathered winner / the interpolation weight (the
# a.e. derivative), never the index — hence the J11 suppression on the
# registry anchor line.
def calib_loss_entry(  # dgenlint: disable=J11
    n_agents: int = CHECK_N_AGENTS,
    soft_tau: float = DEFAULT_TAU,
    *,
    end_year: int = CHECK_END_YEAR,
    econ_years: int = CHECK_ECON_YEARS,
    sizing_iters: int = CHECK_SIZING_ITERS,
):
    """(loss_fn, example_params) for the lint prog registry: the
    calibration loss as an auditable jitted program (J5 fingerprint +
    J6 cost + J11 backward-path rules)."""
    pop, inputs, step_kw, n_years = build_world(
        n_agents, soft_tau=soft_tau, end_year=end_year,
        econ_years=econ_years, sizing_iters=sizing_iters,
    )
    rollout = make_rollout(
        pop.table, pop.profiles, pop.tariffs,
        n_years=n_years, step_kw=step_kw,
    )
    targets = jnp.ones((n_years, pop.table.n_states), jnp.float32)
    loss_fn = make_loss(rollout, inputs, targets)
    return jax.value_and_grad(loss_fn), init_params()
