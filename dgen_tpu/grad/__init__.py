"""dgen_tpu.grad: the differentiable twin of the adoption model.

The hot loop of the paper is a per-agent scalar NPV optimization
(bracketed candidate-grid search, :mod:`dgen_tpu.ops.sizing`) feeding a
payback -> Bass diffusion step (:mod:`dgen_tpu.models.market`). Both are
pure JAX already — what blocks ``jax.grad`` is a handful of
non-differentiable kinks: tariff-tier and TOU-bucket edges in the bill
kernels, the hard relu import/export splits, the payback rounding and
the payback -> max-market-share table snap, and the argmax that picks
the winning candidate.

This package removes them behind one config gate
(``RunConfig.soft_boundaries`` / env ``DGEN_TPU_SOFT``):

* :mod:`~dgen_tpu.grad.smooth` — temperature-controlled softplus /
  soft-min surrogates plus straight-through estimators for the
  deliberate hard gates. Every kernel keeps its hard path bit-exact
  when the temperature is ``None``.
* :mod:`~dgen_tpu.grad.newton` — gradient-based sizing: a few batched,
  damped Newton steps on the smooth NPV objective (one value_and_grad
  kernel call per step instead of two 16-candidate refine rounds),
  bracket-projected, with a per-agent grid fallback where curvature is
  degenerate.
* :mod:`~dgen_tpu.grad.calibrate` — calibration as a workload:
  differentiate the full multi-year ``year_step`` rollout (lax.scan
  with checkpointed remat) to fit Bass p/q and an adoption elasticity
  against observed state-level adoption.
* :mod:`~dgen_tpu.grad.policy` — gradient search over an incentive
  level to hit an adoption target (the inverse-design demo).

CLI: ``python -m dgen_tpu.grad {size,calibrate,policy,check}``.
Runbook: docs/grad.md.
"""

from dgen_tpu.grad.smooth import (  # noqa: F401  (public API)
    clip0_t,
    lerp_lookup,
    min0_t,
    relu_t,
    ste_gate,
)

__all__ = ["relu_t", "clip0_t", "min0_t", "ste_gate", "lerp_lookup"]
