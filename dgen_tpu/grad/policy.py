"""Policy search: gradient-solve an incentive level for an adoption
target.

The inverse-design question a deployment analyst actually asks — "what
capex incentive hits X adopters by the end year?" — is a scalar
root-find through the entire simulation. The reference answers it by
re-running the model over a hand-picked incentive grid; here the final
adoption is differentiable in the incentive (the smooth twin keeps
payback and the market-share lookup differentiable through sizing), so
a few damped Newton iterations on the 1-D objective solve it directly.

The incentive is modeled as a fractional capex reduction applied to the
PV price trajectories (both standalone and PV+battery combined), the
same lever as the reference's ``pv_price_scenarios`` sensitivity runs —
parameterized through a sigmoid so the search stays inside (0, max).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

from dgen_tpu.grad import calibrate
from dgen_tpu.models import scenario as scen

#: incentives above this fraction of capex are outside the model's
#: credible range (and NPV becomes degenerate as cost -> 0)
MAX_INCENTIVE_FRAC = 0.8


def apply_incentive(
    inputs: scen.ScenarioInputs, frac: jax.Array
) -> scen.ScenarioInputs:
    """Scenario inputs with a fractional capex incentive applied to the
    PV price trajectories (traced — the rollout program is compiled
    once and reused across the search)."""
    keep = 1.0 - frac
    return dataclasses.replace(
        inputs,
        pv_capex_per_kw=inputs.pv_capex_per_kw * keep,
        pv_capex_per_kw_combined=inputs.pv_capex_per_kw_combined * keep,
    )


def national_adopters_fn(
    rollout: Callable[[scen.ScenarioInputs], jax.Array],
    base_inputs: scen.ScenarioInputs,
) -> Callable[[jax.Array], jax.Array]:
    """``f(theta) -> final-year national adopters`` where the incentive
    fraction is ``MAX_INCENTIVE_FRAC * sigmoid(theta)`` (unconstrained
    theta, bounded incentive)."""

    def f(theta: jax.Array) -> jax.Array:
        frac = MAX_INCENTIVE_FRAC * jax.nn.sigmoid(theta)
        return jnp.sum(rollout(apply_incentive(base_inputs, frac))[-1])

    return f


def solve_incentive(
    n_agents: int = calibrate.CHECK_N_AGENTS,
    *,
    target_uplift: float = 1.25,
    steps: int = 8,
    soft_tau: float | None = calibrate.DEFAULT_TAU,
    seed: int = 7,
    states=calibrate.CHECK_STATES,
    end_year: int = calibrate.CHECK_END_YEAR,
) -> dict:
    """Find the capex-incentive fraction whose end-year national
    adoption is ``target_uplift`` x the no-incentive baseline.

    Safeguarded Newton on the scalar residual ``f(theta) - target``
    with the exact derivative ``f'(theta)`` from reverse-mode AD
    through the rollout; each iteration is one ``value_and_grad``
    evaluation of the full multi-year program. Adoption is monotone in
    the incentive, so the solver keeps a sign-changing bracket and
    falls back to bisection whenever the Newton step leaves it — the
    sigmoid parameterization's exponentially flat tails would otherwise
    make raw Newton oscillate for targets near the baseline. Targets
    beyond saturation (every developable agent already adopts) are
    reported via ``converged=False`` rather than by diverging.
    """
    pop, inputs, step_kw, n_years = calibrate.build_world(
        n_agents, states=states, end_year=end_year, seed=seed,
        soft_tau=soft_tau,
    )
    rollout = calibrate.make_rollout(
        pop.table, pop.profiles, pop.tariffs,
        n_years=n_years, step_kw=step_kw,
    )
    f = national_adopters_fn(rollout, inputs)
    vg = jax.jit(jax.value_and_grad(f))

    lo, hi = -10.0, 6.0            # sigmoid(-10) ~ no incentive
    f_lo = float(f(jnp.float32(lo)))
    f_hi = float(f(jnp.float32(hi)))
    baseline = f_lo
    target = baseline * float(target_uplift)

    history = []
    if target >= f_hi:
        # saturated: even the max incentive cannot reach the target
        theta, final = jnp.float32(hi), f_hi
    else:
        theta = jnp.float32(0.5 * (lo + hi))
        val = None
        for _ in range(steps):
            val, dval = vg(theta)
            resid = float(val) - target
            if resid > 0.0:
                hi = float(theta)
            else:
                lo = float(theta)
            newton = float(theta) - resid / max(float(dval), 1e-6)
            # bisect when the Newton step exits the current bracket
            bisected = not (lo < newton < hi)
            if bisected:
                newton = 0.5 * (lo + hi)
            history.append({
                "theta": float(theta),
                "adopters": float(val),
                "resid": resid,
                "dadopters_dtheta": float(dval),
                "bisected": bisected,
            })
            theta = jnp.float32(newton)
        final = float(f(theta))
    frac = float(MAX_INCENTIVE_FRAC * jax.nn.sigmoid(theta))
    rel_miss = abs(final - target) / max(target, 1.0)
    # At small populations adoption moves in agent-weight quanta, so a
    # cohort can straddle the target: a bracket collapsed below theta
    # resolution IS the solution to model granularity.
    converged = rel_miss < 0.02 or (target < f_hi and hi - lo < 0.05)
    return {
        "baseline_adopters": baseline,
        "target_adopters": target,
        "target_uplift": target_uplift,
        "incentive_frac": frac,
        "final_adopters": final,
        "rel_miss": rel_miss,
        "converged": converged,
        "theta_bracket_width": hi - lo,
        "history": history,
        "n_agents": n_agents,
        "n_years": n_years,
        "soft_tau": soft_tau,
    }
