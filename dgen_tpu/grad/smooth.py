"""Smoothing primitives for the differentiable objective twin.

Every surrogate here is parameterized by a temperature ``tau`` in the
argument's native units and converges to its hard counterpart as
``tau -> 0``. The kernel call sites (:mod:`dgen_tpu.ops.bill`,
:mod:`dgen_tpu.ops.billpallas`, :mod:`dgen_tpu.ops.sizing`,
:mod:`dgen_tpu.models.market`) take ``soft_tau=None`` by default and
lower their ORIGINAL hard expressions in that case — the smooth twin is
additive, never a rewrite of the oracle.

Two families:

* softplus surrogates (:func:`relu_t`, :func:`clip0_t`, :func:`min0_t`)
  for the import/export splits and the tariff-tier segment clips —
  places where smoothing the VALUE is acceptable inside the smoothing
  radius and a useful gradient matters more than the last 0.1% of bill
  accuracy.
* straight-through estimators (:func:`ste_gate`) for gates whose
  forward value must stay HARD (the rate-switch window, the TOU-sell
  presence test): forward evaluates the exact 0/1 gate, backward
  substitutes a sigmoid bump so the boundary position still receives
  gradient. These are the deliberate J11 suppression sites (see
  docs/lint.md).

:func:`lerp_lookup` replaces a round-to-grid table gather with linear
interpolation between the two bracketing rows; its floor/int-cast pair
is piecewise constant by construction (the gradient flows through the
interpolation weight, which is exactly the a.e. derivative).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def relu_t(x: jax.Array, tau: float) -> jax.Array:
    """Soft relu: ``tau * softplus(x / tau)`` — smooth max(x, 0).

    Overestimates the hard relu by at most ``tau * log(2)`` (at x=0)
    and converges exponentially fast outside a few ``tau`` of the kink.
    """
    return tau * jax.nn.softplus(x / tau)


def min0_t(x: jax.Array, tau: float) -> jax.Array:
    """Smooth min(x, 0) = ``-relu_t(-x, tau)``."""
    return -relu_t(-x, tau)


def clip0_t(x: jax.Array, width: jax.Array, tau: float) -> jax.Array:
    """Smooth ``clip(x, 0, width)`` as a difference of soft relus.

    Exact for ``width >> tau`` away from both edges; at ``width = 0``
    (a degenerate tariff tier) the two softplus terms cancel to 0 like
    the hard clip.
    """
    return relu_t(x, tau) - relu_t(x - width, tau)


@partial(jax.custom_jvp, nondiff_argnums=(1,))
def _ste_gate(x: jax.Array, tau: float) -> jax.Array:
    return (x >= 0.0).astype(jnp.float32)


@_ste_gate.defjvp
def _ste_gate_jvp(tau, primals, tangents):
    (x,) = primals
    (dx,) = tangents
    s = jax.nn.sigmoid(x / tau)
    # d/dx sigmoid(x/tau) = s(1-s)/tau: a bump of width ~tau replacing
    # the true (zero-a.e.) derivative of the step. Defined as a
    # custom_jvp (NOT custom_vjp) because the Newton path takes
    # forward-over-reverse second derivatives (jvp of grad) through the
    # objective; the rule is linear in ``dx``, so reverse mode still
    # derives automatically by transposition.
    return _ste_gate(x, tau), dx * s * (1.0 - s) / tau


def ste_gate(x: jax.Array, tau: float | None) -> jax.Array:
    """Heaviside step ``float(x >= 0)`` with a straight-through
    derivative.

    ``tau=None`` returns the plain hard comparison (no custom-AD rule
    in the program — the oracle path lowers byte-identically). With a
    temperature, the forward value is STILL the exact hard gate — only
    the derivative substitutes a sigmoid bump, so gate boundaries
    (rate-switch kW windows, NEM availability) receive gradient without
    perturbing the priced bill.
    """
    if tau is None:
        return (x >= 0.0).astype(jnp.float32)
    return _ste_gate(x, tau * 1.0)


def lerp_lookup(table: jax.Array, idx_float: jax.Array) -> jax.Array:
    """Linearly interpolated gather along ``table``'s LAST axis.

    ``idx_float`` is a continuous (already clipped/scaled) grid
    coordinate; leading axes of ``table`` must have been gathered away
    by the caller (e.g. ``mms_table[sector_idx]`` -> [N, GRID]).
    Gradient w.r.t. ``idx_float`` is ``table[hi] - table[lo]`` — the
    a.e. derivative of the piecewise-linear interpolant.
    """
    n = table.shape[-1]
    x = jnp.clip(idx_float, 0.0, n - 1.0)
    lo = jnp.clip(jnp.floor(x).astype(jnp.int32), 0, n - 2)
    frac = x - lo.astype(x.dtype)
    v_lo = jnp.take_along_axis(table, lo[..., None], axis=-1)[..., 0]
    v_hi = jnp.take_along_axis(table, (lo + 1)[..., None], axis=-1)[..., 0]
    return v_lo + frac * (v_hi - v_lo)
