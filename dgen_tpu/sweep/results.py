"""Sweep outputs: per-scenario :class:`SimResults` plus the
cross-scenario delta report, and parquet export with the scenario id
stamped into each run directory's ``meta.json``.

The reference answers "what did the ITC step-down change?" by diffing
two separately-exported Postgres schemas by hand; here the sweep knows
its own baseline and emits the deltas as a first-class surface
(``sweep.json`` + per-scenario export directories).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from dgen_tpu.models.simulation import SimResults


class _YearView:
    """Adapter presenting one (scenario, year) slice of collected host
    results with the YearOutputs attribute surface RunExporter reads —
    so the export path is the single-run exporter, unchanged."""

    def __init__(self, res: SimResults, yi: int) -> None:
        self._res = res
        self._yi = yi

    def __getattr__(self, name: str):
        if name == "state_hourly_net_mw":
            h = self._res.state_hourly_net_mw
            if h is None:
                return np.zeros((0, 0), dtype=np.float32)
            return h[self._yi]
        try:
            return self._res.agent[name][self._yi]
        except KeyError as e:
            raise AttributeError(name) from e


@dataclasses.dataclass
class SweepResults:
    """Host-side results of an S-scenario sweep."""

    labels: List[str]
    baseline: int                 # index of the delta reference
    runs: List[SimResults]        # one per scenario, label-aligned
    plan: object                  # the SweepPlan that executed
    bank_bytes_shared: int        # profile-bank bytes uploaded ONCE
    host_mask: np.ndarray
    host_agent_id: np.ndarray
    #: load-time quarantine summary of the shared population
    #: (resilience.quarantine; None = validation off, {} = clean):
    #: every scenario runs over the SAME contained table, so one block
    #: covers the whole sweep — stamped into each scenario's meta.json
    #: and into sweep.json by :meth:`export`
    quarantine: Optional[Dict[str, object]] = None
    #: Monte-Carlo quantile block (dgen_tpu.ensemble.stats
    #: .EnsembleStats) when the runs are ensemble members rather than
    #: policy scenarios: per-year p10/p50/p90 national/state bands.
    #: :meth:`export` stamps it into sweep.json and writes the long-form
    #: ``quantiles.parquet`` beside it. None for ordinary sweeps.
    quantiles: Optional[object] = None

    @property
    def n_scenarios(self) -> int:
        return len(self.runs)

    def __getitem__(self, label_or_idx) -> SimResults:
        if isinstance(label_or_idx, str):
            return self.runs[self.labels.index(label_or_idx)]
        return self.runs[label_or_idx]

    def summaries(self) -> List[Dict[str, np.ndarray]]:
        """Per-scenario national per-year aggregates
        (:meth:`SimResults.summary`)."""
        return [r.summary(self.host_mask) for r in self.runs]

    def delta_report(self) -> Dict[str, object]:
        """Cross-scenario deltas vs the designated baseline scenario:
        per-year national adoption / capacity / storage deltas plus
        fleet NPV, and final-year scalars for quick reading.

        Resume-safe: a resumed sweep's members cover (possibly
        different, possibly empty) suffixes of the year grid —
        checkpoints hold only the cross-year carry, so already-run
        years have no collected outputs. Deltas are computed on the
        years every non-empty run covers; members with no new years
        are reported as ``no_new_years`` entries. Raises ValueError
        when the baseline itself has no collected years (nothing to
        delta against — rerun without resume, or read the exported
        surfaces of the original run)."""
        m = self.host_mask
        base_run = self.runs[self.baseline]
        if not base_run.agent:
            raise ValueError(
                f"baseline scenario '{self.labels[self.baseline]}' has "
                "no collected years (fully resumed, or collect=False); "
                "no delta report is possible"
            )
        nonempty = [i for i, r in enumerate(self.runs) if r.agent]
        years = [
            y for y in base_run.years
            if all(y in self.runs[i].years for i in nonempty)
        ]
        if not years:
            raise ValueError(
                "no common collected years across scenarios; rerun the "
                "sweep without resume for a full delta report"
            )

        def curves(i):
            r = self.runs[i]
            sel = np.asarray([r.years.index(y) for y in years])
            s = r.summary(m)
            npv = (r.agent["npv"] * m[None, :]).sum(axis=1)
            return {k: np.asarray(v)[sel] for k, v in s.items()}, npv[sel]

        base, base_npv = curves(self.baseline)
        scenarios = []
        for i, label in enumerate(self.labels):
            if i not in nonempty:
                scenarios.append({
                    "scenario": label,
                    "is_baseline": i == self.baseline,
                    "no_new_years": True,
                })
                continue
            s, npv = curves(i)
            d_adopt = np.asarray(s["adopters"] - base["adopters"])
            d_kw = np.asarray(s["system_kw_cum"] - base["system_kw_cum"])
            d_kwh = np.asarray(s["batt_kwh_cum"] - base["batt_kwh_cum"])
            d_npv = np.asarray(npv - base_npv)
            scenarios.append({
                "scenario": label,
                "is_baseline": i == self.baseline,
                "adopters_delta": [float(v) for v in d_adopt],
                "system_kw_cum_delta": [float(v) for v in d_kw],
                "batt_kwh_cum_delta": [float(v) for v in d_kwh],
                "npv_total_delta": [float(v) for v in d_npv],
                "final": {
                    "adopters": float(s["adopters"][-1]),
                    "adopters_delta": float(d_adopt[-1]),
                    "system_kw_cum": float(s["system_kw_cum"][-1]),
                    "system_kw_cum_delta": float(d_kw[-1]),
                    "batt_kwh_cum_delta": float(d_kwh[-1]),
                    "npv_total_delta": float(d_npv[-1]),
                },
            })
        return {
            "baseline": self.labels[self.baseline],
            "years": [int(y) for y in years],
            "scenarios": scenarios,
        }

    def export(
        self,
        run_dir: str,
        state_names: Optional[Sequence[str]] = None,
        meta: Optional[Dict[str, object]] = None,
        finance_series: bool = True,
    ) -> str:
        """Write every scenario's three parquet surfaces under
        ``<run_dir>/scenario=<label>/`` (the single-run
        :class:`~dgen_tpu.io.export.RunExporter`, with the scenario id
        stamped into each meta.json) plus the cross-scenario
        ``sweep.json`` delta report at the top. Returns ``run_dir``."""
        from dgen_tpu.io.export import RunExporter
        from dgen_tpu.utils.logging import get_logger

        if all(not r.agent for r in self.runs):
            raise ValueError(
                "no scenario has collected results (collect=False, or a "
                "fully resumed sweep); nothing to export"
            )
        for i, (label, res) in enumerate(zip(self.labels, self.runs)):
            if not res.agent:
                # a resumed member with no NEW years: its surfaces were
                # written by the original run — skip, don't fail the
                # members that do have fresh data
                get_logger().warning(
                    "sweep export: scenario %s has no collected years "
                    "(resumed); skipping", label,
                )
                continue
            exporter = RunExporter(
                os.path.join(run_dir, f"scenario={label}"),
                agent_id=self.host_agent_id,
                mask=self.host_mask,
                state_names=list(state_names) if state_names else None,
                finance_series=finance_series,
                meta={
                    "scenario": label,
                    "scenario_index": i,
                    "sweep_baseline": self.labels[self.baseline],
                    "sweep_n_scenarios": self.n_scenarios,
                    # the shared population's load-time quarantine
                    # block: the mask is carried through sharding and
                    # every scenario, so each exported surface names it
                    **({"quarantine": self.quarantine}
                       if self.quarantine else {}),
                    **(meta or {}),
                },
            )
            for yi, year in enumerate(res.years):
                exporter(int(year), yi, _YearView(res, yi))
        try:
            report = self.delta_report()
        except ValueError as e:
            # partial resume without a usable baseline: still leave a
            # sweep.json breadcrumb saying why the deltas are absent
            report = {"delta_report_unavailable": str(e),
                      "baseline": self.labels[self.baseline]}
        report["bank_bytes_shared"] = int(self.bank_bytes_shared)
        if self.quarantine:
            report["quarantine"] = self.quarantine
        report["groups"] = [
            {"mode": g.mode, "net_billing": bool(g.net_billing),
             "scenarios": [self.labels[i] for i in g.indices]}
            for g in self.plan.groups
        ]
        if self.quantiles is not None:
            # ensemble runs: the quantile bands are the headline
            # surface — into sweep.json verbatim, plus a long-form
            # parquet (one row per year x quantile) for analysis stacks
            report["quantiles"] = self.quantiles.to_json()
            from dgen_tpu.resilience.atomic import atomic_to_parquet

            atomic_to_parquet(
                self.quantiles.frame(),
                os.path.join(run_dir, "quantiles.parquet"),
            )
        from dgen_tpu.resilience.atomic import atomic_write_json

        atomic_write_json(
            os.path.join(run_dir, "sweep.json"), report, indent=1,
        )
        return run_dir
