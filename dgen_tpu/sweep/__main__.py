"""CLI: ``python -m dgen_tpu.sweep`` — run a policy sweep (ITC
schedule x retail-price escalator x storage-cost scale) over one
synthetic population in a single process.

Each axis takes a comma list; the sweep is the cartesian product:

    python -m dgen_tpu.sweep --agents 512 --states DE CA \\
        --end-year 2030 --itc 0.30,0.10,0.0 --esc 0.0,0.01 \\
        --run-dir runs/itc-sweep

prints per-scenario adoption curves and the delta report vs the
baseline (first combination unless ``--baseline`` picks another), and
— with ``--run-dir`` — exports every scenario's parquet surfaces plus
``sweep.json``. Real populations go through the programmatic API
(:class:`dgen_tpu.sweep.SweepSimulation`) with inputs from
``io.reference_inputs`` / ``io.package``.
"""

from __future__ import annotations

import argparse
import itertools
import json
import time

import numpy as np


def _floats(s: str) -> list:
    return [float(tok) for tok in s.split(",") if tok.strip()]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dgen_tpu.sweep",
        description="batched multi-scenario sweep on one population",
    )
    ap.add_argument("--agents", type=int, default=512)
    ap.add_argument("--states", nargs="*", default=["DE", "CA", "TX"])
    ap.add_argument("--start-year", type=int, default=2014)
    ap.add_argument("--end-year", type=int, default=2030)
    ap.add_argument("--itc", type=_floats, default=[0.30, 0.0],
                    help="comma list of flat ITC fractions")
    ap.add_argument("--esc", type=_floats, default=[0.0],
                    help="comma list of retail-price escalators (/yr)")
    ap.add_argument("--batt-scale", type=_floats, default=[1.0],
                    help="comma list of storage capex multipliers")
    ap.add_argument("--baseline", type=int, default=0)
    ap.add_argument("--sizing-iters", type=int, default=8)
    ap.add_argument("--with-hourly", action="store_true")
    ap.add_argument("--run-dir", default=None,
                    help="export parquet surfaces + sweep.json here")
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--resume", action="store_true")
    args = ap.parse_args(argv)

    from dgen_tpu.config import RunConfig, ScenarioConfig
    from dgen_tpu.io import synth
    from dgen_tpu.models import scenario as scen
    from dgen_tpu.parallel.mesh import default_mesh
    from dgen_tpu.sweep import SweepSimulation
    from dgen_tpu.utils import compilecache

    compilecache.enable()

    import jax.numpy as jnp

    cfg = ScenarioConfig(
        name="sweep", start_year=args.start_year, end_year=args.end_year,
        anchor_years=(),
    )
    pop = synth.generate_population(
        args.agents, states=list(args.states), seed=7,
    )
    years = list(cfg.model_years)
    Y, S = len(years), len(cfg.sectors)
    R = pop.n_regions

    members, labels = [], []
    for itc, esc, bscale in itertools.product(
        args.itc, args.esc, args.batt_scale
    ):
        mult = jnp.asarray(
            ((1.0 + esc) ** np.arange(Y, dtype=np.float32))
            [:, None, None] * np.ones((1, R, S), np.float32)
        )
        base = scen.uniform_inputs(
            cfg, n_groups=pop.table.n_groups, n_regions=R,
            overrides={
                "itc_fraction": jnp.full((Y, S), itc, jnp.float32),
                "elec_price_multiplier": mult,
                "elec_price_escalator": jnp.full(
                    (Y, R, S), min(max(esc, -0.01), 0.01), jnp.float32),
            },
        )
        import dataclasses as dc

        members.append(dc.replace(
            base,
            batt_capex_per_kwh=base.batt_capex_per_kwh * bscale,
            batt_capex_per_kwh_combined=(
                base.batt_capex_per_kwh_combined * bscale),
        ))
        labels.append(f"itc{itc:g}-esc{esc:g}-batt{bscale:g}")

    print(f"sweep: {len(members)} scenario(s) x {args.agents} agents, "
          f"{Y} model years")
    t0 = time.time()
    sweep = SweepSimulation(
        pop.table, pop.profiles, pop.tariffs, members, cfg,
        RunConfig(sizing_iters=args.sizing_iters),
        # production placement (2-D hosts x devices under
        # jax.distributed, flat single-host, DGEN_TPU_MESH override)
        mesh=default_mesh(),
        with_hourly=args.with_hourly, labels=labels,
        baseline=args.baseline,
    )
    results = sweep.run(
        checkpoint_dir=args.checkpoint_dir, resume=args.resume,
    )
    wall = time.time() - t0

    try:
        report = results.delta_report()
    except ValueError as e:
        # e.g. a fully resumed sweep collected no new years
        report = {"scenarios": [], "baseline": labels[args.baseline]}
        print(f"delta report unavailable: {e}")
    for s in report["scenarios"]:
        tag = " (baseline)" if s["is_baseline"] else ""
        if s.get("no_new_years"):
            print(f"  {s['scenario']}{tag}: no new years (resumed)")
            continue
        f = s["final"]
        print(
            f"  {s['scenario']}{tag}: adopters {f['adopters']:.1f} "
            f"(delta {f['adopters_delta']:+.1f}), kW delta "
            f"{f['system_kw_cum_delta']:+.1f}, fleet NPV delta "
            f"{f['npv_total_delta']:+.0f}"
        )
    if args.run_dir:
        try:
            results.export(
                args.run_dir, state_names=list(synth.STATES),
                meta={"cli": True},
            )
            print(f"exported to {args.run_dir}")
        except ValueError as e:
            print(f"export skipped: {e}")
    print(json.dumps({
        "scenarios": len(members),
        "agents": args.agents,
        "years": Y,
        "wall_s": round(wall, 2),
        "per_scenario_wall_s": round(wall / len(members), 2),
        "bank_bytes_shared": results.bank_bytes_shared,
        "groups": [
            {"mode": g.mode, "n": g.n_scenarios}
            for g in results.plan.groups
        ],
        "baseline": report["baseline"],
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
