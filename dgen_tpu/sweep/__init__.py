"""Batched multi-scenario sweep engine.

Runs S scenarios (policy variants: ITC schedules, retail-price
escalators, storage-cost curves, NEM caps...) in one process against
ONE HBM-resident copy of the agent table and profile banks — the
scenario axis rides the small [Y, ...] trajectory arrays, never the
multi-GB hourly banks. See :mod:`dgen_tpu.sweep.driver` for the
execution modes, :mod:`dgen_tpu.sweep.plan` for the grouping/HBM
planner, and ``python -m dgen_tpu.sweep --help`` for the CLI.
"""

from dgen_tpu.sweep.driver import (  # noqa: F401
    SweepSimulation,
    bank_nbytes,
    sweep_year_step,
)
from dgen_tpu.sweep.plan import (  # noqa: F401
    MODE_LOOP,
    MODE_VMAP,
    ScenarioGroup,
    SweepBudgetError,
    SweepPlan,
    plan_sweep,
)
from dgen_tpu.sweep.results import SweepResults  # noqa: F401
