"""The sweep driver: S scenarios in one program against one
HBM-resident copy of the agent table and profile banks.

A policy sweep in the reference is S independent invocations of the
whole pipeline — S re-ingests of the same population and S re-uploads
of the identical [N, 8760] profile banks. Here the banks and the agent
table are placed ONCE (the :class:`~dgen_tpu.models.simulation.
Simulation` placement path, reused as-is) and only the small
[Y, ...]-shaped :class:`~dgen_tpu.models.scenario.ScenarioInputs`
leaves carry a scenario axis. Per planner group
(:mod:`dgen_tpu.sweep.plan`) execution is either:

* **vmap mode** — one jitted program per model year vmapping
  :func:`~dgen_tpu.models.simulation.year_step_impl` over the scenario
  axis (:func:`sweep_year_step`); the per-year economics batch S-wide
  on device, sharing every gathered bank read's upstream state; or
* **loop mode** — scenario-major: each scenario runs through the SAME
  compiled single-scenario ``year_step`` executable (identical static
  arguments by construction —
  :meth:`~dgen_tpu.models.simulation.Simulation.with_inputs` siblings),
  so S scenarios pay one compile and HBM stays bounded by the
  single-scenario ``auto_agent_chunk``. Mesh runs always take this
  path: scenario groups ride the existing shard_map layout unchanged.

Steady-state years never retrace in either mode (RetraceGuard-armed
when ``RunConfig.guard_retrace`` is set: in vmap mode from the third
executed year, in loop mode additionally across scenarios — scenario
1..S-1 must compile NOTHING).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from dgen_tpu.config import RunConfig, ScenarioConfig
from dgen_tpu.models.scenario import (
    ScenarioInputs,
    ScenarioStack,
    stack_scenarios,
)
from dgen_tpu.models.simulation import (
    YEAR_STEP_STATIC_ARGNAMES,
    SimCarry,
    SimResults,
    Simulation,
    YearOutputs,
    year_step_impl,
)
from dgen_tpu.sweep.plan import (
    MODE_VMAP,
    ScenarioGroup,
    SweepPlan,
    plan_sweep,
)
from dgen_tpu.resilience.faults import fault_point
from dgen_tpu.sweep.results import SweepResults
from dgen_tpu.utils import timing
from dgen_tpu.utils.logging import get_logger

logger = get_logger()


@partial(
    jax.jit,
    static_argnames=YEAR_STEP_STATIC_ARGNAMES,
    # the stacked cross-year carry is threaded linearly, exactly like
    # the single-scenario program's (dgenlint L7)
    donate_argnames=("carry",),
)
def sweep_year_step(
    table,
    profiles,
    tariffs,
    inputs_s,           # ScenarioInputs with [S, ...] leaves
    carry,              # SimCarry with [S, N] leaves
    year_idx,
    *,
    n_periods: int,
    econ_years: int,
    sizing_iters: int,
    first_year: bool,
    with_hourly: bool,
    storage_enabled: bool,
    year_step_len: float,
    sizing_impl: str = "auto",
    rate_switch: bool = False,
    mesh=None,
    agent_chunk: int = 0,
    net_billing: bool = True,
    daylight=None,
    pack_once: bool = False,
    soft_tau=None,
    anchor: bool = True,
    cluster=None,
    cluster_banks=None,
    cluster_tidx=None,
):
    """One model year for S scenarios as a single device program: the
    un-jitted :func:`year_step_impl` vmapped over the scenario axis of
    (inputs, carry), with the table and the banks closed over UNMAPPED
    — XLA sees one copy of every [N, 8760] gather source. Static
    arguments mirror ``year_step`` exactly, so the two programs share
    the compile-flag vocabulary. The cluster layout (and its compact
    banks/indices) is scenario-invariant, so it stays unmapped like the
    table; the planner pins its per-cluster flags per group, exactly
    like ``net_billing``."""

    def one(inputs, c):
        return year_step_impl(
            table, profiles, tariffs, inputs, c, year_idx,
            n_periods=n_periods, econ_years=econ_years,
            sizing_iters=sizing_iters, first_year=first_year,
            with_hourly=with_hourly, storage_enabled=storage_enabled,
            year_step_len=year_step_len, sizing_impl=sizing_impl,
            rate_switch=rate_switch, mesh=mesh, agent_chunk=agent_chunk,
            net_billing=net_billing, daylight=daylight,
            pack_once=pack_once, soft_tau=soft_tau, anchor=anchor,
            cluster=cluster, cluster_banks=cluster_banks,
            cluster_tidx=cluster_tidx,
        )

    return jax.vmap(one)(inputs_s, carry)


def bank_nbytes(profiles) -> int:
    """Total bytes of the HBM-resident profile banks — the quantity a
    sweep uploads once instead of S times (stamped into bench payloads
    and sweep metadata as ``bank_bytes_shared``)."""
    return int(sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree.leaves(profiles)
    ))


class SweepSimulation:
    """Run S scenarios against one shared population (the sweep
    analogue of :class:`~dgen_tpu.models.simulation.Simulation`).

    Parameters
    ----------
    table, profiles, tariffs : the shared population and banks, placed
        once (Simulation's placement path).
    scenarios : S ScenarioInputs (or a prebuilt ScenarioStack); all
        must share the scenario's static grid — a mismatch raises
        ScenarioStackError naming the field.
    scenario : ScenarioConfig common to every member (the sweep axis is
        the trajectory arrays, not the year grid).
    labels : per-scenario names (default ``scn0..scnS-1``); stamped
        into exports and checkpoint subdirectories.
    baseline : index of the delta-report reference scenario.
    plan : optional precomputed SweepPlan (default: plan_sweep on the
        live device budget).
    max_vmap_scenarios : forwarded to the planner.
    Other parameters match Simulation.
    """

    def __init__(
        self,
        table,
        profiles,
        tariffs,
        scenarios: Union[Sequence[ScenarioInputs], ScenarioStack],
        scenario: ScenarioConfig,
        run_config: Optional[RunConfig] = None,
        mesh=None,
        with_hourly: bool = False,
        econ_years: int = 25,
        labels: Optional[Sequence[str]] = None,
        baseline: int = 0,
        plan: Optional[SweepPlan] = None,
        max_vmap_scenarios: Optional[int] = None,
    ) -> None:
        if isinstance(scenarios, ScenarioStack):
            members = [
                scenarios.scenario(i) for i in range(scenarios.n_scenarios)
            ]
        else:
            members = list(scenarios)
        if not members:
            raise ValueError("sweep needs at least one scenario")
        self.members = members
        self.scenario = scenario
        self.run_config = run_config or RunConfig()
        self.mesh = mesh
        self.with_hourly = with_hourly
        self.labels = (
            list(labels) if labels is not None
            else [f"scn{i}" for i in range(len(members))]
        )
        if len(self.labels) != len(members):
            raise ValueError(
                f"{len(self.labels)} labels for {len(members)} scenarios"
            )
        if not 0 <= baseline < len(members):
            raise ValueError(f"baseline index {baseline} out of range")
        self.baseline = baseline
        years = list(scenario.model_years)

        self.plan = plan if plan is not None else plan_sweep(
            members, years,
            table=table, tariffs=tariffs,
            with_hourly=with_hourly, econ_years=econ_years,
            sizing_iters=self.run_config.sizing_iters,
            bank_bf16=self.run_config.bf16_banks,
            bank_quant=self.run_config.quant_banks,
            mesh=mesh,
            max_vmap_scenarios=max_vmap_scenarios,
            cluster=self.run_config.cluster_tariffs,
            agent_pad_multiple=self.run_config.agent_pad_multiple,
        )

        # the base Simulation does all the one-time work — static
        # flags, daylight layout, chunk derivation, padding/partition,
        # device placement of the table and the multi-GB banks — with
        # the planner's S-aware chunk substituted so a vmapped group's
        # working set fits
        rc = self.run_config
        if self.plan.agent_chunk is not None and rc.agent_chunk is None:
            rc = dataclasses.replace(rc, agent_chunk=self.plan.agent_chunk)
        self.base = Simulation(
            table, profiles, tariffs, members[self.baseline], scenario,
            rc, mesh=mesh, with_hourly=with_hourly, econ_years=econ_years,
        )
        self.years = self.base.years

        #: bytes of profile bank resident in HBM — uploaded once for
        #: the whole sweep (the S-way amortization the engine exists
        #: for); per-scenario siblings share the SAME placed arrays
        self.bank_bytes_shared = bank_nbytes(self.base.profiles)

        # per-scenario sibling runners (loop mode executes these; vmap
        # mode uses them only for init/resume conveniences). Every
        # sibling shares the base's placed table/banks and compiled
        # executables; net_billing is pinned per planner group so a
        # group cannot split the executable.
        nb_of = {
            i: g.net_billing for g in self.plan.groups for i in g.indices
        }
        self.sims: List[Simulation] = [
            self.base.with_inputs(
                m, net_billing=nb_of[i], timing_ctx=self.labels[i],
            )
            for i, m in enumerate(members)
        ]

        if self.plan.global_hbm_bytes is not None:
            logger.info(
                "sweep HBM budget: %.2f GiB global (%dx%d mesh, "
                "%.2f GiB/device), %d bytes/agent-row modeled",
                self.plan.global_hbm_bytes / 1024**3,
                *self.plan.mesh_shape,
                self.plan.hbm_bytes / 1024**3,
                self.plan.per_agent_bytes,
            )
        for g in self.plan.groups:
            logger.info(
                "sweep group (%d scenario(s), net_billing=%s): %s mode",
                g.n_scenarios, g.net_billing, g.mode,
            )

        #: shared io.hostio.HostIOPool for the duration of run(): S
        #: per-scenario pipelines reuse ONE fetch/io thread pair
        #: instead of spawning two threads per scenario
        self._pool = None
        #: per-group/per-scenario HostPipeline.stats() of the last run
        #: (empty when the run serialized)
        self.hostio_stats: Dict[str, dict] = {}

    @property
    def n_scenarios(self) -> int:
        return len(self.members)

    # -- vmap mode ------------------------------------------------------

    def _init_stacked_carry(self, s: int) -> SimCarry:
        n = self.base.table.n_agents
        zeros = SimCarry.zeros(n)
        # one buffer per (field, scenario-stack): broadcast_to would
        # alias, and the step donates the carry
        return jax.tree.map(
            lambda x: jnp.zeros((s,) + x.shape, x.dtype), zeros
        )

    def _run_group_vmap(
        self,
        group: ScenarioGroup,
        collect: bool,
        checkpoint_dir: Optional[str],
        resume: bool,
        guard_label: str,
    ) -> Dict[int, SimResults]:
        from dgen_tpu.io import checkpoint as ckpt

        s = group.n_scenarios
        stack = stack_scenarios([self.members[i] for i in group.indices])
        inputs_s = stack.inputs
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(self.mesh, P())
            inputs_s = jax.tree.map(
                lambda x: self.base._put(x, repl), inputs_s
            )

        kwargs = self.base.step_kwargs(first_year=True)
        kwargs["net_billing"] = group.net_billing
        # a 1-device mesh adds nothing inside a vmapped body (the
        # planner sends >1-device meshes to loop mode); dropping it
        # keeps sharding constraints out of the batched trace
        kwargs["mesh"] = None
        # one compiled program per group: the group flag pins every
        # cluster flag the same way (with_inputs does the same for the
        # loop-mode siblings), so member scenarios cannot split the
        # per-cluster executables either
        if kwargs.get("cluster") is not None:
            kwargs["cluster"] = kwargs["cluster"].pin_net_billing(
                group.net_billing
            )
        kwargs.update(self.base.step_operands())

        carry = self._init_stacked_carry(s)
        start_idx = 0
        writer = None
        scn_key = guard_label        # per-group stacked checkpoint dir
        if resume:
            if not checkpoint_dir:
                raise ValueError("resume=True requires checkpoint_dir")
            last = ckpt.latest_year(checkpoint_dir, scenario=scn_key)
            if last is not None and last not in self.years:
                raise ValueError(
                    f"checkpointed year {last} is not on this sweep's "
                    f"year grid {self.years}; refusing to resume"
                )
            if last is not None:
                _, carry = ckpt.restore_year(
                    checkpoint_dir, self.base.table.n_agents, last,
                    scenario=scn_key, n_scenarios=s,
                )
                start_idx = self.years.index(last) + 1
                logger.info(
                    "sweep %s: resuming after year %d (index %d)",
                    scn_key, last, start_idx,
                )
        if checkpoint_dir is not None:
            writer = ckpt.Writer(checkpoint_dir, scenario=scn_key)

        agent_fields = [
            f.name for f in dataclasses.fields(YearOutputs)
            if f.name != "state_hourly_net_mw"
        ]
        collected: Dict[str, list] = {k: [] for k in agent_fields}
        hourly: List[np.ndarray] = []

        # background host-IO pipeline (io.hostio): the stacked year
        # steps dispatch back to back while collection and the stacked
        # checkpoint saves drain on the sweep's shared worker pair
        async_io = (
            self.run_config.async_io_enabled
            and not self.run_config.debug_invariants
            and jax.process_count() == 1
            and (collect or writer is not None)
        )
        pipeline = None
        collector = None
        consumers: list = []
        if async_io:
            from dgen_tpu.io import hostio

            if collect:
                collector = hostio.CollectConsumer(
                    agent_fields, self.with_hourly)
                consumers.append(collector)
            if writer is not None:
                consumers.append(hostio.CheckpointConsumer(writer))

        guard = None
        loop_failed = False
        try:
            for yi, year in enumerate(self.years):
                if yi < start_idx:
                    continue
                if (
                    self.run_config.guard_retrace and guard is None
                    and yi - start_idx >= 2
                ):
                    from dgen_tpu.lint.guard import RetraceGuard

                    guard = RetraceGuard(
                        context=f"sweep {guard_label} steady state"
                    ).start()
                kwargs["first_year"] = (yi == 0)
                with timing.timer("sweep_year_step", ctx=guard_label):
                    carry, outs = sweep_year_step(
                        self.base.table, self.base.profiles,
                        self.base.tariffs, inputs_s, carry,
                        jnp.asarray(yi, dtype=jnp.int32), **kwargs,
                    )
                    if not async_io:
                        jax.block_until_ready(carry.market.market_share)
                if async_io:
                    if pipeline is None:
                        pipeline = hostio.pipeline_for(
                            consumers, outs,
                            carry=carry if writer is not None else None,
                            timing_ctx=guard_label,
                            pool=self._pool,
                        )
                    # stacked-carry snapshot BEFORE the next
                    # iteration's sweep_year_step donates it
                    snap = (hostio.snapshot_carry(carry)
                            if writer is not None else None)
                    pipeline.submit(year, yi, outs, carry=snap)
                else:
                    if writer is not None:
                        writer.save(year, carry)
                    if collect:
                        to_fetch = {
                            k: getattr(outs, k) for k in agent_fields
                        }
                        if self.with_hourly:
                            to_fetch["_hourly"] = outs.state_hourly_net_mw
                        # serialized parity-oracle path (async sweeps
                        # route through hostio)
                        host = jax.device_get(to_fetch)  # dgenlint: disable=L9
                        for k in agent_fields:
                            collected[k].append(host[k])
                        if self.with_hourly:
                            hourly.append(host["_hourly"])
                if guard is not None:
                    guard.check(f"year {year}")
        except BaseException:
            loop_failed = True
            raise
        finally:
            if guard is not None:
                guard.stop()
            try:
                if pipeline is not None:
                    # flush queued years before the writer closes,
                    # without masking a loop failure
                    self.hostio_stats[guard_label] = pipeline.drain(
                        failed=loop_failed)
            finally:
                # nested finally: drain() re-raises a worker error on
                # the success path, and even then a mid-run exception
                # must not abandon orbax's background save threads
                # without wait_until_finished (io.checkpoint.Writer)
                if writer is not None:
                    writer.close()
        if async_io:
            # drain the dispatched year chain (scalar fetch: readiness
            # alone is unreliable through remote-tunnel transports)
            with timing.timer("device_drain", ctx=guard_label):
                jax.block_until_ready(carry.market.market_share)
                float(jnp.sum(carry.batt_adopters_cum))
        if collector is not None:
            collected, hourly = collector.collected, collector.hourly

        run_years = self.years[start_idx:]
        out: Dict[int, SimResults] = {}
        for j, idx in enumerate(group.indices):
            agent = (
                {k: np.stack([v[j] for v in vs])
                 for k, vs in collected.items()}
                if collect and collected[agent_fields[0]] else {}
            )
            out[idx] = SimResults(
                years=list(run_years),
                agent=agent,
                state_hourly_net_mw=(
                    np.stack([h[j] for h in hourly]) if hourly else None
                ),
            )
        return out

    # -- loop mode ------------------------------------------------------

    def _run_group_loop(
        self,
        group: ScenarioGroup,
        collect: bool,
        checkpoint_dir: Optional[str],
        resume: bool,
    ) -> Dict[int, SimResults]:
        from dgen_tpu.io import checkpoint as ckpt

        out: Dict[int, SimResults] = {}
        guard = None
        try:
            for k, idx in enumerate(group.indices):
                # resilience drill hook: a scenario dying between the
                # scenarios of a loop-mode group; the supervisor's
                # retry re-enters at (scenario, year) via the
                # per-scenario checkpoint layout
                fault_point("sweep_scenario")
                sim = self.sims[idx]
                scn_ckpt = (
                    ckpt.scenario_dir(checkpoint_dir, self.labels[idx])
                    if checkpoint_dir else None
                )
                out[idx] = sim.run(
                    collect=collect, checkpoint_dir=scn_ckpt,
                    resume=resume,
                )
                if sim.hostio_stats is not None:
                    self.hostio_stats[self.labels[idx]] = sim.hostio_stats
                if (
                    self.run_config.guard_retrace and guard is None
                    and k == 0 and len(group.indices) > 1
                ):
                    # scenario 0 compiled the program pair; every later
                    # scenario in the group must compile NOTHING — the
                    # whole point of grouping by static config
                    from dgen_tpu.lint.guard import RetraceGuard

                    guard = RetraceGuard(
                        context="sweep cross-scenario"
                    ).start()
                elif guard is not None:
                    guard.check(f"scenario {self.labels[idx]}")
        finally:
            if guard is not None:
                guard.stop()
        return out

    # -- the sweep ------------------------------------------------------

    def run(
        self,
        collect: bool = True,
        checkpoint_dir: Optional[str] = None,
        resume: bool = False,
    ) -> SweepResults:
        """Run every scenario of every planner group.

        ``checkpoint_dir`` lays out per-scenario subdirectories
        (``scn=<label>/`` in loop mode, one stacked ``scn=<group>/``
        per vmapped group), so ``resume=True`` continues a killed sweep
        at (scenario, year) instead of restarting it.

        Host consumers ride the background host-IO pipeline
        (:mod:`dgen_tpu.io.hostio`) exactly like single runs — with
        ONE shared worker pair across every per-scenario pipeline, not
        two threads per scenario. ``RunConfig.async_host_io=False``
        (env ``DGEN_TPU_ASYNC_IO=0``) serializes, and
        :attr:`hostio_stats` carries the per-group/per-scenario
        pipeline stats afterwards.
        """
        self.hostio_stats = {}
        pool = None
        # same gate as the per-scenario pipelines (_run_group_vmap /
        # Simulation.run): no consumer or a debug/multi-process run
        # never builds a pipeline, so don't spawn the worker pair
        if (
            self.run_config.async_io_enabled
            and not self.run_config.debug_invariants
            and jax.process_count() == 1
            and (collect or checkpoint_dir is not None)
        ):
            from dgen_tpu.io import hostio

            pool = hostio.HostIOPool()
        self._pool = pool
        for sim in self.sims:
            sim._hostio_pool = pool
        results: Dict[int, SimResults] = {}
        try:
            for gi, group in enumerate(self.plan.groups):
                if group.mode == MODE_VMAP:
                    results.update(self._run_group_vmap(
                        group, collect, checkpoint_dir, resume,
                        guard_label=f"group{gi}",
                    ))
                else:
                    results.update(self._run_group_loop(
                        group, collect, checkpoint_dir, resume,
                    ))
        finally:
            self._pool = None
            for sim in self.sims:
                sim._hostio_pool = None
            if pool is not None:
                pool.close()
        rep_q = getattr(self.base, "quarantine_report", None)
        return SweepResults(
            labels=list(self.labels),
            baseline=self.baseline,
            runs=[results[i] for i in range(self.n_scenarios)],
            plan=self.plan,
            bank_bytes_shared=self.bank_bytes_shared,
            host_mask=self.base.host_mask,
            host_agent_id=self.base.host_agent_id,
            # load-time quarantine of the ONE shared table: the mask
            # rides every scenario/shard unchanged, so a single block
            # attributes the whole sweep's exports
            quarantine=(
                rep_q.summary()
                if rep_q is not None and not rep_q.is_clean else None
            ),
        )
