"""Sweep planner: group scenarios so each group shares ONE compiled
executable, and budget the batched scenario axis against the HBM
footprint model.

Scenarios in a sweep run against one shared agent table and one
HBM-resident copy of the profile banks; the only thing that may vary
is the small [Y, ...]-shaped trajectory arrays in
:class:`~dgen_tpu.models.scenario.ScenarioInputs`. Two things can still
split the compiled program:

* a **static-shape mismatch** (different year grid / group / region /
  state axis sizes) — rejected outright with an error naming the field
  (:func:`~dgen_tpu.models.scenario.validate_scenario_statics`), since
  such scenarios cannot share the table either;
* the **net-billing compile flag**
  (:func:`~dgen_tpu.models.simulation.run_static_flags`): an all-NEM
  scenario statically drops the bucket-sums kernel. Scenarios are
  grouped by this flag, so each group compiles once and shares the
  compilecache entry.

Per group the planner also picks the execution mode against the
per-agent HBM model (:func:`_per_agent_step_bytes`): ``vmap`` batches
the per-year economics over the scenario axis in one program (the
cheap-parameter-axis observation of the columnar-ABM literature);
``loop`` runs scenario-major over the SAME compiled single-scenario
executable when S would blow the vmapped working set — HBM stays
bounded by ``auto_agent_chunk`` either way.

Budgets are **mesh-global**: per-device HBM x mesh size is what a
national-scale plan actually has to spend (the J9 mesh audit
cross-checks the same per-device model against the compiler's static
memory analysis at 3x slack, docs/lint.md). A plan that cannot fit
even the 128-row streaming-chunk floor raises
:class:`SweepBudgetError` naming the mesh shape and the global budget.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from dgen_tpu.models.scenario import ScenarioInputs, validate_scenario_statics
from dgen_tpu.models.simulation import (
    _CHUNK_FLOOR_ROWS,
    _HBM_RESERVE_FRAC,
    _PERSISTENT_ROW_BYTES,
    _per_agent_step_bytes,
    auto_agent_chunk,
    default_hbm_bytes,
    run_static_flags,
    table_static_cache,
)

#: vmap-width cap when the device exposes no HBM budget (CPU/virtual
#: backends, where the byte model is not calibrated): small sweeps
#: batch, large sweeps fall back to the scenario-major loop
DEFAULT_MAX_VMAP_SCENARIOS = 8

MODE_VMAP = "vmap"
MODE_LOOP = "loop"


class SweepBudgetError(ValueError):
    """A sweep plan that cannot fit the mesh's GLOBAL HBM even at the
    streaming-chunk floor. The message names the mesh shape, the
    per-device and global budgets, and the footprint that broke them —
    an over-budget 10M-row national plan must be diagnosable from the
    message alone (no debugger, no byte model spelunking)."""


def _gib(n: int) -> str:
    return f"{n / 1024**3:.2f} GiB"


def _budget_error(
    *, what: str, need_bytes: int, hbm_bytes: int, mesh_shape, n_dev: int,
    n_global_rows: int, group_scenarios: int, per_agent: int,
) -> SweepBudgetError:
    h, d = mesh_shape
    return SweepBudgetError(
        f"sweep plan over budget: {what} needs {_gib(need_bytes)} per "
        f"device, but the {h}x{d} mesh budgets {_gib(hbm_bytes)}/device "
        f"({_gib(hbm_bytes * n_dev)} global HBM across {n_dev} devices, "
        f"{_HBM_RESERVE_FRAC:.0%} reserved for compiler scratch) for "
        f"{n_global_rows} global agent rows at {per_agent} modeled "
        f"bytes/row (models.simulation._per_agent_step_bytes); the "
        f"scenario-major loop holds ONE of the group's "
        f"{group_scenarios} scenario(s) resident at a time, so this is "
        f"already the plan's cheapest mode. Fixes: grow the mesh (more "
        f"global HBM), split the scenario axis across runs, or shrink "
        f"the table. docs/perf.md 'HBM budgeting'."
    )


@dataclasses.dataclass(frozen=True)
class ScenarioGroup:
    """Scenarios that share one compiled executable."""

    indices: Tuple[int, ...]     # positions in the sweep's scenario list
    net_billing: bool            # the group's compile-time bill flag
    mode: str                    # MODE_VMAP | MODE_LOOP

    @property
    def n_scenarios(self) -> int:
        return len(self.indices)


@dataclasses.dataclass(frozen=True)
class SweepPlan:
    """Execution plan for an S-scenario sweep."""

    groups: Tuple[ScenarioGroup, ...]
    n_scenarios: int
    #: agent-axis streaming chunk the sweep should run with (value for
    #: RunConfig.agent_chunk): None = keep the operator's setting (no
    #: HBM information); 0 = whole-table; >0 = budgeted for the widest
    #: vmapped group, so every group's working set fits
    agent_chunk: Optional[int]
    #: per-device HBM bytes the budget used (None = unknown backend)
    hbm_bytes: Optional[int]
    #: modeled peak step bytes per (agent x scenario) row
    per_agent_bytes: int
    #: (hosts, devices) shape of the mesh the plan budgeted for —
    #: (1, 1) for meshless runs, so every budget decision names its
    #: topology (J9 cross-checks this same model per device)
    mesh_shape: Tuple[int, int] = (1, 1)
    #: hbm_bytes x mesh size: the global accelerator memory the whole
    #: sweep is budgeted against (None = unknown backend)
    global_hbm_bytes: Optional[int] = None
    #: Monte-Carlo ensemble members riding each scenario (the
    #: ``dgen_tpu.ensemble`` member axis): every budget decision above
    #: was made at ``s * n_members`` batched rows
    n_members: int = 1

    @property
    def max_vmap_width(self) -> int:
        widths = [g.n_scenarios for g in self.groups if g.mode == MODE_VMAP]
        return max(widths) if widths else 1


def plan_sweep(
    scenarios: Sequence[ScenarioInputs],
    years: List[int],
    *,
    table,
    tariffs,
    with_hourly: bool = False,
    econ_years: int = 25,
    sizing_iters: int = 12,
    bank_bf16: bool = False,
    bank_quant: bool = False,
    mesh=None,
    hbm_bytes: Optional[int] = -1,
    max_vmap_scenarios: Optional[int] = None,
    enforce_budget: bool = True,
    cluster: bool = False,
    agent_pad_multiple: int = 128,
    n_members: int = 1,
) -> SweepPlan:
    """Plan an S-scenario sweep over one shared population.

    ``hbm_bytes``: per-device accelerator memory; the default sentinel
    ``-1`` reads the live device (:func:`default_hbm_bytes`), ``None``
    means explicitly unknown (mode decisions then fall back to the
    :data:`DEFAULT_MAX_VMAP_SCENARIOS` width cap).

    ``cluster``: budget for a tariff-clustered layout
    (RunConfig.cluster_tariffs; ops.tariffcluster). The PER-ROW model
    is unchanged — the bucket buckets are padded to a fixed minor axis
    (``B_PAD``) regardless of ``n_periods``, and the per-row hour
    arrays don't depend on the rate structure — but the clustered
    table itself is wider: every per-(device, cluster) segment rounds
    up to the layout pad multiple, so the planner adds a
    ``K x agent_pad_multiple`` per-device row allowance (the upper
    bound of the segment round-up; the layout's true multiple also
    folds in the streaming chunk, whose padding the unclustered table
    pays too). Rate-switch corpora ignore the flag, exactly like
    Simulation does.

    Raises :class:`~dgen_tpu.models.scenario.ScenarioStackError` when
    scenarios disagree on a static field (the error names it), and
    :class:`SweepBudgetError` when even the 128-row streaming-chunk
    floor cannot fit the mesh's budget — the message names the mesh
    shape and the GLOBAL (per-device x mesh size) HBM budget, so an
    over-budget national plan is diagnosable from the message alone.
    ``enforce_budget=False`` returns the best-effort plan instead
    (floor chunks may overshoot the device — the pre-pod behavior,
    kept for deliberately starved what-if planning).

    ``n_members``: Monte-Carlo ensemble members per scenario
    (``dgen_tpu.ensemble``). The member axis batches exactly like the
    scenario axis — members of one scenario share the scenario's
    compile flags by construction (draws never perturb ``nem_cap_kw``)
    — so every width decision below runs at ``s * n_members`` batched
    rows: the persistent carry is counted ``s * n_members``-wide, the
    vmap width cap applies to the product, and loop mode reuses ONE
    compiled executable member-major when the product doesn't fit.
    """
    scenarios = list(scenarios)
    validate_scenario_statics(scenarios)
    n_members = max(int(n_members), 1)
    if hbm_bytes == -1:
        hbm_bytes = default_hbm_bytes()
    max_vmap = (
        max_vmap_scenarios if max_vmap_scenarios is not None
        else DEFAULT_MAX_VMAP_SCENARIOS
    )

    # group by the compile-time flags (rate_switch is table-only and
    # identical across scenarios; net_billing depends on each
    # scenario's NEM caps) — first-seen order keeps group 0 anchored on
    # scenario 0, the conventional sweep baseline. The table-derived
    # half is computed once (table_static_cache); only the NEM-gate
    # proof reruns per member.
    tcache = table_static_cache(table, tariffs)
    rate_switch = tcache["rate_switch"]
    by_flag: dict = {}
    for i, inputs in enumerate(scenarios):
        _, nb = run_static_flags(
            table, tariffs, inputs, years, table_cache=tcache)
        by_flag.setdefault(nb, []).append(i)

    from dgen_tpu.parallel.mesh import mesh_shape_of

    n_dev = int(mesh.devices.size) if mesh is not None else 1
    mesh_shape = mesh_shape_of(mesh) if mesh is not None else (1, 1)
    n_local = max(table.n_agents // n_dev, 1)
    if cluster and not rate_switch:
        # per-device row allowance for the cluster-major layout's
        # segment padding: only clusters with live member rows appear
        # in the layout (plan_layout drops the rest)
        from dgen_tpu.ops.tariffcluster import analyze_bank

        import numpy as np

        plan_c = analyze_bank(tariffs)
        live = np.unique(plan_c.cluster_of_tariff[
            np.asarray(table.tariff_idx)[np.asarray(table.mask) > 0]
        ])
        n_local += len(live) * int(agent_pad_multiple)

    def check_chunk_floor(group_scenarios: int, per_agent_b: int,
                          what: str) -> None:
        # the one unplannable case: even a floor-sized streaming chunk
        # (plus the persistent [N] row state — loop mode keeps ONE
        # scenario resident at a time, the same model auto_agent_chunk
        # budgets) busts the per-device budget — auto_agent_chunk would
        # silently return the floor and the run would OOM, so fail HERE
        # with the mesh/global numbers
        if hbm_bytes is None or not enforce_budget:
            return
        budget = int(hbm_bytes * (1.0 - _HBM_RESERVE_FRAC))
        persistent = n_local * _PERSISTENT_ROW_BYTES
        # a shard SMALLER than the floor that fits whole is plannable
        # (auto_agent_chunk returns 0 there) — only a shard that can't
        # stream even min(n_local, floor) rows is hopeless
        need_rows = min(n_local, _CHUNK_FLOOR_ROWS)
        need = persistent + need_rows * per_agent_b
        if (budget - persistent) // per_agent_b < need_rows:
            raise _budget_error(
                what=what, need_bytes=need, hbm_bytes=hbm_bytes,
                mesh_shape=mesh_shape, n_dev=n_dev,
                n_global_rows=table.n_agents,
                group_scenarios=group_scenarios,
                per_agent=per_agent_b,
            )

    # worst-case per-row footprint across the sweep's flag groups (a
    # single chunk choice must hold for every group)
    per_agent = max(
        _per_agent_step_bytes(
            sizing_iters=sizing_iters, econ_years=econ_years,
            with_hourly=with_hourly, net_billing=nb,
            rate_switch=rate_switch, bank_bf16=bank_bf16,
            bank_quant=bank_quant,
        )
        for nb in by_flag
    )

    groups: List[ScenarioGroup] = []
    chunk: Optional[int] = None
    for nb, idxs in by_flag.items():
        s = len(idxs)
        # the batched width HBM actually sees: scenarios x ensemble
        # members (one carry row-set per member per scenario)
        w = s * n_members
        if mesh is not None and mesh.devices.size > 1:
            # multi-chip: scenario groups ride the existing shard_map
            # layout unchanged — the scenario-major loop reuses the
            # single-scenario executable and its mesh placement as-is,
            # including its per-device streaming chunk
            mode = MODE_LOOP
            if hbm_bytes is not None:
                check_chunk_floor(
                    s, per_agent,
                    f"the scenario-major loop's floor chunk "
                    f"({_CHUNK_FLOOR_ROWS} rows/device)",
                )
                c = auto_agent_chunk(
                    n_local, sizing_iters=sizing_iters,
                    econ_years=econ_years, with_hourly=with_hourly,
                    hbm_bytes=hbm_bytes, net_billing=nb,
                    rate_switch=rate_switch, bank_bf16=bank_bf16,
                    bank_quant=bank_quant,
                )
                if c:
                    chunk = c if chunk is None else min(chunk, c)
        elif hbm_bytes is None:
            mode = MODE_VMAP if w <= max_vmap else MODE_LOOP
        else:
            # budget (S x E) x N rows against the device (the same
            # model auto_agent_chunk uses, with the persistent
            # [S*E, N] carry counted (S*E)-wide)
            budget = int(hbm_bytes * (1.0 - _HBM_RESERVE_FRAC))
            budget -= w * n_local * _PERSISTENT_ROW_BYTES
            rows_fit = max(budget, 0) // per_agent
            if w <= max_vmap and w * n_local <= rows_fit:
                mode = MODE_VMAP            # whole table, (S*E)-way batched
            elif w <= max_vmap and rows_fit // w >= _CHUNK_FLOOR_ROWS:
                mode = MODE_VMAP            # chunked, (S*E)-way batched
                c = (int(rows_fit // w) // _CHUNK_FLOOR_ROWS
                     * _CHUNK_FLOOR_ROWS)
                chunk = c if chunk is None else min(chunk, c)
            else:
                mode = MODE_LOOP
                check_chunk_floor(
                    s, per_agent,
                    f"the scenario-major loop's floor chunk "
                    f"({_CHUNK_FLOOR_ROWS} rows/device)",
                )
                c = auto_agent_chunk(
                    n_local, sizing_iters=sizing_iters,
                    econ_years=econ_years, with_hourly=with_hourly,
                    hbm_bytes=hbm_bytes, net_billing=nb,
                    rate_switch=rate_switch, bank_bf16=bank_bf16,
                    bank_quant=bank_quant,
                )
                if c:
                    chunk = c if chunk is None else min(chunk, c)
        groups.append(ScenarioGroup(
            indices=tuple(idxs), net_billing=nb, mode=mode,
        ))

    if hbm_bytes is not None and chunk is None:
        chunk = 0   # everything fits whole-table

    return SweepPlan(
        groups=tuple(groups),
        n_scenarios=len(scenarios),
        agent_chunk=chunk,
        hbm_bytes=hbm_bytes,
        per_agent_bytes=per_agent,
        mesh_shape=mesh_shape,
        global_hbm_bytes=(
            hbm_bytes * n_dev if hbm_bytes is not None else None),
        n_members=n_members,
    )
