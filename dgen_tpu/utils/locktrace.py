"""Runtime lock-order sentinel: instrumented ``threading.Lock``/``RLock``.

The static concurrency tier (``python -m dgen_tpu.lint --conc``, rules
C1-C6) proves lock *discipline* on the AST; this module proves lock
*behaviour* at runtime.  :func:`arm` replaces the ``threading.Lock`` and
``threading.RLock`` factories with wrappers that record, per thread:

* the **held-set** — which locks the thread holds at each acquisition;
* the **order graph** — an edge ``A -> B`` whenever a thread acquires
  ``B`` while holding ``A`` (first sighting keeps a witness: thread
  name plus a trimmed stack);
* **contention stats** per lock *site* (acquisition count, total and
  max wait, max hold) for the bench payloads;
* **hold violations** — a lock held longer than the configured ceiling
  while another thread was blocked on it (the PR 11
  probe-under-the-supervisor-lock class, caught live).

:func:`check` then fails on any cycle in the observed order graph (a
real, witnessed deadlock *possibility* — two threads interleaving those
stacks stop forever) or on hold violations.  The fleet, gang and
serve-scale drills run with the sentinel armed via ``tools/check.sh``
(``DGEN_TPU_LOCKTRACE=1`` -> :func:`arm_from_env`).

Zero cost when disarmed: nothing is patched, every helper returns
empty, and code that never calls :func:`arm` pays not one branch.
Locks created *before* arming keep their raw C implementation and are
simply invisible to the sentinel — arm first (the drills arm before
the serving stack is constructed).

Naming: a lock is named by its creation site (``file.py:lineno``), so
every ``FleetFront`` instance's ``self._lock`` aggregates into one
named series — which is what a contention report wants.  The aliasing
is load-bearing for ordering too: nesting two *sibling* locks born at
the same site records a self-edge, which is the account-transfer
deadlock (same-class instances locked in no global order) and fails
:func:`check` like any other cycle.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

# the raw factories, captured before any patching can happen
_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock

#: default hold-time ceiling (seconds) before a *contended* hold is a
#: violation — generous enough for a compile-cache-warm batch step,
#: far below the 2 s readiness-probe round-trip PR 11 evicted from
#: under the supervisor lock.
DEFAULT_HOLD_CEILING_S = 1.0

_armed = False
_hold_ceiling_s = DEFAULT_HOLD_CEILING_S
_state = _ORIG_LOCK()          # leaf lock guarding the tables below
_held = threading.local()      # per-thread list of _Held entries
_edges: Dict[Tuple[str, str], dict] = {}
_stats: Dict[str, dict] = {}
_violations: List[dict] = []


class _Held:
    __slots__ = ("wrapper", "t_acq", "depth")

    def __init__(self, wrapper, t_acq: float) -> None:
        self.wrapper = wrapper
        self.t_acq = t_acq
        self.depth = 1


def _held_stack() -> List[_Held]:
    try:
        return _held.stack
    except AttributeError:
        _held.stack = []
        return _held.stack


def _site_name() -> str:
    """``file.py:lineno`` of the frame that called the lock factory,
    skipping stdlib ``threading.py`` internals (Condition allocating
    its RLock should name the Condition's creator, not threading.py)."""
    f = sys._getframe(2)
    while f is not None and os.path.basename(f.f_code.co_filename) in (
        "threading.py", "locktrace.py",
    ):
        f = f.f_back
    if f is None:
        return "<unknown>"
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


def _short_stack(skip: int = 2, limit: int = 8) -> List[str]:
    frames = traceback.extract_stack()[: -skip][-limit:]
    return [f"{os.path.basename(fr.filename)}:{fr.lineno}:{fr.name}"
            for fr in frames]


def _stat(name: str) -> dict:
    s = _stats.get(name)
    if s is None:
        s = _stats[name] = {
            "acquisitions": 0, "total_wait_s": 0.0,
            "max_wait_s": 0.0, "max_hold_s": 0.0,
        }
    return s


class _TracedLock:
    """Wrapper around a raw lock: held-set + order + contention
    recording.  The plain-Lock variant; deliberately does NOT define
    ``_release_save``/``_acquire_restore``/``_is_owned`` so
    ``threading.Condition`` falls back to acquire/release on it."""

    _reentrant = False

    def __init__(self, inner, name: str) -> None:
        self._inner = inner
        self._name = name
        self._nwait = 0

    # -- core bookkeeping ----------------------------------------------
    def _on_acquired(self, waited: float) -> None:
        stack = _held_stack()
        if self._reentrant:
            for h in stack:
                if h.wrapper is self:
                    h.depth += 1
                    with _state:
                        s = _stat(self._name)
                        s["acquisitions"] += 1
                    return
        now = time.perf_counter()
        new_edges = []
        for h in stack:
            if h.wrapper is not self:
                key = (h.wrapper._name, self._name)
                if key not in _edges:
                    new_edges.append(key)
        with _state:
            s = _stat(self._name)
            s["acquisitions"] += 1
            s["total_wait_s"] += waited
            if waited > s["max_wait_s"]:
                s["max_wait_s"] = waited
            for key in new_edges:
                # first sighting of an order edge keeps the witness
                _edges.setdefault(key, {
                    "thread": threading.current_thread().name,
                    "stack": _short_stack(skip=3),
                })
        stack.append(_Held(self, now))

    def _on_release(self) -> None:
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            h = stack[i]
            if h.wrapper is self:
                if self._reentrant and h.depth > 1:
                    h.depth -= 1
                    return
                hold = time.perf_counter() - h.t_acq
                del stack[i]
                with _state:
                    s = _stat(self._name)
                    if hold > s["max_hold_s"]:
                        s["max_hold_s"] = hold
                    if hold > _hold_ceiling_s and self._nwait > 0:
                        _violations.append({
                            "lock": self._name,
                            "hold_s": round(hold, 4),
                            "ceiling_s": _hold_ceiling_s,
                            "waiters": self._nwait,
                            "thread": threading.current_thread().name,
                            "stack": _short_stack(skip=3),
                        })
                return

    # -- lock protocol --------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        t0 = time.perf_counter()
        with _state:
            self._nwait += 1
        try:
            got = self._inner.acquire(blocking, timeout)
        finally:
            with _state:
                self._nwait -= 1
        if got:
            self._on_acquired(time.perf_counter() - t0)
        return got

    def release(self) -> None:
        self._on_release()
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<locktrace {self._name} of {self._inner!r}>"


class _TracedRLock(_TracedLock):
    """RLock variant: reentrancy-aware, and Condition-compatible via
    ``_release_save``/``_acquire_restore``/``_is_owned`` (Condition.wait
    fully releases the lock — the held-set must drop the entry and
    restore it with its depth on wakeup)."""

    _reentrant = True

    def _release_save(self):
        stack = _held_stack()
        depth = 1
        for i in range(len(stack) - 1, -1, -1):
            if stack[i].wrapper is self:
                depth = stack[i].depth
                hold = time.perf_counter() - stack[i].t_acq
                del stack[i]
                with _state:
                    s = _stat(self._name)
                    if hold > s["max_hold_s"]:
                        s["max_hold_s"] = hold
                break
        return (self._inner._release_save(), depth)

    def _acquire_restore(self, state):
        inner_state, depth = state
        self._inner._acquire_restore(inner_state)
        h = _Held(self, time.perf_counter())
        h.depth = depth
        _held_stack().append(h)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


def _lock_factory():
    return _TracedLock(_ORIG_LOCK(), _site_name())


def _rlock_factory():
    return _TracedRLock(_ORIG_RLOCK(), _site_name())


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def arm(hold_ceiling_s: Optional[float] = None) -> None:
    """Patch the ``threading.Lock``/``RLock`` factories; idempotent.
    Locks created from here on are traced."""
    global _armed, _hold_ceiling_s
    if hold_ceiling_s is not None:
        _hold_ceiling_s = float(hold_ceiling_s)
    if _armed:
        return
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    _armed = True


def disarm() -> None:
    """Restore the raw factories (recorded data is kept — call
    :func:`reset` to drop it).  Already-created traced locks keep
    working; they just stop being joined by new ones."""
    global _armed
    threading.Lock = _ORIG_LOCK
    threading.RLock = _ORIG_RLOCK
    _armed = False


def is_armed() -> bool:
    return _armed


def arm_from_env(env: str = "DGEN_TPU_LOCKTRACE") -> bool:
    """Arm when ``$DGEN_TPU_LOCKTRACE`` is a truthy value ("", "0",
    "false" hold fire); ceiling override via
    ``$DGEN_TPU_LOCKTRACE_HOLD_S``.  Returns whether armed."""
    val = os.environ.get(env, "").strip().lower()
    if val in ("", "0", "false", "no"):
        return False
    ceiling = os.environ.get(f"{env}_HOLD_S")
    arm(float(ceiling) if ceiling else None)
    return True


def reset() -> None:
    """Drop all recorded edges/stats/violations (stays armed)."""
    with _state:
        _edges.clear()
        _stats.clear()
        del _violations[:]


def stats() -> Dict[str, dict]:
    """Per-named-lock ``{acquisitions, total_wait_s, max_wait_s,
    max_hold_s}`` (names are creation sites, ``file.py:lineno``)."""
    with _state:
        return {
            k: dict(v, total_wait_s=round(v["total_wait_s"], 6),
                    max_wait_s=round(v["max_wait_s"], 6),
                    max_hold_s=round(v["max_hold_s"], 6))
            for k, v in sorted(_stats.items())
        }


def order_edges() -> List[Tuple[str, str]]:
    with _state:
        return sorted(_edges.keys())


def _find_cycle() -> Optional[List[str]]:
    """One cycle in the observed order graph (DFS back-edge), as the
    node list ``[a, b, ..., a]``; None when acyclic."""
    graph: Dict[str, List[str]] = {}
    for a, b in order_edges():
        graph.setdefault(a, []).append(b)
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}
    path: List[str] = []

    def dfs(n: str) -> Optional[List[str]]:
        color[n] = GREY
        path.append(n)
        for m in graph.get(n, ()):  # noqa: B023 — local recursion
            c = color.get(m, WHITE)
            if c == GREY:
                return path[path.index(m):] + [m]
            if c == WHITE:
                found = dfs(m)
                if found:
                    return found
        path.pop()
        color[n] = BLACK
        return None

    for n in list(graph):
        if color.get(n, WHITE) == WHITE:
            found = dfs(n)
            if found:
                return found
    return None


def check() -> dict:
    """The sentinel's verdict: ``ok`` is False on any observed
    lock-order cycle or hold violation; the report carries the witness
    (thread, stack, lock names) for each."""
    cycle = _find_cycle()
    witnesses = []
    if cycle:
        with _state:
            for a, b in zip(cycle, cycle[1:]):
                w = _edges.get((a, b))
                if w:
                    witnesses.append({"edge": [a, b], **w})
    with _state:
        violations = [dict(v) for v in _violations]
    return {
        "ok": cycle is None and not violations,
        "armed": _armed,
        "hold_ceiling_s": _hold_ceiling_s,
        "cycle": cycle,
        "cycle_witnesses": witnesses,
        "hold_violations": violations,
        "locks": stats(),
        "n_edges": len(order_edges()),
    }


def format_report(report: dict) -> str:
    """Human lines for a failing :func:`check` (drill logs)."""
    lines: List[str] = []
    if report.get("cycle"):
        lines.append(
            "locktrace: LOCK-ORDER CYCLE " + " -> ".join(report["cycle"])
        )
        for w in report.get("cycle_witnesses", ()):
            a, b = w["edge"]
            lines.append(f"  edge {a} -> {b}  [thread {w['thread']}]")
            for fr in w.get("stack", ()):
                lines.append(f"    {fr}")
    for v in report.get("hold_violations", ()):
        lines.append(
            f"locktrace: HOLD VIOLATION {v['lock']} held "
            f"{v['hold_s']}s > {v['ceiling_s']}s with {v['waiters']} "
            f"waiter(s)  [thread {v['thread']}]"
        )
        for fr in v.get("stack", ()):
            lines.append(f"    {fr}")
    return "\n".join(lines)
