"""Version-tolerant wrappers for jax APIs that moved between releases.

The codebase targets the current jax API surface (``jax.shard_map``,
the ``jax_num_cpu_devices`` config); older toolchains (0.4.x) spell
these ``jax.experimental.shard_map.shard_map(check_rep=...)`` and
``XLA_FLAGS=--xla_force_host_platform_device_count``. Everything that
needs one of these goes through here so the fallback logic lives in
exactly one place.
"""

from __future__ import annotations

import os

import jax


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` when available, else the 0.4.x
    ``jax.experimental.shard_map.shard_map`` (where ``check_vma`` is
    spelled ``check_rep``)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` when available; older
    toolchains expose the same fact via the distributed global state."""
    if hasattr(jax.distributed, "is_initialized"):
        return bool(jax.distributed.is_initialized())
    try:
        from jax._src import distributed

        return distributed.global_state.client is not None
    except Exception:  # noqa: BLE001 — absent module = not initialized
        return False


def set_cpu_device_count(n: int) -> None:
    """Request ``n`` virtual CPU devices.

    Uses the ``jax_num_cpu_devices`` config when this jax has it;
    otherwise falls back to ``--xla_force_host_platform_device_count``,
    which only takes effect if the CPU backend has not been initialized
    yet (callers run this at process start, before any computation).
    """
    try:
        jax.config.update("jax_num_cpu_devices", n)
        return
    except AttributeError:
        pass
    flag = f"--xla_force_host_platform_device_count={n}"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " " + flag).strip()
