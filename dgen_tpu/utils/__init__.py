"""Host-side utilities: logging, timing/tracing, and the pytree
invariant harness (the analogue of the reference's runtime-test safety
net, agents.py:149-262)."""

from dgen_tpu.utils import invariants, logging, timing  # noqa: F401
