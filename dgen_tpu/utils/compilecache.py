"""Persistent XLA compilation cache wiring.

The month-blocked sizing kernels compile in ~80-170 s/program on the
TPU backend, and a cold national run pays ~170 s of compilation before
its first step (BENCH_r04 trace).  JAX's persistent compilation cache
eliminates that on every process after the first: compiled executables
are serialized to disk keyed by (HLO, compile options, backend), and a
later process deserializes in ~10 ms instead of recompiling.  The
reference has no analogue — its PySAM/Postgres engine is interpreted —
so this is pure TPU-side win; the equivalent of what its operators get
from long-lived worker pools (dgen_model.py keeps one pool per task,
never paying per-run process start).

Call :func:`enable` once per process before building simulations; it is
idempotent, keys entries by backend (CPU test entries never collide
with TPU ones), and refuses to engage on multi-process CPU (gloo)
backends where asymmetric cache hits deadlock the first collective
(see :func:`enable`).  Knobs:

  DGEN_TPU_CACHE_DIR   cache directory (default <repo>/.jax_cache;
                       "0"/"off" disables)
"""

from __future__ import annotations

import os
from typing import Optional

_enabled_dir: Optional[str] = None

_DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".jax_cache",
)


def cache_dir() -> Optional[str]:
    """The resolved cache directory, or None when disabled."""
    raw = os.environ.get("DGEN_TPU_CACHE_DIR", _DEFAULT_DIR).strip()
    if raw.lower() in ("", "0", "off", "none"):
        return None
    return raw


def enable() -> Optional[str]:
    """Turn on the persistent compilation cache; returns the directory
    in use (None = disabled).  Idempotent.

    Refuses on multi-process CPU (gloo) backends: processes there must
    compile SYMMETRICALLY — one process hitting the cache reaches the
    first collective while its peer is still compiling, gloo's fixed
    30 s key-value rendezvous times out, and the coordination service
    kills the peer (no jax knob raises that timeout).  TPU multihost
    keeps the cache; its collectives rendezvous through the
    coordination service's own, much longer barriers.  The probe only
    touches the backend when jax.distributed is already initialized,
    so import-time callers don't trigger backend bring-up."""
    global _enabled_dir
    d = cache_dir()
    if d is None:
        # flipping DGEN_TPU_CACHE_DIR off mid-process must actually
        # disarm a previously-enabled cache, not report it as active
        disable()
        return None
    if _enabled_dir == d:
        return _enabled_dir
    import jax

    from dgen_tpu.utils import compat

    if (
        compat.distributed_is_initialized()
        and jax.process_count() > 1
        and jax.default_backend() == "cpu"
    ):
        return None

    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", d)
    # the default 1 s floor would skip small programs whose *remote*
    # compile round-trip is still expensive on the tunneled backend
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _enabled_dir = d
    return d


def disable() -> None:
    """Turn the persistent cache back off for this process (clears the
    jax config; on-disk entries are untouched)."""
    global _enabled_dir
    if _enabled_dir is None:
        return
    import jax

    jax.config.update("jax_compilation_cache_dir", None)
    _enabled_dir = None


def ensure_safe_for_backend() -> None:
    """Re-check the gloo refusal AFTER distributed bring-up.

    :func:`enable` can only refuse when jax.distributed is already
    initialized, but several entry points enable the cache at module
    import — before any ``initialize()``.  Call this right after
    ``jax.distributed.initialize`` (``parallel.launch`` does) to
    disable a cache that import-time enabling armed on a
    multi-process CPU (gloo) backend."""
    import jax

    from dgen_tpu.utils import compat

    if (
        _enabled_dir is not None
        and compat.distributed_is_initialized()
        and jax.process_count() > 1
        and jax.default_backend() == "cpu"
    ):
        disable()


class HitCounter:
    """Counts persistent-cache hits/misses inside a ``with`` region via
    jax.monitoring events (one ``cache_hits`` event per deserialized
    executable; one ``compile_requests_use_cache`` per compile request
    that consulted the cache — misses are the difference).

    The serving fleet's boot report uses this to *prove* shared-cache
    fast boot: a replica whose warmup reports ``hits == requests``
    compiled nothing, it deserialized its bucket programs from the
    cache a sibling (or a previous incarnation) populated."""

    def __init__(self) -> None:
        self.hits = 0
        self.requests = 0

    @property
    def misses(self) -> int:
        return max(self.requests - self.hits, 0)

    def __enter__(self) -> "HitCounter":
        def _cb(event: str, **kw) -> None:
            if event == "/jax/compilation_cache/cache_hits":
                self.hits += 1
            elif event == "/jax/compilation_cache/compile_requests_use_cache":
                self.requests += 1

        from jax._src import monitoring

        self._cb = _cb
        monitoring.register_event_listener(_cb)
        return self

    def __exit__(self, *exc) -> None:
        from jax._src import monitoring

        try:
            monitoring._unregister_event_listener_by_callback(self._cb)
        except ValueError:  # already gone (test teardown ordering)
            pass

    def to_json(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "requests": self.requests}


def stats() -> dict:
    """Entry count / bytes of the active cache (for meta.json stamps)."""
    d = _enabled_dir or cache_dir()
    if not d or not os.path.isdir(d):
        return {"dir": d, "entries": 0, "bytes": 0}
    entries = 0
    total = 0
    # concurrent processes write entries tmp-file-then-rename; a file
    # may vanish between listdir and stat, which must not crash the
    # run that is merely stamping provenance
    for n in os.listdir(d):
        try:
            total += os.path.getsize(os.path.join(d, n))
            entries += 1
        except OSError:
            continue
    return {"dir": d, "entries": entries, "bytes": total}
