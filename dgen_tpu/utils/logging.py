"""Structured logging (analogue of reference utility_functions.py:36
``get_logger`` + the ``[BATCH_STATE]`` stateful adapter at :24-34)."""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional

_LOGGER_NAME = "dgen_tpu"


def get_logger(prefix: Optional[str] = None) -> logging.Logger:
    """Process-wide logger; ``prefix`` (e.g. a shard/state tag) is added
    to every record so interleaved multi-host logs stay attributable,
    mirroring the reference's ``BATCH_STATE`` adapter."""
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
        logger.addHandler(handler)
        level = os.environ.get("DGEN_TPU_LOGLEVEL", "INFO").upper()
        logger.setLevel(getattr(logging, level, logging.INFO))
        logger.propagate = False
    if prefix:
        return logging.LoggerAdapter(logger, {})  # type: ignore[return-value]
    return logger
