"""Tracing/profiling decorators (analogue of reference decorators.py:28
``fn_timer`` and utility_functions.py:112 ``Timer``) plus a fixed-bucket
log-spaced latency histogram for long-lived processes (the serving
engine's request-latency percentiles, ``dgen_tpu.serve``)."""

from __future__ import annotations

import functools
import math
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, List

from dgen_tpu.utils.logging import get_logger

#: accumulated (name -> [durations]) for the profiler report, the
#: in-memory analogue of the reference's ``code_profiler.csv`` scrape
#: (utility_functions.py:89-102).
_TIMINGS: Dict[str, List[float]] = {}


def fn_timer(tab_level: int = 0) -> Callable:
    """Decorator logging wall time per call and accumulating stats."""

    def deco(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            dt = time.perf_counter() - t0
            _TIMINGS.setdefault(fn.__qualname__, []).append(dt)
            get_logger().debug("%s%s took: %.3fs", "\t" * tab_level, fn.__qualname__, dt)
            return out

        return wrapper

    return deco


def _key(name: str, ctx: str | None) -> str:
    """Timer accumulation key: ``ctx:name`` when a context label is
    given, else the bare name. Sweeps label per-scenario phases (e.g.
    ``timer("year_step", ctx="scn3")``) so S scenarios' year steps do
    not collide in one global bucket."""
    return f"{ctx}:{name}" if ctx else name


@contextmanager
def timer(name: str, ctx: str | None = None):
    t0 = time.perf_counter()
    yield
    dt = time.perf_counter() - t0
    key = _key(name, ctx)
    _TIMINGS.setdefault(key, []).append(dt)
    get_logger().debug("%s took: %.3fs", key, dt)


# ---------------------------------------------------------------------------
# Fixed-bucket log-spaced histogram
# ---------------------------------------------------------------------------
#
# The per-call duration lists above are right for run drivers (tens of
# calls per phase) but wrong for a serving process answering millions of
# requests: an append-per-request list grows without bound. The
# histogram below is O(1) memory and O(1) record — 48 log-spaced
# buckets from 100 µs, each sqrt(2) wider than the last (~1.6e3 s at
# the top), which resolves percentiles to within ~±19% anywhere on the
# range. `/metricz` and the bench serve section read percentiles from
# here via :func:`timing_report`.

_HIST_MIN = 1e-4          # seconds: first bucket upper bound
_HIST_GROWTH = 2.0 ** 0.5
_HIST_N = 48

#: bucket upper bounds (seconds), shared by every histogram
HIST_BOUNDS: tuple = tuple(
    _HIST_MIN * _HIST_GROWTH ** i for i in range(_HIST_N)
)


class LogHistogram:
    """Fixed log-spaced-bucket histogram of nonnegative durations.

    ``counts[i]`` holds observations <= ``HIST_BOUNDS[i]`` (and greater
    than the previous bound); the final slot is the overflow bucket.
    Thread-safe: the serving batcher records from worker threads while
    `/metricz` reads from handler threads.
    """

    __slots__ = ("counts", "n", "total", "vmax", "_lock")

    def __init__(self) -> None:
        self.counts = [0] * (_HIST_N + 1)
        self.n = 0
        self.total = 0.0
        self.vmax = 0.0
        self._lock = threading.Lock()

    def record(self, seconds: float) -> None:
        v = max(float(seconds), 0.0)
        # log-index without a per-record scan: bound_i = MIN*GROWTH^i
        if v <= _HIST_MIN:
            i = 0
        else:
            i = min(
                int(math.ceil(math.log(v / _HIST_MIN) / math.log(_HIST_GROWTH))),
                _HIST_N,
            )
        with self._lock:
            self.counts[i] += 1
            self.n += 1
            self.total += v
            if v > self.vmax:
                self.vmax = v

    def percentile(self, q: float) -> float:
        """Value at quantile ``q`` in [0, 1]: the upper bound of the
        bucket containing the q-th observation (capped at the observed
        max, so a single-bucket histogram reports its true extreme)."""
        with self._lock:
            if not self.n:
                return 0.0
            target = q * self.n
            cum = 0
            for i, c in enumerate(self.counts):
                cum += c
                if cum >= target:
                    bound = HIST_BOUNDS[i] if i < _HIST_N else self.vmax
                    return min(bound, self.vmax)
            return self.vmax

    def snapshot(self) -> Dict[str, float]:
        """{count, total, mean, p50, p90, p99, max} summary."""
        with self._lock:
            n, total, vmax = self.n, self.total, self.vmax
        if not n:
            return {"count": 0, "total": 0.0, "mean": 0.0,
                    "p50": 0.0, "p90": 0.0, "p99": 0.0, "max": 0.0}
        return {
            "count": n, "total": total, "mean": total / n,
            "p50": self.percentile(0.50),
            "p90": self.percentile(0.90),
            "p99": self.percentile(0.99),
            "max": vmax,
        }


#: (key -> LogHistogram); same ``ctx:name`` keying as _TIMINGS
_HISTS: Dict[str, LogHistogram] = {}
_HISTS_LOCK = threading.Lock()


def observe(name: str, seconds: float, ctx: str | None = None) -> None:
    """Record one duration into the named histogram (O(1) memory —
    safe for per-request latencies in a long-lived server, unlike
    :func:`timer`'s per-call list)."""
    key = _key(name, ctx)
    h = _HISTS.get(key)
    if h is None:
        with _HISTS_LOCK:
            h = _HISTS.setdefault(key, LogHistogram())
    h.record(seconds)


def histogram(name: str, ctx: str | None = None) -> LogHistogram | None:
    """The named histogram, or None if nothing was observed yet."""
    return _HISTS.get(_key(name, ctx))


def timing_report(ctx: str | None = None) -> Dict[str, Dict[str, float]]:
    """Per-name {count, total, mean} summary; histogram'd names
    (:func:`observe`) instead carry the histogram's
    count/total/mean/p50/p90/p99/max. A name recorded through BOTH
    :func:`timer` and :func:`observe` reports the histogram (observe
    shadows timer for that key) — instrument one phase under two
    distinct names if both views are needed. ``ctx`` filters to one
    context's timers (keys come back with the ``ctx:`` prefix
    stripped, i.e. as the bare phase names recorded under it)."""
    def _select(items):
        if ctx is None:
            return list(items)
        prefix = f"{ctx}:"
        return [
            (k[len(prefix):], v) for k, v in items if k.startswith(prefix)
        ]

    out = {
        k: {"count": len(v), "total": sum(v), "mean": sum(v) / len(v)}
        for k, v in _select(_TIMINGS.items())
        if v
    }
    for k, h in _select(list(_HISTS.items())):
        snap = h.snapshot()
        if snap["count"]:
            out[k] = snap
    return out


def reset_timings() -> None:
    _TIMINGS.clear()
    with _HISTS_LOCK:
        _HISTS.clear()
