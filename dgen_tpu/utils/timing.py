"""Tracing/profiling decorators (analogue of reference decorators.py:28
``fn_timer`` and utility_functions.py:112 ``Timer``)."""

from __future__ import annotations

import functools
import time
from contextlib import contextmanager
from typing import Callable, Dict, List

from dgen_tpu.utils.logging import get_logger

#: accumulated (name -> [durations]) for the profiler report, the
#: in-memory analogue of the reference's ``code_profiler.csv`` scrape
#: (utility_functions.py:89-102).
_TIMINGS: Dict[str, List[float]] = {}


def fn_timer(tab_level: int = 0) -> Callable:
    """Decorator logging wall time per call and accumulating stats."""

    def deco(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            dt = time.perf_counter() - t0
            _TIMINGS.setdefault(fn.__qualname__, []).append(dt)
            get_logger().debug("%s%s took: %.3fs", "\t" * tab_level, fn.__qualname__, dt)
            return out

        return wrapper

    return deco


def _key(name: str, ctx: str | None) -> str:
    """Timer accumulation key: ``ctx:name`` when a context label is
    given, else the bare name. Sweeps label per-scenario phases (e.g.
    ``timer("year_step", ctx="scn3")``) so S scenarios' year steps do
    not collide in one global bucket."""
    return f"{ctx}:{name}" if ctx else name


@contextmanager
def timer(name: str, ctx: str | None = None):
    t0 = time.perf_counter()
    yield
    dt = time.perf_counter() - t0
    key = _key(name, ctx)
    _TIMINGS.setdefault(key, []).append(dt)
    get_logger().debug("%s took: %.3fs", key, dt)


def timing_report(ctx: str | None = None) -> Dict[str, Dict[str, float]]:
    """Per-name {count, total, mean} summary. ``ctx`` filters to one
    context's timers (keys come back with the ``ctx:`` prefix
    stripped, i.e. as the bare phase names recorded under it)."""
    if ctx is None:
        items = _TIMINGS.items()
    else:
        prefix = f"{ctx}:"
        items = (
            (k[len(prefix):], v) for k, v in _TIMINGS.items()
            if k.startswith(prefix)
        )
    return {
        k: {"count": len(v), "total": sum(v), "mean": sum(v) / len(v)}
        for k, v in items
        if v
    }


def reset_timings() -> None:
    _TIMINGS.clear()
