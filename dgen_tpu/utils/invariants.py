"""Pytree invariant harness — the TPU analogue of the reference's
runtime dataframe tests (agents.py:149-262 ``run_with_runtime_tests``),
which check after every agent-table transform that no columns were
dropped, no NaNs appeared, row count/ids are unchanged, and dtypes
didn't drift.

Here the agent table is a pytree of fixed-schema dense arrays, so most
of those failure modes are impossible by construction; what remains
worth checking after each year step is: leaf set unchanged, shapes
unchanged on the agent axis, dtypes unchanged, and no non-finite values
in updated leaves (with an allowlist, mirroring config.py:50-53).
"""

from __future__ import annotations

from typing import Iterable, Optional, Set

import jax
import numpy as np


class InvariantViolation(AssertionError):
    pass


def _leaf_paths(tree) -> dict:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {jax.tree_util.keystr(path): leaf for path, leaf in flat}


def check_transform(
    before,
    after,
    allow_nonfinite: Optional[Iterable[str]] = None,
    context: str = "",
) -> None:
    """Validate an agent-table transform preserved the schema.

    ``allow_nonfinite``: leaf-path substrings exempt from the finiteness
    check (the reference keeps a similar exception list for columns that
    legitimately carry NaNs, config.py:50-53).
    """
    allow: Set[str] = set(allow_nonfinite or ())
    b = _leaf_paths(before)
    a = _leaf_paths(after)

    missing = set(b) - set(a)
    added = set(a) - set(b)
    if missing or added:
        raise InvariantViolation(
            f"{context}: leaf set changed (missing={sorted(missing)}, added={sorted(added)})"
        )
    for path, leaf_b in b.items():
        leaf_a = a[path]
        if getattr(leaf_b, "shape", None) != getattr(leaf_a, "shape", None):
            raise InvariantViolation(
                f"{context}: shape of {path} changed {leaf_b.shape} -> {leaf_a.shape}"
            )
        if getattr(leaf_b, "dtype", None) != getattr(leaf_a, "dtype", None):
            raise InvariantViolation(
                f"{context}: dtype of {path} changed {leaf_b.dtype} -> {leaf_a.dtype}"
            )


def nonfinite_rows(arr, k: int = 8) -> np.ndarray:
    """First ``k`` leading-axis (agent) indices holding any non-finite
    value — the attribution primitive shared by :func:`check_finite`'s
    error messages and the health sentinel's narrowing step
    (``dgen_tpu.models.health``)."""
    a = np.asarray(arr)
    if a.ndim == 0:
        return np.asarray([0] if not np.isfinite(a) else [],
                          dtype=np.int64)
    bad = ~np.isfinite(a.reshape(a.shape[0], -1)).all(axis=1)
    return np.flatnonzero(bad)[:k]


def check_finite(tree, allow_nonfinite: Optional[Iterable[str]] = None,
                 context: str = "", top_k: int = 8) -> None:
    """Assert every float leaf is finite (allowlist by path substring);
    violations name the first ``top_k`` offending *agent indices*
    (leading-axis rows), not just the leaf path, so a failure is
    attributable without a rerun.

    Host-side check — call sparingly (it syncs device values)."""
    allow = tuple(allow_nonfinite or ())
    for path, leaf in _leaf_paths(tree).items():
        if any(s in path for s in allow):
            continue
        arr = np.asarray(leaf)
        if arr.dtype.kind == "f" and not np.isfinite(arr).all():
            n_bad = int((~np.isfinite(arr)).sum())
            rows = nonfinite_rows(arr, k=top_k).tolist()
            raise InvariantViolation(
                f"{context}: {n_bad} non-finite values in {path} "
                f"(first offending agent rows: {rows})"
            )
