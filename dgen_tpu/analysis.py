"""Post-run analysis passes the sizing hot loop deliberately skips.

The reference disables demand charges globally in its adoption loop
(``SKIP_DEMAND_CHARGES``, financial_functions.py:35) but its tariff
layer can price them (tariff_functions.py:762-799). Here the same
split: the sizing kernels never price demand, and this module offers
the analysis-run path — annual per-agent demand charges over the
baseline / PV-only / PV+battery net loads of a converted population
whose tariffs carry ``d_flat_*`` / ``d_tou_*`` structures
(io.convert preserves them as each tariff spec's ``"demand"``
sub-spec).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp

from dgen_tpu.models.agents import AgentTable, ProfileBank
from dgen_tpu.ops import demand as demand_ops
from dgen_tpu.ops import dispatch as dispatch_ops
from dgen_tpu.ops.sizing import INV_EFF, net_hourly_profiles


def demand_charge_audit(
    table: AgentTable,
    profiles: ProfileBank,
    tariff_specs: Sequence[dict],
    load_kwh_per_customer: jax.Array,
    system_kw: Optional[jax.Array] = None,
    batt_kw: Optional[jax.Array] = None,
    batt_kwh: Optional[jax.Array] = None,
    batt_rt_eff: Optional[jax.Array] = None,
) -> Optional[Dict[str, jax.Array]]:
    """Annual demand charges ($/customer-year) per agent and scenario.

    Returns ``{"baseline": [N], "pv_only": [N], "with_batt": [N]}``
    (the latter two only when sizes are given; agents whose tariff has
    no demand charges price 0), or None when NO tariff in the corpus
    carries demand structures — the adoption-loop norm (reference
    SKIP_DEMAND_CHARGES, financial_functions.py:35).

    ``system_kw`` etc. are typically a run's sized outputs
    (``YearOutputs.system_kw`` / ``batt_kw`` / ``batt_kwh``); net loads
    are rebuilt exactly as the sizing kernel's hourly outputs
    (ops.sizing.net_hourly_profiles), so the audit prices the same
    profiles the adoption model aggregated.
    """
    bank = demand_ops.compile_demand_bank(
        [s.get("demand") for s in tariff_specs]
    )
    if bank is None:
        return None
    at = jax.tree.map(lambda x: x[table.tariff_idx], bank)

    load = profiles.load[table.load_idx] * load_kwh_per_customer[:, None]
    charge = jax.vmap(demand_ops.annual_demand_charge)

    out: Dict[str, jax.Array] = {
        "baseline": charge(load, at) * table.mask,
    }
    if system_kw is None:
        return out
    gen = profiles.solar_cf[table.cf_idx] * (system_kw * INV_EFF)[:, None]
    _, pv_net, _ = net_hourly_profiles(load, gen, gen)
    out["pv_only"] = charge(pv_net, at) * table.mask
    if batt_kw is not None and batt_kwh is not None:
        rt = (
            jnp.full(table.n_agents, dispatch_ops.DEFAULT_RT_EFF,
                     jnp.float32)
            if batt_rt_eff is None else batt_rt_eff
        )
        dr = jax.vmap(dispatch_ops.dispatch_battery)(
            load, gen, batt_kw, batt_kwh, rt
        )
        _, _, batt_net = net_hourly_profiles(load, gen, dr.system_out)
        out["with_batt"] = charge(batt_net, at) * table.mask
    return out
