"""Post-run analysis passes the sizing hot loop deliberately skips.

The reference disables demand charges globally in its adoption loop
(``SKIP_DEMAND_CHARGES``, financial_functions.py:35) but its tariff
layer can price them (tariff_functions.py:762-799). Here the same
split: the sizing kernels never price demand, and this module offers
the analysis-run path — annual per-agent demand charges over the
baseline / PV-only / PV+battery net loads of a converted population
whose tariffs carry ``d_flat_*`` / ``d_tou_*`` structures
(io.convert preserves them as each tariff spec's ``"demand"``
sub-spec).

It also carries the dispatch observability surface
(:func:`dispatch_diagnostics`) — the analyst tool the reference prints
per run (``dispatch_export_diags``, batt_dispatch_helpers.py:103-336):
midday PV-surplus capture, energy routing totals, charge-power vs SOC
bottleneck hours, and sell/buy-rate revenue splits — vectorized over
the whole agent table instead of printed one agent at a time.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp

from dgen_tpu.models.agents import AgentTable, ProfileBank
from dgen_tpu.ops import demand as demand_ops
from dgen_tpu.ops import dispatch as dispatch_ops
from dgen_tpu.ops.sizing import INV_EFF, net_hourly_profiles


def demand_charge_audit(
    table: AgentTable,
    profiles: ProfileBank,
    tariff_specs: Sequence[dict],
    load_kwh_per_customer: jax.Array,
    system_kw: Optional[jax.Array] = None,
    batt_kw: Optional[jax.Array] = None,
    batt_kwh: Optional[jax.Array] = None,
    batt_rt_eff: Optional[jax.Array] = None,
) -> Optional[Dict[str, jax.Array]]:
    """Annual demand charges ($/customer-year) per agent and scenario.

    Returns ``{"baseline": [N], "pv_only": [N], "with_batt": [N]}``
    (the latter two only when sizes are given; agents whose tariff has
    no demand charges price 0), or None when NO tariff in the corpus
    carries demand structures — the adoption-loop norm (reference
    SKIP_DEMAND_CHARGES, financial_functions.py:35).

    ``system_kw`` etc. are typically a run's sized outputs
    (``YearOutputs.system_kw`` / ``batt_kw`` / ``batt_kwh``); net loads
    are rebuilt exactly as the sizing kernel's hourly outputs
    (ops.sizing.net_hourly_profiles), so the audit prices the same
    profiles the adoption model aggregated.
    """
    bank = demand_ops.compile_demand_bank(
        [s.get("demand") for s in tariff_specs]
    )
    if bank is None:
        return None
    at = jax.tree.map(lambda x: x[table.tariff_idx], bank)

    load = profiles.load[table.load_idx] * load_kwh_per_customer[:, None]
    charge = jax.vmap(demand_ops.annual_demand_charge)

    out: Dict[str, jax.Array] = {
        "baseline": charge(load, at) * table.mask,
    }
    if system_kw is None:
        return out
    gen = profiles.solar_cf[table.cf_idx] * (system_kw * INV_EFF)[:, None]
    _, pv_net, _ = net_hourly_profiles(load, gen, gen)
    out["pv_only"] = charge(pv_net, at) * table.mask
    if batt_kw is not None and batt_kwh is not None:
        rt = (
            jnp.full(table.n_agents, dispatch_ops.DEFAULT_RT_EFF,
                     jnp.float32)
            if batt_rt_eff is None else batt_rt_eff
        )
        dr = jax.vmap(dispatch_ops.dispatch_battery)(
            load, gen, batt_kw, batt_kwh, rt
        )
        _, _, batt_net = net_hourly_profiles(load, gen, dr.system_out)
        out["with_batt"] = charge(batt_net, at) * table.mask
    return out


def dispatch_diagnostics(
    load: jax.Array,            # [N, 8760] kWh/h
    gen: jax.Array,             # [N, 8760] PV output kWh/h
    dr,                         # DispatchResult (leaves [N, 8760])
    sell: jax.Array,            # [N, 8760] $/kWh sell rate
    buy: Optional[jax.Array] = None,   # [N, 8760] $/kWh buy rate
    batt_kw: Optional[jax.Array] = None,
    midday_hours: tuple = (11, 15),
    night_eps: float = 1e-6,
) -> Dict[str, jax.Array]:
    """Per-agent dispatch/export diagnostics (all values [N]).

    The table-level analogue of the reference's per-agent printout
    (``dispatch_export_diags``, batt_dispatch_helpers.py:103-336):
    midday PV-surplus capture fraction, energy routing totals
    (PV->batt / PV->grid / PV->load / batt->load), charge-power-bound
    vs SOC-bound hour counts, day/night sell-rate means, export revenue
    and avoided retail spend. Differences by design: this framework's
    greedy self-consumption dispatch (ops.dispatch) never routes
    battery->grid or grid->battery, so those reference columns are
    identically zero and omitted.

    ``batt_kw`` defaults to the observed maximum of ``dr``'s charge
    trace; it only sets the power-bound classification threshold.
    """
    hod = jnp.arange(load.shape[1]) % 24
    midday = (hod >= midday_hours[0]) & (hod <= midday_hours[1])
    night = gen < night_eps                                  # [N, H]

    surplus = jnp.maximum(gen - load, 0.0)
    s2b = dr.charge                                          # PV -> batt
    b2l = dr.discharge                                       # batt -> load
    # meter-level exports of the battery-modified system output
    s2g = jnp.maximum(dr.system_out - load, 0.0)
    s2l = jnp.maximum(jnp.minimum(gen - s2b, load), 0.0)     # PV direct

    msum = lambda x, m: jnp.sum(x * m[None, :], axis=1) if m.ndim == 1 \
        else jnp.sum(x * m, axis=1)
    tot = lambda x: jnp.sum(x, axis=1)

    surplus_mid = msum(surplus, midday)
    s2b_mid = msum(s2b, midday)
    capture_mid = jnp.where(surplus_mid > 1e-9, s2b_mid / surplus_mid, 0.0)

    # bottlenecks: hours whose surplus the battery did NOT fully
    # absorb, split by observed cause — the charge trace hit the power
    # cap, or (otherwise) energy headroom ran out. Cause-accurate where
    # the reference classifies by SOC threshold alone
    # (batt_dispatch_helpers.py:216-222).
    if batt_kw is None:
        batt_kw = jnp.max(s2b, axis=1)                       # observed cap
    unabsorbed = (surplus - s2b) > 1e-6
    power_bound = unabsorbed & (s2b >= batt_kw[:, None] * (1 - 1e-5))
    soc_bound = unabsorbed & ~power_bound
    day = ~night

    out: Dict[str, jax.Array] = {
        "surplus_total_kwh": tot(surplus),
        "surplus_mid_kwh": surplus_mid,
        "pv_to_batt_total_kwh": tot(s2b),
        "pv_to_batt_mid_kwh": s2b_mid,
        "pv_to_grid_total_kwh": tot(s2g),
        "pv_to_grid_mid_kwh": msum(s2g, midday),
        "pv_direct_to_load_total_kwh": tot(s2l),
        "batt_to_load_kwh": tot(b2l),
        "capture_mid_frac": capture_mid,
        "power_bound_hours": jnp.sum(power_bound, axis=1),
        "soc_bound_hours": jnp.sum(soc_bound, axis=1),
        "power_bound_mid_hours": msum(power_bound, midday),
        "soc_bound_mid_hours": msum(soc_bound, midday),
        "sell_mean_day": jnp.sum(sell * day, axis=1)
        / jnp.maximum(jnp.sum(day, axis=1), 1),
        "sell_mean_night": jnp.sum(sell * night, axis=1)
        / jnp.maximum(jnp.sum(night, axis=1), 1),
        "pv_export_revenue_usd": tot(s2g * sell),
        "pv_export_revenue_mid_usd": msum(s2g * sell, midday),
    }
    if buy is not None:
        out["avoided_pv_self_usd"] = tot(s2l * buy)
        out["avoided_batt_self_usd"] = tot(b2l * buy)
    return out


def summarize_dispatch(diags: Dict[str, jax.Array], mask) -> Dict[str, float]:
    """Population roll-up of :func:`dispatch_diagnostics` (the concise
    per-run stats block the reference prints): kWh/$ fields sum over
    real agents; fractions and rates are surplus- or agent-weighted
    means."""
    import numpy as np

    m = np.asarray(mask) > 0
    d = {k: np.asarray(v)[m] for k, v in diags.items()}
    w = d["surplus_mid_kwh"]
    out = {}
    for k, v in d.items():
        if k.endswith("_kwh") or k.endswith("_usd") or "hours" in k:
            out[k] = float(v.sum())
        elif k == "capture_mid_frac":
            out[k] = float((v * w).sum() / max(w.sum(), 1e-9))
        else:
            out[k] = float(v.mean())
    return out
