"""The five BASELINE.json benchmark configurations as runnable presets.

The reference pins its headline numbers to five named configs
(BASELINE.json "configs"; the operator reproduces them through
submit_all.sh + the scenario workbook). Here each is one command:

    python -m dgen_tpu.presets delaware-res
    python -m dgen_tpu.presets national-all-sector --agents 1048576

Populations are synthetic (the reference's real agent pickles live only
in its Postgres dump) at the config's scale and sector mix; scenario
trajectories come from the reference's own input_data CSVs when the
mount exists (io.reference_inputs), else the uniform synthetic
defaults — the run's meta.json says which.

Each run prints a per-phase breakdown (build / compile / steps /
exports) and a final one-line JSON so bench.py and operators consume
the same machinery (``run_preset``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Callable, Dict, Optional

import numpy as np

REFERENCE_INPUT_ROOT = "/root/reference/dgen_os/input_data"


@dataclasses.dataclass(frozen=True)
class Preset:
    """One BASELINE.json config as a buildable simulation."""

    name: str
    baseline_config: str       # the BASELINE.json "configs" line
    states: Optional[list]     # None = all states
    sector_weights: tuple
    start_year: int
    end_year: int
    storage_enabled: bool
    with_hourly: bool
    default_agents: int
    load_growth_scenario: Optional[str] = None  # substring of the CSV


PRESETS: Dict[str, Preset] = {p.name: p for p in (
    Preset(
        name="delaware-res",
        baseline_config="Delaware residential solar-only, 2014–2024 default scenario (small_states single-state)",
        states=["DE"], sector_weights=(1.0, 0.0, 0.0),
        start_year=2014, end_year=2024,
        storage_enabled=False, with_hourly=True, default_agents=1024,
    ),
    Preset(
        name="california-res-com",
        baseline_config="California residential + commercial solar, default ATB cost trajectory",
        states=["CA"], sector_weights=(0.7, 0.3, 0.0),
        start_year=2014, end_year=2040,
        storage_enabled=False, with_hourly=True, default_agents=8192,
    ),
    Preset(
        name="ercot-all-sector",
        baseline_config="ERCOT ISO all-sector solar+storage (battery dispatch on, NEM tariffs)",
        states=["TX"], sector_weights=(0.6, 0.3, 0.1),
        start_year=2014, end_year=2040,
        storage_enabled=True, with_hourly=True, default_agents=8192,
    ),
    Preset(
        name="national-res",
        baseline_config="National residential solar, 2014–2050 biennial, all states sharded over pod",
        states=None, sector_weights=(1.0, 0.0, 0.0),
        start_year=2014, end_year=2050,
        storage_enabled=False, with_hourly=False, default_agents=65536,
    ),
    Preset(
        name="national-all-sector",
        baseline_config="National all-sector solar+storage, high-electrification load-growth scenario",
        states=None, sector_weights=(0.7, 0.2, 0.1),
        start_year=2014, end_year=2050,
        storage_enabled=True, with_hourly=True, default_agents=1048576,
        load_growth_scenario="Experimental",
    ),
)}


def build(
    name: str,
    n_agents: Optional[int] = None,
    input_root: Optional[str] = None,
    run_config=None,
    mesh=None,
):
    """(Simulation, population, meta) for a named preset."""
    from dgen_tpu.utils import compilecache

    cache_d = compilecache.enable()

    import jax.numpy as jnp

    from dgen_tpu.config import RunConfig, ScenarioConfig
    from dgen_tpu.io import synth
    from dgen_tpu.io.reference_inputs import (
        scenario_inputs_from_reference,
        wholesale_profile_bank,
    )
    from dgen_tpu.models import scenario as scen
    from dgen_tpu.models.agents import ProfileBank
    from dgen_tpu.models.simulation import Simulation

    p = PRESETS[name]
    cfg = ScenarioConfig(
        name=p.name, start_year=p.start_year, end_year=p.end_year,
        storage_enabled=p.storage_enabled, anchor_years=(),
    )
    n = int(n_agents or p.default_agents)
    root = input_root or REFERENCE_INPUT_ROOT

    meta: Dict[str, object] = {
        "preset": p.name, "baseline_config": p.baseline_config,
        "n_agents": n,
        # provenance stamp: which persistent-compile-cache the run used
        # and how warm it was at build time (entries present before this
        # run compiled anything = prior processes' executables)
        "compile_cache": (
            dict(compilecache.stats(), enabled=True)
            if cache_d else {"enabled": False}
        ),
    }
    # inputs always cover the FULL state list: synthetic populations
    # index global state ids even when only the preset's states are
    # populated (same contract as parallel.launch.main)
    states = list(synth.STATES)
    prefer = (
        {"load_growth": p.load_growth_scenario}
        if p.load_growth_scenario else None
    )
    if os.path.isdir(root):
        inputs, ref_meta = scenario_inputs_from_reference(
            root, cfg, states, prefer=prefer)
        meta["data_sources"] = ref_meta.get("data_sources", {})
        meta["market_curves"] = ref_meta["market_curves"]
        n_regions = len(ref_meta["regions"])
        wholesale = jnp.asarray(wholesale_profile_bank(ref_meta, root))
    else:
        meta["data_sources"] = {"all": "synthetic_default"}
        meta["market_curves"] = {"mms": "synthetic_default",
                                 "bass": "synthetic_default"}
        inputs = None
        n_regions = 10
        wholesale = None

    pop = synth.generate_population(
        n, states=p.states, seed=7, sector_weights=p.sector_weights,
        n_regions=n_regions,
    )
    if inputs is None:
        inputs = scen.uniform_inputs(
            cfg, n_groups=pop.table.n_groups, n_regions=n_regions)
        profiles = pop.profiles
    else:
        profiles = ProfileBank(
            load=pop.profiles.load, solar_cf=pop.profiles.solar_cf,
            wholesale=wholesale,
        )

    sim = Simulation(
        pop.table, profiles, pop.tariffs, inputs, cfg,
        run_config or RunConfig(), mesh=mesh, with_hourly=p.with_hourly,
    )
    meta["agent_chunk"] = sim._agent_chunk
    return sim, pop, meta


class _TimedExporter:
    """RunExporter wrapper accumulating host-side export seconds."""

    def __init__(self, inner):
        self.inner = inner
        self.seconds = 0.0

    def prepare(self, year, year_idx, outs):
        # dispatch-only (no fetch); forwarded so the deferred-transfer
        # prep still lands right behind the producing step
        prep = getattr(self.inner, "prepare", None)
        if prep is not None:
            prep(year, year_idx, outs)

    def __call__(self, year, year_idx, outs):
        t0 = time.time()
        self.inner(year, year_idx, outs)
        self.seconds += time.time() - t0


def run_preset(
    name: str,
    n_agents: Optional[int] = None,
    run_dir: Optional[str] = None,
    export: bool = True,
    checkpoint: bool = False,
) -> Dict[str, object]:
    """Build and run a preset end to end; returns the timing record.

    The record is the full-run truth BASELINE.md's north star names:
    cold start -> every model year -> all three parquet surfaces
    written (exports on), with the per-phase split.
    """
    from dgen_tpu.io.export import RunExporter

    t_start = time.time()
    sim, pop, meta = build(name, n_agents=n_agents)
    build_s = time.time() - t_start

    callback = None
    if export or checkpoint:
        # checkpointing needs a run dir even when exports are off
        run_dir = run_dir or os.path.join(
            "runs", f"preset-{name}-{int(t_start)}")
    if export:
        from dgen_tpu.io import synth
        from dgen_tpu.io.export import static_frame_from_table

        callback = _TimedExporter(RunExporter(
            run_dir, agent_id=sim.host_agent_id, mask=sim.host_mask,
            state_names=None, meta=meta,
            static_frame=static_frame_from_table(
                pop.table, states=list(synth.STATES)),
        ))

    year_times: list = []
    orig_step = sim.step

    def timed_step(carry, yi, first_year):
        t0 = time.time()
        out = orig_step(carry, yi, first_year)
        year_times.append(time.time() - t0)
        return out

    sim.step = timed_step
    t0 = time.time()
    res = sim.run(
        callback=callback, collect=False,
        checkpoint_dir=(os.path.join(run_dir, "ckpt")
                        if (checkpoint and run_dir) else None),
    )
    run_s = time.time() - t0
    total_s = time.time() - t_start

    n_real = int(np.asarray(sim.host_mask).sum())
    n_years = len(res.years)
    # sim.step times measure DISPATCH, so only the first dispatch —
    # which blocks on compilation — is meaningful. Exports are DEFERRED
    # one year by Simulation.run and overlap device compute, so
    # export_s (the callback wall, which includes waiting for the
    # overlapped year to finish) cannot be subtracted from the run wall
    # as if it were serial: steady per-year time is the run wall net of
    # compile only, and export_overlapped_s reports the export wall for
    # what it is.
    compile_s = max(
        year_times[0] - float(np.median(year_times[1:])), 0.0
    ) if len(year_times) > 2 else 0.0
    export_s = callback.seconds if callback else 0.0
    steady = max(run_s - compile_s, 0.0) / max(n_years, 1)
    rec = {
        "preset": name,
        "agents": n_real,
        "years": n_years,
        "agent_chunk": meta["agent_chunk"],
        "with_hourly": PRESETS[name].with_hourly,
        "storage": PRESETS[name].storage_enabled,
        "total_s": round(total_s, 1),
        "build_s": round(build_s, 1),
        "run_s": round(run_s, 1),
        "compile_s": round(compile_s, 1),
        "steady_year_s": round(steady, 2),
        "export_overlapped_s": round(export_s, 1),
        "agent_years_per_sec": round(n_real * n_years / total_s, 1),
        "run_dir": run_dir,
        "data_sources": meta["data_sources"],
    }
    return rec


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser(
        description="Run a BASELINE.json preset end to end")
    ap.add_argument("name", choices=sorted(PRESETS))
    ap.add_argument("--agents", type=int, default=None)
    ap.add_argument("--run-dir", default=None)
    ap.add_argument("--no-export", action="store_true")
    ap.add_argument("--checkpoint", action="store_true")
    args = ap.parse_args(argv)

    p = PRESETS[args.name]
    print(f"preset {p.name}: {p.baseline_config}")
    rec = run_preset(
        args.name, n_agents=args.agents, run_dir=args.run_dir,
        export=not args.no_export, checkpoint=args.checkpoint,
    )
    print(f"build {rec['build_s']}s | compile ~{rec['compile_s']}s | "
          f"steady year {rec['steady_year_s']}s | "
          f"exports(overlapped) {rec['export_overlapped_s']}s | total {rec['total_s']}s "
          f"({rec['agent_years_per_sec']} agent-years/sec)")
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
