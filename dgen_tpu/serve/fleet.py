"""ReplicaSupervisor: N serving replicas, kept alive and routable.

The serving story so far is one process; the north star is "heavy
traffic from millions of users", and one process is one preemption away
from zero capacity.  This module turns ``python -m dgen_tpu.serve``
into a *fleet*: a supervisor spawns N replica processes, gates each on
**readiness** (not liveness — a replica is routable only after its
``/readyz`` reports warmup complete; replicas boot in seconds because
they share the AOT compile cache, ``utils/compilecache.py``), restarts
dead replicas under the resilience layer's :class:`~dgen_tpu.
resilience.supervisor.RetryPolicy` backoff, and refuses to feed a
crash loop (more than ``FleetConfig.max_restarts`` deaths inside
``restart_window_s`` marks the replica FAILED instead of burning CPU
on restart storms).

Replica discovery is a **portfile**: each replica binds an ephemeral
port (``--port 0``), then atomically writes
``<fleet_dir>/replica-<i>.json`` (pid, port) — the supervisor polls
for the file, then polls ``/readyz`` until green.  No registry, no
race: the file appears only after the socket is bound.

Lifecycle per replica::

    SPAWNING --portfile--> BOOTING --/readyz 200--> READY
        |                     |                       |
        +----- process death / boot timeout ----------+
                              |
                    BACKOFF (RetryPolicy) --> SPAWNING ...
                              |
                    FAILED (crash-loop breaker tripped)

The routing front (:mod:`dgen_tpu.serve.front`) holds a supervisor and
routes over :meth:`ReplicaSupervisor.ready_handles`; the fault drill
(``python -m dgen_tpu.resilience drill --serve-fleet``) shoots at it.

This module imports no jax: supervision is pure process/socket work,
and must stay responsive while replicas compile.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import os
import random
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from dgen_tpu.config import FleetConfig
from dgen_tpu.resilience.supervisor import RetryPolicy
from dgen_tpu.utils.logging import get_logger

logger = get_logger()

# replica lifecycle states
SPAWNING = "spawning"   # process launched, portfile not yet written
BOOTING = "booting"     # port known, /readyz not yet green
READY = "ready"         # routable
BACKOFF = "backoff"     # dead, restart scheduled
FAILED = "failed"       # crash-loop breaker tripped; no more restarts
STOPPED = "stopped"     # supervisor shut it down

#: replicas are children of this process; discovery and probing are
#: loopback-only regardless of what interface the front binds
REPLICA_HOST = "127.0.0.1"

#: every transport failure a one-shot local HTTP call can raise — ONE
#: tuple shared by the supervisor's probes, the front's forwards and
#: scrapes, and the drill's clients, so no caller can under-catch
#: (a replica dying mid-response raises BadStatusLine, an
#: HTTPException, NOT an OSError)
HTTP_ERRORS = (OSError, http.client.HTTPException, ValueError)


class _NoDelayHTTPConnection(http.client.HTTPConnection):
    """HTTPConnection with Nagle disabled.  http.client writes headers
    and body in separate sends; with Nagle + delayed ACK that costs a
    ~40 ms stall PER HOP on loopback keep-alive POSTs — two hops
    (client->front->replica) turn a microsecond mmap lookup into an
    80 ms answer.  TCP_NODELAY removes it; the server side sets
    ``disable_nagle_algorithm`` for the same reason."""

    def connect(self):
        super().connect()
        try:
            self.sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass   # non-TCP transports (tests) just skip it


def http_json(port: int, path: str, *, method: str = "GET",
              body: Optional[bytes] = None, timeout: float = 5.0,
              host: str = REPLICA_HOST,
              pool: Optional["HTTPPool"] = None) -> tuple:
    """HTTP request to a local replica/front: ``(status, raw body
    bytes, headers dict)``.  Transport failures raise members of
    :data:`HTTP_ERRORS`; callers decide whether to swallow (probes,
    scrapes) or fail over (the front's forwards).

    Without ``pool`` this is one-shot: fresh TCP connection, closed
    after the response.  With ``pool`` the connection is checked out
    of (and, on a clean keep-alive response, back into) the pool — the
    serving path's steady state then pays zero TCP handshakes per
    query (the replica side already speaks HTTP/1.1 keep-alive)."""
    if pool is not None:
        return pool.request(
            port, path, method=method, body=body, timeout=timeout,
            host=host,
        )
    conn = _NoDelayHTTPConnection(host, port, timeout=timeout)
    try:
        conn.request(
            method, path, body=body,
            headers=(
                {"Content-Type": "application/json"}
                if body is not None else {}
            ),
        )
        r = conn.getresponse()
        return r.status, r.read(), dict(r.getheaders())
    finally:
        conn.close()


class HTTPPool:
    """Keep-alive connection pool for the loopback serving mesh.

    Every proxied query used to pay a fresh TCP handshake (connect +
    slow-start) on the front->replica hop; with both sides speaking
    HTTP/1.1 keep-alive, pooling makes the steady-state hop a single
    write+read on an established socket.  Semantics:

    * per-(host, port) stacks of idle connections, bounded by
      ``max_idle`` (extras are closed on check-in, not refused);
    * a response advertising ``Connection: close`` (or any transport
      error) closes the connection instead of pooling it;
    * a **reused** connection that fails mid-request is retried once
      on a FRESH connection — the server may have idle-timed the
      socket between uses, which is not a replica failure and must
      not count against a breaker.  A fresh connection's failure
      propagates (that IS a replica/transport failure).

    Thread-safe; counters feed ``/metricz`` (``reused / requests``
    is the handshake-elision rate the keep-alive satellite exists
    to prove).
    """

    def __init__(self, max_idle: int = 8) -> None:
        self.max_idle = int(max_idle)
        self._idle: Dict[tuple, List[http.client.HTTPConnection]] = {}
        self._lock = threading.Lock()
        self._closed = False
        self.n_requests = 0
        self.n_created = 0
        self.n_reused = 0
        self.n_stale_retries = 0

    def _checkout(self, key: tuple, timeout: float):
        with self._lock:
            stack = self._idle.get(key)
            conn = stack.pop() if stack else None
            if conn is not None:
                self.n_reused += 1
        if conn is not None:
            conn.timeout = timeout
            if conn.sock is not None:
                conn.sock.settimeout(timeout)
            return conn, True
        with self._lock:
            self.n_created += 1
        return _NoDelayHTTPConnection(
            key[0], key[1], timeout=timeout), False

    def _checkin(self, key: tuple, conn) -> None:
        with self._lock:
            if not self._closed:
                stack = self._idle.setdefault(key, [])
                if len(stack) < self.max_idle:
                    stack.append(conn)
                    return
        conn.close()

    def _once(self, conn, method: str, path: str,
              body: Optional[bytes]) -> tuple:
        conn.request(
            method, path, body=body,
            headers=(
                {"Content-Type": "application/json"}
                if body is not None else {}
            ),
        )
        r = conn.getresponse()
        return r.status, r.read(), dict(r.getheaders()), r.will_close

    def request(self, port: int, path: str, *, method: str = "GET",
                body: Optional[bytes] = None, timeout: float = 5.0,
                host: str = REPLICA_HOST) -> tuple:
        key = (host, port)
        with self._lock:
            self.n_requests += 1
        conn, reused = self._checkout(key, timeout)
        try:
            status, blob, headers, will_close = self._once(
                conn, method, path, body)
        except HTTP_ERRORS as e:
            conn.close()
            # retry ONLY the reused-and-idle-closed shape (server shut
            # the pooled socket between uses: reset/broken-pipe on
            # send, BadStatusLine on the response read).  A TIMEOUT is
            # not that — the request was delivered and the replica is
            # hanging; retrying it would double both the time-to-
            # failover and the hung replica's queued work
            if not reused or isinstance(e, TimeoutError):
                raise
            with self._lock:
                self.n_stale_retries += 1
                self.n_created += 1
            conn = _NoDelayHTTPConnection(host, port, timeout=timeout)
            try:
                status, blob, headers, will_close = self._once(
                    conn, method, path, body)
            except HTTP_ERRORS:
                conn.close()
                raise
        if will_close:
            conn.close()
        else:
            self._checkin(key, conn)
        return status, blob, headers

    def drop(self, port: int, host: str = REPLICA_HOST) -> None:
        """Close every idle connection to one endpoint (a replica died
        or was retired; its pooled sockets are garbage)."""
        with self._lock:
            stack = self._idle.pop((host, port), [])
        for c in stack:
            c.close()

    def stats(self) -> dict:
        with self._lock:
            idle = sum(len(s) for s in self._idle.values())
            return {
                "requests": self.n_requests,
                "created": self.n_created,
                "reused": self.n_reused,
                "stale_retries": self.n_stale_retries,
                "idle": idle,
            }

    def close(self) -> None:
        with self._lock:
            self._closed = True
            stacks = list(self._idle.values())
            self._idle = {}
        for s in stacks:
            for c in s:
                c.close()


@dataclasses.dataclass
class ReplicaHandle:
    """One replica slot's mutable state (the supervisor owns writes;
    readers snapshot under the supervisor lock)."""

    index: int
    portfile: str
    state: str = SPAWNING
    proc: Optional[subprocess.Popen] = None
    port: Optional[int] = None
    pid: Optional[int] = None
    #: completed spawns (0 on the first; env_for sees it, so a drill
    #: can arm faults on incarnation 0 only)
    spawn_count: int = 0
    spawned_at: float = 0.0
    ready_at: Optional[float] = None
    boot_wall_s: Optional[float] = None
    restart_at: Optional[float] = None
    deaths: deque = dataclasses.field(
        default_factory=lambda: deque(maxlen=64))
    exit_codes: List[int] = dataclasses.field(default_factory=list)
    last_death_at: Optional[float] = None
    #: wall from last death to back READY (the failover recovery
    #: number the drill and bench stamp)
    last_recovery_s: Optional[float] = None

    def summary(self) -> dict:
        return {
            "index": self.index,
            "state": self.state,
            "port": self.port,
            "pid": self.pid,
            "spawn_count": self.spawn_count,
            "deaths": len(self.deaths),
            "exit_codes": list(self.exit_codes),
            "boot_wall_s": (
                round(self.boot_wall_s, 3)
                if self.boot_wall_s is not None else None
            ),
            "last_recovery_s": (
                round(self.last_recovery_s, 3)
                if self.last_recovery_s is not None else None
            ),
        }


def default_replica_cmd(
    serve_args: Sequence[str],
) -> Callable[[int, str], List[str]]:
    """The standard replica command: ``python -m dgen_tpu.serve
    --replica-index I --port 0 --portfile F <serve_args>``."""

    def cmd_for(index: int, portfile: str) -> List[str]:
        return [
            sys.executable, "-m", "dgen_tpu.serve",
            "--replica-index", str(index),
            "--port", "0", "--portfile", portfile,
            *serve_args,
        ]

    return cmd_for


class ReplicaSupervisor:
    """Spawn, readiness-gate, monitor, restart (bounded) N replicas.

    Parameters
    ----------
    cmd_for : ``(index, portfile) -> argv`` — the replica command.
        Tests substitute a stub; production uses
        :func:`default_replica_cmd`.
    config : :class:`~dgen_tpu.config.FleetConfig`.
    policy : restart backoff (:class:`RetryPolicy`; only its
        ``backoff_s`` schedule is used here — classification is the
        exit code, restart bounding is the crash-loop window).
    env_for : optional ``(index, spawn_count) -> dict`` of EXTRA env
        for a spawn (the fleet drill arms per-replica fault specs on
        incarnation 0 only).  ``DGEN_TPU_FAULTS`` is stripped from the
        inherited environment either way: a spec meant for the parent
        must never leak into every replica.
    fleet_dir : portfiles + per-replica logs (default: a fresh
        tempdir).
    """

    def __init__(
        self,
        cmd_for: Callable[[int, str], List[str]],
        config: Optional[FleetConfig] = None,
        policy: Optional[RetryPolicy] = None,
        env_for: Optional[Callable[[int, int], Optional[dict]]] = None,
        fleet_dir: Optional[str] = None,
        seed: int = 0,
    ) -> None:
        self.config = config or FleetConfig()
        self.policy = policy or RetryPolicy()
        self._cmd_for = cmd_for
        self._env_for = env_for
        self.fleet_dir = fleet_dir or tempfile.mkdtemp(prefix="dgen-fleet-")
        os.makedirs(self.fleet_dir, exist_ok=True)
        self._rng = random.Random(seed)
        self._lock = threading.RLock()
        self._stopping = False
        self._monitor: Optional[threading.Thread] = None
        self.events: deque = deque(maxlen=1000)
        self.replicas: List[ReplicaHandle] = [
            ReplicaHandle(
                index=i,
                portfile=os.path.join(self.fleet_dir, f"replica-{i}.json"),
            )
            for i in range(self.config.n_replicas)
        ]

    # -- events --------------------------------------------------------

    def _event(self, index: int, event: str, **detail) -> None:
        rec = {"t": round(time.time(), 3), "replica": index,
               "event": event, **detail}
        self.events.append(rec)
        logger.info("fleet: replica %d %s %s", index, event,
                    detail or "")

    # -- spawning ------------------------------------------------------

    def _spawn(self, h: ReplicaHandle) -> None:
        if os.path.exists(h.portfile):
            os.unlink(h.portfile)
        env = os.environ.copy()
        # a fault spec armed for THIS process must not leak into every
        # replica; drills arm per-replica specs through env_for
        env.pop("DGEN_TPU_FAULTS", None)
        env["DGEN_TPU_SERVE_REPLICA"] = str(h.index)
        extra = self._env_for(h.index, h.spawn_count) if self._env_for else None
        if extra:
            env.update({k: str(v) for k, v in extra.items()})
        log_path = os.path.join(
            self.fleet_dir, f"replica-{h.index}.log")
        # append-only diagnostics, not a run artifact: a torn tail is
        # exactly what a crashed replica's log should show
        logf = open(log_path, "ab")  # dgenlint: disable=L11
        try:
            h.proc = subprocess.Popen(
                self._cmd_for(h.index, h.portfile),
                stdout=logf, stderr=subprocess.STDOUT, env=env,
            )
        finally:
            logf.close()   # the child holds its own fd now
        h.spawn_count += 1
        h.spawned_at = time.monotonic()
        h.port = None
        h.pid = h.proc.pid
        h.state = SPAWNING
        self._event(h.index, "spawned", pid=h.proc.pid,
                    incarnation=h.spawn_count - 1)

    def start(self) -> "ReplicaSupervisor":
        with self._lock:
            for h in self.replicas:
                self._spawn(h)
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="dgen-fleet-monitor",
            daemon=True,
        )
        self._monitor.start()
        return self

    # -- monitoring ----------------------------------------------------

    @staticmethod
    def _probe_ready(port: int) -> bool:
        try:
            status, _, _ = http_json(port, "/readyz", timeout=2.0)
            return status == 200
        except HTTP_ERRORS:
            # includes BadStatusLine from a replica dying mid-response
            # — the probe reports unready, the liveness poll then sees
            # the death
            return False

    def _on_death(self, h: ReplicaHandle, rc: Optional[int]) -> None:
        now = time.monotonic()
        h.deaths.append(now)
        if rc is not None:
            h.exit_codes.append(rc)
        h.last_death_at = now
        h.port = None
        window = [t for t in h.deaths
                  if now - t <= self.config.restart_window_s]
        if len(window) > self.config.max_restarts:
            h.state = FAILED
            self._event(h.index, "crash_loop", exit_code=rc,
                        deaths_in_window=len(window))
            return
        backoff = self.policy.backoff_s(
            min(len(window) - 1, 6), self._rng)
        h.restart_at = now + backoff
        h.state = BACKOFF
        self._event(h.index, "died", exit_code=rc,
                    restart_in_s=round(backoff, 3))

    def _kill_boot_timeout(self, h: ReplicaHandle):
        """The blocking half of a boot timeout.  Runs OUTSIDE
        self._lock: kill + wait can block up to 10 s, and nothing that
        long may run under the supervisor lock (the same rule as the
        readiness probes — see _tick)."""
        rc = None
        if h.proc is not None:
            if h.proc.poll() is None:
                h.proc.kill()
                try:
                    h.proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    pass   # unkillable (D-state); poll again next tick
            rc = h.proc.poll()
        return rc

    def _tick(self) -> None:
        # readiness probes are network round-trips (up to 2 s); run
        # them OUTSIDE the lock so the front's per-request
        # ready_handles() snapshot never waits behind a stalling probe.
        # The monitor thread is the only state mutator, so a handle
        # probed here cannot change state underneath us.
        with self._lock:
            to_probe = [
                (h, h.port) for h in self.replicas
                if h.state == BOOTING and h.port is not None
            ]
        probe_ok = {h.index: self._probe_ready(port)
                    for h, port in to_probe}
        now = time.monotonic()
        timed_out = []
        with self._lock:
            for h in self.replicas:
                if h.state == STOPPED:
                    # a retired (scale-down) replica drains on its own;
                    # poll() reaps the eventual exit so it never
                    # lingers as a zombie
                    if h.proc is not None:
                        h.proc.poll()
                    continue
                if h.state in (SPAWNING, BOOTING, READY):
                    rc = h.proc.poll() if h.proc is not None else 1
                    if rc is not None:
                        self._on_death(h, rc)
                        continue
                if h.state == SPAWNING:
                    if os.path.isfile(h.portfile):
                        try:
                            with open(h.portfile) as f:
                                data = json.load(f)
                            h.port = int(data["port"])
                        except (OSError, ValueError, KeyError):
                            pass   # partially visible; next tick re-reads
                        else:
                            h.state = BOOTING
                            self._event(h.index, "bound", port=h.port)
                    elif now - h.spawned_at > self.config.boot_timeout_s:
                        self._event(h.index, "boot_timeout")
                        timed_out.append(h)
                elif h.state == BOOTING:
                    if probe_ok.get(h.index, False):
                        h.state = READY
                        h.ready_at = now
                        h.boot_wall_s = now - h.spawned_at
                        if h.last_death_at is not None:
                            h.last_recovery_s = now - h.last_death_at
                        self._event(
                            h.index, "ready",
                            boot_wall_s=round(h.boot_wall_s, 3),
                            recovery_s=(
                                round(h.last_recovery_s, 3)
                                if h.last_recovery_s is not None else None
                            ),
                        )
                    elif now - h.spawned_at > self.config.boot_timeout_s:
                        self._event(h.index, "boot_timeout")
                        timed_out.append(h)
                elif h.state == BACKOFF:
                    if h.restart_at is not None and now >= h.restart_at:
                        self._spawn(h)
        # kill + reap outside the lock (blocking, up to 10 s each),
        # then reacquire for the state transition.  Only this thread
        # mutates states, but recheck anyway: retire_replica() may
        # have STOPPED the handle between the two critical sections.
        for h in timed_out:
            rc = self._kill_boot_timeout(h)
            with self._lock:
                if h.state in (SPAWNING, BOOTING):
                    self._on_death(h, rc)

    def _monitor_loop(self) -> None:
        while not self._stopping:
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — the monitor must outlive
                # any single bad tick: a dead monitor means an
                # unsupervised fleet that still LOOKS supervised
                logger.exception("fleet monitor: tick failed")
            time.sleep(self.config.poll_interval_s)

    # -- elasticity (the autoscaler's verbs) ---------------------------

    def add_replica(self) -> ReplicaHandle:
        """Grow the fleet by one replica slot and spawn it (the
        autoscaler's scale-up verb; also usable by an operator).  The
        monitor gates it through the normal SPAWNING -> BOOTING ->
        READY lifecycle — it joins routing only when /readyz is green,
        which is fast when the shared compile cache is warm."""
        with self._lock:
            if self._stopping:
                raise RuntimeError("supervisor is stopping")
            i = len(self.replicas)
            h = ReplicaHandle(
                index=i,
                portfile=os.path.join(self.fleet_dir, f"replica-{i}.json"),
            )
            self.replicas.append(h)
            self._spawn(h)
        self._event(i, "scale_up_spawned", n_replicas=i + 1)
        return h

    def retire_replica(self, index: int,
                       drain_timeout_s: float = 30.0) -> bool:
        """Shrink the fleet: SIGTERM one replica (it drains its
        in-flight batches — the replica CLI's SIGTERM handler) and
        mark it STOPPED so the monitor neither counts the exit as a
        death nor restarts it.  The process is reaped asynchronously
        by the monitor.  False when the slot is already dead/stopped.
        """
        with self._lock:
            if not (0 <= index < len(self.replicas)):
                return False
            h = self.replicas[index]
            if h.state == STOPPED or h.proc is None \
                    or h.proc.poll() is not None:
                return False
            # state first: the monitor's next tick must already see
            # STOPPED when the SIGTERM exit lands
            h.state = STOPPED
            h.proc.send_signal(signal.SIGTERM)
        self._event(index, "scale_down_retired",
                    drain_timeout_s=drain_timeout_s)
        return True

    def live_count(self) -> int:
        """Replica slots not STOPPED/FAILED (what the fleet is
        currently trying to keep alive — the autoscaler's notion of
        current size)."""
        with self._lock:
            return sum(
                1 for h in self.replicas
                if h.state not in (STOPPED, FAILED)
            )

    def live_indices(self) -> set:
        """Indices of slots not STOPPED (the front prunes per-replica
        state keyed outside this set)."""
        with self._lock:
            return {h.index for h in self.replicas if h.state != STOPPED}

    def stopped_ports(self) -> List[int]:
        """Ports of retired (STOPPED) slots — their pooled sockets are
        garbage the front should drop."""
        with self._lock:
            return [
                h.port for h in self.replicas
                if h.state == STOPPED and h.port is not None
            ]

    # -- queries -------------------------------------------------------

    def ready_handles(self) -> List[ReplicaHandle]:
        """Snapshot of routable replicas (READY, port known)."""
        with self._lock:
            return [h for h in self.replicas
                    if h.state == READY and h.port is not None]

    def states(self) -> Dict[int, str]:
        with self._lock:
            return {h.index: h.state for h in self.replicas}

    def summary(self) -> List[dict]:
        with self._lock:
            return [h.summary() for h in self.replicas]

    def wait_ready(self, n: Optional[int] = None,
                   timeout: float = 180.0) -> bool:
        """Block until >= n replicas are READY (default: all of them).
        False on timeout — callers decide whether partial strength is
        acceptable."""
        want = self.config.n_replicas if n is None else n
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(self.ready_handles()) >= want:
                return True
            time.sleep(min(self.config.poll_interval_s, 0.1))
        return len(self.ready_handles()) >= want

    # -- control -------------------------------------------------------

    def terminate_replica(self, index: int,
                          sig: int = signal.SIGKILL) -> bool:
        """Deliver ``sig`` to a replica (benches shoot fleets with
        this; drills prefer deterministic fault specs).  The monitor
        then sees the death and handles restart."""
        with self._lock:
            h = self.replicas[index]
            if h.proc is None or h.proc.poll() is not None:
                return False
            h.proc.send_signal(sig)
            self._event(index, "signalled", sig=int(sig))
            return True

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        """Stop the fleet: SIGTERM every live replica (graceful drain
        — each finishes its in-flight batches), bounded wait, SIGKILL
        stragglers.  ``drain=False`` goes straight to SIGKILL."""
        timeout = timeout if timeout is not None else (
            self.config.drain_timeout_s)
        with self._lock:
            if self._stopping and all(
                h.state == STOPPED for h in self.replicas
            ):
                return   # already stopped (drain_front + CLI finally)
            self._stopping = True
        if self._monitor is not None and self._monitor.is_alive():
            self._monitor.join(timeout=10.0)
        live = [h for h in self.replicas
                if h.proc is not None and h.proc.poll() is None]
        for h in live:
            h.proc.send_signal(
                signal.SIGTERM if drain else signal.SIGKILL)
        deadline = time.monotonic() + timeout
        for h in live:
            remaining = max(deadline - time.monotonic(), 0.1)
            try:
                h.proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                logger.warning(
                    "fleet: replica %d did not drain in %.1fs; killing",
                    h.index, timeout)
                h.proc.kill()
                h.proc.wait(timeout=10.0)
        with self._lock:
            for h in self.replicas:
                h.state = STOPPED
        self._event(-1, "fleet_stopped")
