"""CLI: ``python -m dgen_tpu.serve`` — stand up the what-if query
endpoint over a synthetic population (or a preset's population when a
reference input mount exists).

    python -m dgen_tpu.serve --agents 8192 --port 8178
    curl -s localhost:8178/healthz
    curl -s -XPOST localhost:8178/query -d \\
        '{"agent_ids": [17], "year": 2026,
          "overrides": {"scale": {"itc_fraction": 0.0}}}'

Serve knobs come from :class:`dgen_tpu.config.ServeConfig` (env:
DGEN_TPU_SERVE_*); the population/scenario build mirrors the bench
driver's synthetic path.
"""

from __future__ import annotations

import argparse


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="python -m dgen_tpu.serve",
        description="online what-if query engine (docs/serve.md)",
    )
    ap.add_argument("--agents", type=int, default=8192)
    ap.add_argument("--start-year", type=int, default=2014)
    ap.add_argument("--end-year", type=int, default=2050)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--host", default=None)
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--max-wait-ms", type=float, default=None)
    ap.add_argument("--no-warmup", action="store_true")
    args = ap.parse_args(argv)

    from dgen_tpu.utils import compilecache

    compilecache.enable()

    from dgen_tpu.config import RunConfig, ScenarioConfig, ServeConfig
    from dgen_tpu.io import synth
    from dgen_tpu.models import scenario as scen
    from dgen_tpu.models.simulation import Simulation
    from dgen_tpu.serve.engine import ServeEngine
    from dgen_tpu.serve.server import ServeApp, serve_forever

    overrides = {}
    for k, v in (
        ("host", args.host), ("port", args.port),
        ("max_batch", args.max_batch), ("max_wait_ms", args.max_wait_ms),
    ):
        if v is not None:
            overrides[k] = v
    if args.no_warmup:
        overrides["warmup"] = False
    serve_cfg = ServeConfig.from_env(**overrides)

    cfg = ScenarioConfig(
        name="serve", start_year=args.start_year, end_year=args.end_year,
        anchor_years=(),
    )
    pop = synth.generate_population(args.agents, seed=args.seed)
    inputs = scen.uniform_inputs(
        cfg, n_groups=pop.table.n_groups, n_regions=pop.n_regions
    )
    sim = Simulation(
        pop.table, pop.profiles, pop.tariffs, inputs, cfg,
        RunConfig.from_env(),
    )
    app = ServeApp(ServeEngine(sim), serve_cfg)
    serve_forever(app)


if __name__ == "__main__":
    main()
