"""CLI: ``python -m dgen_tpu.serve`` — stand up the what-if query
endpoint over a synthetic population (or a preset's population when a
reference input mount exists).

Production-throughput layers (docs/serve.md "Production throughput"):
``--build-surface DIR`` precomputes the zero-override answer surface
(batch mode: build, print the header, exit); ``--surface DIR`` serves
from it (provenance-gated); ``--cache-dir DIR`` shares an exact
result cache across replicas; ``--autoscale`` (fleet mode) sizes the
fleet from the aggregated occupancy signal between
``--min-replicas``/``--max-replicas``.

Three serving modes:

* **single process** (default) — the PR 5 behavior::

      python -m dgen_tpu.serve --agents 8192 --port 8178
      curl -s localhost:8178/healthz

* **fleet** — supervise N replicas behind the routing front
  (docs/serve.md "Fleet operations")::

      python -m dgen_tpu.serve --fleet 3 --agents 8192 --port 8177
      curl -s localhost:8177/metricz     # fleet-aggregated

* **replica** — one fleet member (normally spawned by the supervisor,
  not by hand): binds ``--port 0``, writes ``--portfile`` once the
  socket is bound, warms up in the background so ``/healthz`` answers
  (liveness) while ``/readyz`` stays 503 until warmup completes
  (readiness), and arms any ``DGEN_TPU_FAULTS`` spec from its
  environment (the fleet drill injects per-replica faults this way)::

      python -m dgen_tpu.serve --replica-index 0 --port 0 \\
          --portfile /tmp/replica-0.json --agents 8192

Serve knobs come from :class:`dgen_tpu.config.ServeConfig` (env:
DGEN_TPU_SERVE_*), fleet knobs from :class:`~dgen_tpu.config.
FleetConfig` (env: DGEN_TPU_FLEET_*); the population/scenario build
mirrors the bench driver's synthetic path.  SIGTERM always means
graceful drain (finish in-flight, then exit).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import threading


def _build_sim(args):
    """One synthetic population + Simulation from the CLI args — the
    same build in every mode, so every replica of a fleet (and the
    drill's single-replica oracle) computes over identical banks."""
    from dgen_tpu.config import RunConfig, ScenarioConfig
    from dgen_tpu.io import synth
    from dgen_tpu.models import scenario as scen
    from dgen_tpu.models.simulation import Simulation

    cfg = ScenarioConfig(
        name="serve", start_year=args.start_year, end_year=args.end_year,
        anchor_years=(),
    )
    pop = synth.generate_population(args.agents, seed=args.seed)
    inputs = scen.uniform_inputs(
        cfg, n_groups=pop.table.n_groups, n_regions=pop.n_regions
    )
    rc = RunConfig.from_env()
    if args.sizing_iters is not None:
        rc = dataclasses.replace(rc, sizing_iters=args.sizing_iters)
    kw = {}
    if args.econ_years is not None:
        kw["econ_years"] = args.econ_years
    return Simulation(
        pop.table, pop.profiles, pop.tariffs, inputs, cfg, rc, **kw
    )


def _serve_config(args):
    from dgen_tpu.config import ServeConfig

    overrides = {}
    for k, v in (
        ("host", args.host), ("port", args.port),
        ("max_batch", args.max_batch), ("max_wait_ms", args.max_wait_ms),
        ("min_bucket", args.min_bucket),
        ("surface_dir", args.surface),
        ("result_cache_dir", args.cache_dir),
        ("result_cache_entries", args.cache_entries),
    ):
        if v is not None:
            overrides[k] = v
    if args.no_warmup:
        overrides["warmup"] = False
    return ServeConfig.from_env(**overrides)


def _attach_layers(engine, serve_cfg):
    """Attach the engine-free serving layers a config names: the
    provenance-gated answer surface and the cross-replica result
    cache.  Refusals are loud and non-fatal (engine-path serving is
    always available)."""
    from dgen_tpu.serve import surface as surface_mod
    from dgen_tpu.serve.resultcache import ResultCache

    if serve_cfg.surface_dir:
        surface_mod.load_and_attach(engine, serve_cfg.surface_dir)
    if serve_cfg.result_cache_dir:
        engine.attach_result_cache(ResultCache(
            serve_cfg.result_cache_dir,
            provenance_key=surface_mod.provenance_key(engine),
            max_entries=serve_cfg.result_cache_entries,
        ))
    return engine


def _build_surface_cmd(args) -> None:
    """``--build-surface DIR``: sweep the zero-override answer for
    every (year, table row) through the live query program at full
    bucket width and publish it as a provenance-stamped mmap table."""
    import json as _json

    from dgen_tpu.serve.engine import ServeEngine
    from dgen_tpu.serve.surface import build_surface

    serve_cfg = _serve_config(args)
    engine = ServeEngine(_build_sim(args))
    bucket = serve_cfg.max_batch
    engine.warmup([bucket])
    header = build_surface(engine, args.build_surface, bucket)
    print(_json.dumps({
        "surface_dir": args.build_surface,
        "bucket": bucket,
        "years": header["meta"]["year_indices"],
        "rows": header["columns"]["agent_id"]["shape"][1],
        "content_hash": header["content_hash"],
        "build_wall_s": header["meta"]["build_wall_s"],
        "provenance": header["meta"]["provenance"],
    }, indent=1))


def _run_single(args) -> None:
    from dgen_tpu.serve.engine import ServeEngine
    from dgen_tpu.serve.server import ServeApp, serve_forever

    serve_cfg = _serve_config(args)
    engine = _attach_layers(ServeEngine(_build_sim(args)), serve_cfg)
    app = ServeApp(engine, serve_cfg)
    serve_forever(app)


def _run_replica(args) -> None:
    """One fleet member: bind first (liveness), portfile second
    (discovery), warm up third (readiness)."""
    from dgen_tpu.resilience import faults
    from dgen_tpu.serve.engine import ServeEngine
    from dgen_tpu.serve.server import ServeApp, make_server, serve_forever
    from dgen_tpu.utils.logging import get_logger

    logger = get_logger()
    faults.install_from_env()   # the drill's per-replica fault specs
    serve_cfg = _serve_config(args)
    engine = _attach_layers(ServeEngine(_build_sim(args)), serve_cfg)
    app = ServeApp(
        engine, serve_cfg,
        replica_index=args.replica_index, defer_warmup=True,
    )
    srv = make_server(app)
    if args.portfile:
        tmp = args.portfile + ".tmp"
        with open(tmp, "w") as f:   # dgenlint: disable=L11
            json.dump({
                "pid": os.getpid(),
                "port": srv.server_address[1],
                "replica_index": args.replica_index,
            }, f)
        os.replace(tmp, args.portfile)

    def _warm() -> None:
        try:
            app.warmup_now()
        except Exception:  # noqa: BLE001 — never-ready is the signal
            logger.exception(
                "replica %s warmup failed; staying unready",
                args.replica_index,
            )

    threading.Thread(
        target=_warm, name="dgen-serve-warmup", daemon=True
    ).start()
    serve_forever(app, srv)


def _run_fleet(args) -> None:
    from dgen_tpu.config import FleetConfig
    from dgen_tpu.serve.fleet import ReplicaSupervisor, default_replica_cmd
    from dgen_tpu.serve.front import (
        FleetFront,
        install_sigterm_drain_front,
        make_front_server,
    )
    from dgen_tpu.utils.logging import get_logger

    logger = get_logger()
    overrides = {"n_replicas": args.fleet}
    if args.host is not None:
        overrides["host"] = args.host
    if args.port is not None:
        overrides["port"] = args.port
    if args.autoscale:
        overrides["autoscale"] = True
    if args.min_replicas is not None:
        overrides["min_replicas"] = args.min_replicas
    if args.max_replicas is not None:
        overrides["max_replicas"] = args.max_replicas
    fleet_cfg = FleetConfig.from_env(**overrides)

    serve_args = [
        "--agents", str(args.agents),
        "--start-year", str(args.start_year),
        "--end-year", str(args.end_year),
        "--seed", str(args.seed),
    ]
    if args.econ_years is not None:
        serve_args += ["--econ-years", str(args.econ_years)]
    if args.sizing_iters is not None:
        serve_args += ["--sizing-iters", str(args.sizing_iters)]
    if args.max_batch is not None:
        serve_args += ["--max-batch", str(args.max_batch)]
    if args.min_bucket is not None:
        serve_args += ["--min-bucket", str(args.min_bucket)]
    if args.max_wait_ms is not None:
        serve_args += ["--max-wait-ms", str(args.max_wait_ms)]
    if args.no_warmup:
        serve_args += ["--no-warmup"]
    if args.surface:
        serve_args += ["--surface", args.surface]
    if args.cache_dir:
        serve_args += ["--cache-dir", args.cache_dir]
    if args.cache_entries is not None:
        serve_args += ["--cache-entries", str(args.cache_entries)]

    sup = ReplicaSupervisor(
        default_replica_cmd(serve_args), fleet_cfg,
    ).start()
    front = FleetFront(sup, fleet_cfg).start()
    scaler = None
    if fleet_cfg.autoscale:
        from dgen_tpu.serve.autoscale import Autoscaler

        scaler = Autoscaler(sup, front.pressure, fleet_cfg).start()
    srv = make_front_server(front)
    install_sigterm_drain_front(front, srv)
    host, port = srv.server_address[:2]
    logger.info(
        "dgen-tpu serve fleet: %d replicas (%d agents each), front on "
        "http://%s:%d (/query /healthz /readyz /metricz); fleet dir %s",
        fleet_cfg.n_replicas, args.agents, host, port, sup.fleet_dir,
    )
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        logger.info("fleet front: shutting down")
    finally:
        if scaler is not None:
            scaler.stop()
        srv.server_close()
        front.close()
        sup.stop(drain=True)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="python -m dgen_tpu.serve",
        description="online what-if query engine (docs/serve.md)",
    )
    ap.add_argument("--agents", type=int, default=8192)
    ap.add_argument("--start-year", type=int, default=2014)
    ap.add_argument("--end-year", type=int, default=2050)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--econ-years", type=int, default=None)
    ap.add_argument("--sizing-iters", type=int, default=None)
    ap.add_argument("--host", default=None)
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--min-bucket", type=int, default=None)
    ap.add_argument("--max-wait-ms", type=float, default=None)
    ap.add_argument("--no-warmup", action="store_true")
    ap.add_argument("--surface", default=None, metavar="DIR",
                    help="serve zero-override queries from this "
                         "precomputed answer surface (provenance-"
                         "gated; docs/serve.md 'Production "
                         "throughput')")
    ap.add_argument("--build-surface", default=None, metavar="DIR",
                    help="build the answer surface for this "
                         "population/config into DIR and exit")
    ap.add_argument("--cache-dir", default=None, metavar="DIR",
                    help="cross-replica exact result cache directory "
                         "(shared by every replica of a fleet)")
    ap.add_argument("--cache-entries", type=int, default=None,
                    help="result cache entry bound (LRU eviction)")
    ap.add_argument("--autoscale", action="store_true",
                    help="fleet mode: scale replicas between "
                         "--min-replicas/--max-replicas from the "
                         "aggregated occupancy signal")
    ap.add_argument("--min-replicas", type=int, default=None)
    ap.add_argument("--max-replicas", type=int, default=None)
    ap.add_argument("--fleet", type=int, default=None, metavar="N",
                    help="supervise N replicas behind the routing front")
    ap.add_argument("--replica-index", type=int, default=None,
                    help="run as fleet replica I (spawned by the "
                         "supervisor)")
    ap.add_argument("--portfile", default=None,
                    help="replica mode: write {pid, port} here once "
                         "the socket is bound")
    args = ap.parse_args(argv)

    from dgen_tpu.utils import compilecache

    compilecache.enable()

    if args.fleet is not None and args.replica_index is not None:
        ap.error("--fleet and --replica-index are mutually exclusive")
    if args.build_surface is not None:
        if args.fleet is not None or args.replica_index is not None:
            ap.error("--build-surface is a batch command (no fleet/"
                     "replica flags)")
        _build_surface_cmd(args)
        return
    if args.fleet is not None:
        _run_fleet(args)
    elif args.replica_index is not None or args.portfile:
        _run_replica(args)
    else:
        _run_single(args)


if __name__ == "__main__":
    main()
