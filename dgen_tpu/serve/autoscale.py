"""Occupancy-driven fleet autoscaling: close the loop the PR 9 fleet
left open.

:class:`~dgen_tpu.serve.fleet.ReplicaSupervisor` already knows how to
spawn, warm, health-gate, restart, and drain replicas; the fleet front
already aggregates every replica's ``/metricz`` occupancy and queue
depth.  This module connects the two: a small control loop that grows
the fleet under sustained pressure and drains it back down when idle,
instead of holding N fixed while queues melt or machines sit warm and
empty.

Control policy (deliberately boring — serving control loops reward
predictability over cleverness):

* **signal** — :meth:`FleetFront.pressure`: aggregate queue depth as a
  fraction of aggregate queue capacity, plus batch-weighted occupancy,
  over *fresh* READY-replica scrapes.  No fresh signal = no action
  (never scale blind, the same rule load shedding follows).
* **hysteresis** — pressure must be *sustained* for
  ``scale_up_sustain_s`` before a scale-up, and idleness for
  ``scale_down_sustain_s`` before a scale-down; the down thresholds
  sit strictly below the up thresholds (enforced by
  :class:`~dgen_tpu.config.FleetConfig`), so a blip can't flap the
  fleet.
* **cooldown** — after ANY action the controller holds for
  ``scale_cooldown_s``: a freshly spawned replica needs time to reach
  READY and absorb load before the signal means anything again.
* **bounds** — the fleet never leaves
  ``[min_replicas, max_replicas]``.
* **verbs** — scale-up is ``supervisor.add_replica()`` (readiness-
  gated boot off the shared compile cache: seconds, not minutes);
  scale-down is ``supervisor.retire_replica(i)`` on the
  highest-index READY replica (SIGTERM -> the replica drains its
  in-flight batches; the monitor does not count the exit as a death).

Every decision lands in the supervisor's event ledger (and the
autoscaler's own ``events`` list), so a bench or drill can replay
exactly when and why the fleet changed size.

``signal_fn`` and ``clock`` are injectable: unit tests drive the full
hysteresis matrix with scripted signals and a fake clock; the
``--serve-scale`` drill feeds synthetic occupancy to a REAL fleet.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from dgen_tpu.config import FleetConfig
from dgen_tpu.serve.fleet import READY, ReplicaSupervisor
from dgen_tpu.utils.logging import get_logger

logger = get_logger()


class Autoscaler:
    """The control loop (module docstring).

    Parameters
    ----------
    supervisor : the fleet to scale.
    signal_fn : ``() -> Optional[dict]`` with keys ``queue_frac``,
        ``occupancy`` (:meth:`FleetFront.pressure`); None = no fresh
        signal, hold.
    config : :class:`~dgen_tpu.config.FleetConfig` (autoscale knobs).
    clock : injectable monotonic clock (tests).
    """

    def __init__(
        self,
        supervisor: ReplicaSupervisor,
        signal_fn: Callable[[], Optional[dict]],
        config: Optional[FleetConfig] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.sup = supervisor
        self.signal_fn = signal_fn
        self.config = config or supervisor.config
        self._clock = clock
        self._pressure_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_action_at: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # guards the ledger + counters: tick() runs on the control
        # thread, stats() on whoever asks (bench, metricz, tests)
        self._lock = threading.Lock()
        self.events: List[dict] = []
        self.n_scale_up = 0
        self.n_scale_down = 0

    # -- decision core (pure given signal + clock; unit-testable) ------

    def _record(self, action: str, **detail) -> None:
        rec = {"t": round(time.time(), 3), "action": action, **detail}
        with self._lock:
            self.events.append(rec)
        self.sup._event(-1, f"autoscale_{action}", **detail)

    def _in_cooldown(self, now: float) -> bool:
        return (
            self._last_action_at is not None
            and now - self._last_action_at < self.config.scale_cooldown_s
        )

    def tick(self) -> Optional[str]:
        """One control decision; returns "up"/"down" when an action
        was taken, else None."""
        cfg = self.config
        now = self._clock()
        sig = self.signal_fn()
        if sig is None:
            # no fresh signal: hold, and restart both hysteresis
            # windows — a gap in telemetry proves nothing either way
            self._pressure_since = None
            self._idle_since = None
            return None
        hot = (
            sig["queue_frac"] >= cfg.scale_up_queue_frac
            or sig["occupancy"] >= cfg.scale_up_occupancy
        )
        idle = (
            sig["queue_frac"] <= cfg.scale_down_queue_frac
            and sig["occupancy"] <= cfg.scale_down_occupancy
        )
        n = self.sup.live_count()
        if hot:
            self._idle_since = None
            if self._pressure_since is None:
                self._pressure_since = now
            sustained = now - self._pressure_since >= cfg.scale_up_sustain_s
            if sustained and not self._in_cooldown(now) \
                    and n < cfg.max_replicas:
                self.sup.add_replica()
                with self._lock:
                    self.n_scale_up += 1
                self._last_action_at = now
                self._pressure_since = None
                self._record(
                    "up", n_replicas=n + 1,
                    queue_frac=round(sig["queue_frac"], 4),
                    occupancy=round(sig["occupancy"], 4),
                )
                return "up"
        elif idle:
            self._pressure_since = None
            if self._idle_since is None:
                self._idle_since = now
            sustained = now - self._idle_since >= cfg.scale_down_sustain_s
            if sustained and not self._in_cooldown(now) \
                    and n > cfg.min_replicas:
                victim = self._pick_victim()
                if victim is not None and self.sup.retire_replica(
                    victim, drain_timeout_s=cfg.drain_timeout_s
                ):
                    with self._lock:
                        self.n_scale_down += 1
                    self._last_action_at = now
                    self._idle_since = None
                    self._record(
                        "down", retired=victim, n_replicas=n - 1,
                        queue_frac=round(sig["queue_frac"], 4),
                        occupancy=round(sig["occupancy"], 4),
                    )
                    return "down"
        else:
            # between the bands: neither window accumulates
            self._pressure_since = None
            self._idle_since = None
        return None

    def _pick_victim(self) -> Optional[int]:
        """Highest-index READY replica (LIFO: the most recently scaled
        up is the first retired — lower indices keep stable
        identities)."""
        ready = [h.index for h in self.sup.replicas if h.state == READY]
        return max(ready) if ready else None

    # -- loop ----------------------------------------------------------

    def start(self) -> "Autoscaler":
        self._thread = threading.Thread(
            target=self._loop, name="dgen-fleet-autoscale", daemon=True,
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the controller must
                # outlive any bad tick (same rule as the fleet monitor)
                logger.exception("autoscaler: tick failed")
            self._stop.wait(self.config.scale_interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)

    def stats(self) -> dict:
        live = self.sup.live_count()
        with self._lock:
            return {
                "enabled": True,
                "min_replicas": self.config.min_replicas,
                "max_replicas": self.config.max_replicas,
                "live_replicas": live,
                "scale_ups": self.n_scale_up,
                "scale_downs": self.n_scale_down,
                "events": list(self.events),
            }
