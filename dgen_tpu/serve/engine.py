"""The serving engine: ad-hoc per-agent what-if queries against the
HBM-resident agent table and profile banks.

Every offline subsystem so far answers "run the whole 2014->2050
scenario"; the question a live product asks is pointwise: *what is THIS
customer's optimal PV+storage size, bill savings and payback under THIS
tariff/incentive tweak?* That query is exactly one row of the paper's
hot loop (per-agent bill engine + sizing search + 25-year cashflow,
reference financial_functions.py:291-565) — embarrassingly parallel
once the banks are resident, the same columnar-residency argument the
sweep engine already exploits for whole-scenario batching.

Design contract (the serving analogue of the one-program-per-year
rule):

* the agent table, profile banks and tariff bank are placed ONCE at
  engine construction (reusing :class:`~dgen_tpu.models.simulation.
  Simulation`'s placement path) and never re-uploaded per query;
* query programs are jitted with FIXED shapes — one compiled program
  per power-of-two bucket size (``ServeConfig.buckets``) — so a
  steady-state serving session compiles nothing after warmup
  (RetraceGuard-verifiable);
* scenario overrides ride the small ``[Y, ...]`` ScenarioInputs leaves
  as traced ARGUMENTS (exactly like the sweep's scenario axis): a
  what-if tweak changes data, never the program.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from collections import OrderedDict
from functools import partial
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dgen_tpu.models.scenario import ScenarioInputs, apply_year
from dgen_tpu.models.simulation import (
    Simulation,
    build_econ_inputs,
    compute_nem_allowed,
    starting_state_kw,
)
from dgen_tpu.ops import sizing as sizing_ops
from dgen_tpu.resilience.faults import fault_point
from dgen_tpu.utils.logging import get_logger

logger = get_logger()


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class QueryOutputs:
    """Per-agent answers of one query bucket (all leaves [B, ...]) —
    the pointwise slice of what a full run writes per agent per year
    (sizing/economics columns of ``YearOutputs``)."""

    agent_id: jax.Array                       # [B] int32
    nem_allowed: jax.Array                    # [B] 1.0 = NEM available
    system_kw: jax.Array
    npv: jax.Array
    payback_period: jax.Array
    batt_kw: jax.Array
    batt_kwh: jax.Array
    first_year_bill_with_system: jax.Array
    first_year_bill_without_system: jax.Array
    bill_savings_y1: jax.Array                # without - with, year 1
    annual_kwh: jax.Array
    capacity_factor: jax.Array
    cash_flow: jax.Array                      # [B, Y+1]


#: QueryOutputs field names, in declaration order (the JSON row schema)
QUERY_FIELDS = tuple(f.name for f in dataclasses.fields(QueryOutputs))


#: compile-time arguments of :func:`query_program` — shared with the
#: program auditor (dgen_tpu.lint.prog), whose serve entry lowers the
#: same program over the same static vocabulary
QUERY_STATIC_ARGNAMES = (
    "n_periods", "econ_years", "sizing_iters", "sizing_impl",
    "rate_switch", "net_billing", "daylight",
)


def query_static_kwargs(sim: "Simulation") -> dict:
    """The serving static set for :func:`query_program` over a built
    Simulation — ONE constructor shared by :class:`ServeEngine` and
    the program auditor, so the audited serve program is byte-for-byte
    the program production compiles. ``net_billing`` is pinned True:
    an override can close a NEM gate the base scenario holds open, and
    True is numerically exact either way (the False flag is only ever
    a compile-time kernel skip)."""
    return dict(
        n_periods=sim.tariffs.max_periods,
        econ_years=sim.econ_years,
        sizing_iters=sim.run_config.sizing_iters,
        sizing_impl="auto",
        rate_switch=sim._rate_switch,
        net_billing=True,
        daylight=sim._daylight,
    )


@partial(jax.jit, static_argnames=QUERY_STATIC_ARGNAMES)
def query_program(
    table,
    profiles,
    tariffs,
    inputs: ScenarioInputs,
    idx: jax.Array,          # [B] int32 row indices into the table
    year_idx: jax.Array,     # scalar int32 model-year index
    *,
    n_periods: int,
    econ_years: int,
    sizing_iters: int,
    sizing_impl: str = "auto",
    rate_switch: bool = False,
    net_billing: bool = True,
    daylight=None,
    cluster_tidx: Optional[jax.Array] = None,
) -> QueryOutputs:
    """One query bucket as a single device program: gather the B
    requested rows from the resident table, rebuild their one-year
    economics environment, and size them through the same
    :func:`~dgen_tpu.ops.sizing.size_agents` engine the year step runs.

    The bucket is evaluated at FIRST-YEAR market state (state capacity
    = the scenario's starting capacities, :func:`starting_state_kw`):
    a what-if point query answers "this customer, were they deciding in
    model year ``year_idx``", not "row N of a particular diffusion
    trajectory" — so the program is a pure function of (banks, inputs,
    idx, year), independent of any run's carry, and one answer is
    bit-identical whether it was computed alone or inside a coalesced
    bucket (per-row math only; the one cross-agent term, the NEM state
    cap, depends on inputs alone).

    ``cluster_tidx``: per-row COMPACT tariff indices of a clustered
    Simulation (ops.tariffcluster) — the engine passes it together
    with one cluster's compact bank as ``tariffs`` and the cluster's
    tight ``n_periods``, so a mono-cluster bucket runs the specialized
    program; ``None`` (mixed buckets, unclustered sims) prices against
    the full bank at global pads.
    """
    sub = jax.tree.map(lambda a: a[idx], table)
    if cluster_tidx is not None:
        tidx = cluster_tidx[idx]
        sub = dataclasses.replace(
            sub, tariff_idx=tidx, tariff_switch_idx=tidx)
    ya = apply_year(sub, inputs, year_idx)
    state_kw = starting_state_kw(table, inputs)
    nem_allowed = compute_nem_allowed(sub, inputs, year_idx, state_kw)
    envs = build_econ_inputs(
        sub, profiles, tariffs, ya, nem_allowed, sub.incentives,
        rate_switch=rate_switch,
    )
    res = sizing_ops.size_agents(
        envs, n_periods=n_periods, n_years=econ_years,
        n_iters=sizing_iters, keep_hourly=False, impl=sizing_impl,
        net_billing=net_billing, daylight=daylight,
    )
    return QueryOutputs(
        agent_id=sub.agent_id,
        nem_allowed=nem_allowed,
        system_kw=res.system_kw,
        npv=res.npv,
        payback_period=res.payback_period,
        batt_kw=res.batt_kw,
        batt_kwh=res.batt_kwh,
        first_year_bill_with_system=res.first_year_bill_with_system,
        first_year_bill_without_system=res.first_year_bill_without_system,
        bill_savings_y1=(
            res.first_year_bill_without_system
            - res.first_year_bill_with_system
        ),
        annual_kwh=res.annual_energy_production_kwh,
        capacity_factor=res.capacity_factor,
        cash_flow=res.cash_flow,
    )


class OverrideError(ValueError):
    """A scenario override names an unknown field or cannot broadcast
    to the field's static shape."""


def apply_overrides(
    inputs: ScenarioInputs, overrides: Optional[dict]
) -> ScenarioInputs:
    """Build a what-if variant of ``inputs``: ``{"set": {field: v},
    "scale": {field: f}}`` replaces / scales named trajectory fields.

    Values broadcast to the field's existing shape and KEEP its dtype,
    so the override variant is pytree-compatible with the base inputs —
    the compiled query programs see new data, never a new program
    (exactly the sweep engine's scenario-axis contract). Unchanged
    leaves are the base's already-placed arrays; only touched fields
    re-upload. The arithmetic runs in NUMPY on purpose: building a
    variant must upload small arrays, not compile tiny XLA programs —
    a steady-state serving process compiles nothing after warmup, new
    override keys included.
    """
    if not overrides:
        return inputs
    valid = {f.name for f in dataclasses.fields(ScenarioInputs)}
    unknown_ops = set(overrides) - {"set", "scale"}
    if unknown_ops:
        raise OverrideError(
            f"unknown override op(s) {sorted(unknown_ops)}; expected "
            "{'set': {field: value}, 'scale': {field: factor}}"
        )
    repl: Dict[str, jax.Array] = {}
    for op in ("set", "scale"):
        for field, value in (overrides.get(op) or {}).items():
            if field not in valid:
                raise OverrideError(
                    f"unknown ScenarioInputs field '{field}'; valid "
                    f"fields: {', '.join(sorted(valid))}"
                )
            leaf = repl.get(field, getattr(inputs, field))
            host = np.asarray(leaf)
            is_int = np.issubdtype(host.dtype, np.integer)
            try:
                if op == "set":
                    v = np.asarray(value, dtype=host.dtype)
                    if is_int and not np.array_equal(v, np.asarray(value)):
                        raise ValueError("lossy integer conversion")
                    new = np.broadcast_to(v, host.shape)
                else:
                    # scale in f64 so an integer field (loan_term_yrs)
                    # that lands off-grid raises instead of silently
                    # truncating the client's what-if
                    exact = host * np.asarray(value, dtype=np.float64)
                    new = exact.astype(host.dtype)
                    if is_int and not np.array_equal(new, exact):
                        raise ValueError("lossy integer conversion")
                if new.shape != host.shape:
                    raise ValueError("shape changed")
            except (TypeError, ValueError) as e:
                raise OverrideError(
                    f"override for '{field}' does not fit its static "
                    f"shape/dtype ({host.shape}, {host.dtype}): {e}"
                ) from e
            repl[field] = jnp.asarray(np.ascontiguousarray(new))
    return dataclasses.replace(inputs, **repl)


def override_key(overrides: Optional[dict]) -> str:
    """Canonical string key of an override dict (the microbatcher's
    coalescing key: requests batch together only when they share the
    same what-if scenario)."""
    if not overrides:
        return ""
    return json.dumps(overrides, sort_keys=True, default=float)


class ServeEngine:
    """Long-lived query engine over one placed population.

    Parameters
    ----------
    sim : a built :class:`~dgen_tpu.models.simulation.Simulation` — the
        engine reuses its placed table/banks, its host-decided static
        flags (rate_switch, daylight) and its year grid. Serving pins
        ``net_billing=True`` regardless of the run-time static proof:
        an override can close a NEM gate the base scenario holds open,
        and True is numerically exact either way (the False flag is
        only ever a compile-time kernel skip).
    max_override_cache : LRU size of resolved override->ScenarioInputs
        variants (each is O(Y x G) host bytes + a few small uploads).
    """

    def __init__(self, sim: Simulation, max_override_cache: int = 128) -> None:
        if sim.mesh is not None and jax.process_count() > 1:
            raise ValueError(
                "the serving engine is single-controller; run it on one "
                "process (multi-host meshes serve via a router in front)"
            )
        self.sim = sim
        self.years = list(sim.years)
        self._year_to_idx = {int(y): i for i, y in enumerate(self.years)}
        # stable-id -> padded-table row; padding rows (mask 0) reuse
        # agent_id fill values, so only masked-in rows may claim an id
        mask = np.asarray(sim.host_mask) > 0
        ids = np.asarray(sim.host_agent_id)
        self._id_to_row: Dict[int, int] = {}
        for row in np.flatnonzero(mask):
            self._id_to_row.setdefault(int(ids[row]), int(row))
        self.n_agents = int(mask.sum())
        # quarantined agents (resilience.quarantine): their rows exist
        # but were contained at load (mask 0) — a query for one answers
        # 422 with the machine-readable reasons, never a silent-garbage
        # 200 and never an indistinguishable-from-typo 400
        rep = getattr(sim, "quarantine_report", None)
        self._quarantined: Dict[int, list] = (
            {int(a): rep.reasons_for(a) for a in rep.ids}
            if rep is not None else {}
        )
        self._static_kwargs = query_static_kwargs(sim)
        # per-cluster serving (ops.tariffcluster): a clustered sim's
        # mono-cluster buckets run the cluster's specialized program —
        # compact bank, tight n_periods — and mixed buckets fall back
        # to the full-bank program (exact either way; docs/serve.md)
        layout = getattr(sim, "_cluster_layout", None)
        self._cluster = None
        if layout is not None:
            self._cluster = dict(
                cid=layout.cluster_of_rows(),
                banks=sim._cluster_banks,
                tidx=sim._cluster_tidx,
                statics=tuple(
                    dict(self._static_kwargs, n_periods=c.n_periods,
                         rate_switch=False)
                    for c in layout.clusters
                ),
            )
        self._override_cache: "OrderedDict[str, ScenarioInputs]" = (
            OrderedDict()
        )
        self._override_lock = threading.Lock()
        self._max_override_cache = int(max_override_cache)
        # engine-free serving layers, attached after construction:
        # the precomputed zero-override answer surface (serve.surface)
        # and the cross-replica exact result cache (serve.resultcache)
        self._surface = None
        self._result_cache = None
        #: why a configured surface was refused (stale/corrupt), for
        #: /metricz — a refused surface must be VISIBLY absent, not
        #: silently absent
        self.surface_refused: Optional[str] = None
        # bucket sizes whose program has executed at least once;
        # mutated by worker threads, snapshotted under the lock (the
        # /healthz "warm" report; a report, not a guard — RetraceGuard
        # is the enforcement)
        self._warm: set = set()

    # -- engine-free layers --------------------------------------------

    @property
    def surface(self):
        """The attached :class:`~dgen_tpu.serve.surface.AnswerSurface`
        (or None): zero-override queries for covered years are served
        straight from its mmap, engine-free."""
        return self._surface

    @property
    def result_cache(self):
        """The attached :class:`~dgen_tpu.serve.resultcache.
        ResultCache` (or None)."""
        return self._result_cache

    def attach_surface(self, surface) -> None:
        # boot-time arming: called once before the HTTP server starts,
        # then read-only; the rebind itself is one GIL-atomic store
        self._surface = surface  # dgenlint: disable=C1

    def attach_result_cache(self, cache) -> None:
        # boot-time arming, same contract as attach_surface
        self._result_cache = cache  # dgenlint: disable=C1

    def serve_stats(self) -> dict:
        """Surface/cache counters for /metricz (empty when neither
        layer is attached)."""
        rec = {}
        if self._surface is not None:
            rec["surface"] = self._surface.stats()
        elif self.surface_refused:
            rec["surface_refused"] = self.surface_refused
        if self._result_cache is not None:
            rec["result_cache"] = self._result_cache.stats()
        return rec

    @property
    def warm_buckets(self) -> tuple:
        """Sorted program shapes executed so far — a SNAPSHOT (taken
        under the lock), safe to iterate from probe threads while
        worker threads warm new shapes."""
        with self._override_lock:
            return tuple(sorted(self._warm))

    # -- request plumbing ----------------------------------------------

    def rows_for(self, agent_ids: Sequence[int]) -> np.ndarray:
        """[n] int32 table rows for stable agent ids; unknown ids raise
        KeyError naming the id (a clean 4xx at the HTTP layer) and
        quarantined ids raise
        :class:`~dgen_tpu.resilience.quarantine.QuarantinedAgentError`
        (422: the row exists, its data was contained at load)."""
        from dgen_tpu.resilience.quarantine import QuarantinedAgentError

        rows = np.empty(len(agent_ids), dtype=np.int32)
        for i, a in enumerate(agent_ids):
            try:
                ai = int(a)
                # reject non-integral ids (int(17.9) == 17 would
                # silently answer for the WRONG agent)
                if ai != a:
                    raise ValueError("non-integer id")
                if ai in self._quarantined:
                    raise QuarantinedAgentError(
                        ai, self._quarantined[ai])
                rows[i] = self._id_to_row[ai]
            except QuarantinedAgentError:
                raise
            except (KeyError, TypeError, ValueError):
                raise KeyError(f"unknown agent_id {a!r}") from None
        return rows

    def year_index(self, year: Optional[int]) -> int:
        """Model-year index for a calendar year (default: first model
        year); off-grid years raise KeyError naming the grid."""
        if year is None:
            return 0
        try:
            yi = int(year)
            if yi != year:   # 2016.7 must not answer as 2016
                raise ValueError("non-integer year")
            return self._year_to_idx[yi]
        except (KeyError, TypeError, ValueError):
            raise KeyError(
                f"year {year!r} is not on the model grid {self.years}"
            ) from None

    def inputs_for(self, overrides: Optional[dict]) -> ScenarioInputs:
        """The (cached) ScenarioInputs variant for an override dict."""
        key = override_key(overrides)
        if not key:
            return self.sim.inputs
        with self._override_lock:
            cached = self._override_cache.get(key)
            if cached is not None:
                self._override_cache.move_to_end(key)
                return cached
        variant = apply_overrides(self.sim.inputs, overrides)
        with self._override_lock:
            self._override_cache[key] = variant
            while len(self._override_cache) > self._max_override_cache:
                self._override_cache.popitem(last=False)
        return variant

    # -- execution ------------------------------------------------------

    def query_rows(
        self,
        rows: np.ndarray,
        year_idx: int,
        inputs: Optional[ScenarioInputs] = None,
        bucket: Optional[int] = None,
        key: Optional[str] = None,
    ) -> Dict[str, np.ndarray]:
        """Run one bucket: table rows -> host result arrays [n, ...].

        ``bucket=None`` runs the direct single-shot program at the
        exact request shape (the parity oracle); ``bucket=B`` pads the
        rows to B (repeating the first requested row — per-row math,
        so padding rows change nothing, and the pad stays inside the
        request's tariff cluster) and slices the first n answers back
        out. The two paths are bit-identical per row.

        ``key`` is the request's canonical override key when known
        (``""`` = zero-override): it unlocks the engine-free layers —
        a zero-override query for a surface-covered year answers from
        the mmap (bit-exact at the surface's build bucket), and any
        keyed bucketed query consults/feeds the cross-replica result
        cache (hits are exact: same key + bucket + rows = same bytes).
        ``key=None`` (the default, and what every pre-existing caller
        passes) bypasses both — the compiled-engine parity oracle.
        """
        # resilience drill hooks: a device failure on the serving path
        # (the batcher must fail only this batch's futures — its worker
        # thread and the queue's load-shed/occupancy signals survive),
        # a replica dying mid-query (kill: the fleet front fails over,
        # the supervisor restarts), and a replica stalling mid-query
        # (hang: the worker sleeps, stalling every queued batch — the
        # front's forward timeout + breaker route around it)
        fault_point("serve_query")
        fault_point("serve_replica_kill")
        fault_point("serve_replica_hang")
        rows = np.asarray(rows, dtype=np.int32)
        n = rows.shape[0]
        if (
            key == ""
            and self._surface is not None
            and self._surface.covers(year_idx)
        ):
            return self._surface.lookup(rows, year_idx)
        cache_key = None
        if (
            key is not None
            and bucket is not None
            and self._result_cache is not None
        ):
            cache_key = self._result_cache.key(year_idx, key, bucket, rows)
            hit = self._result_cache.get(cache_key)
            if hit is not None:
                return hit
        if bucket is not None:
            if bucket < n:
                raise ValueError(f"bucket {bucket} < {n} requested rows")
            # pad by repeating the FIRST requested row (not table row
            # 0): per-row math, so padding changes no answer, and it
            # keeps a mono-cluster bucket mono-cluster
            fill = rows[0] if n else 0
            rows = np.concatenate(
                [rows, np.full(bucket - n, fill, dtype=np.int32)]
            )
        statics = self._static_kwargs
        tariffs = self.sim.tariffs
        operands = {}
        if self._cluster is not None and rows.size:
            cids = self._cluster["cid"][rows]
            ci = int(cids[0])
            if np.all(cids == ci):
                statics = self._cluster["statics"][ci]
                tariffs = self._cluster["banks"][ci]
                operands = dict(cluster_tidx=self._cluster["tidx"])
        out = query_program(
            self.sim.table, self.sim.profiles, tariffs,
            inputs if inputs is not None else self.sim.inputs,
            jnp.asarray(rows), jnp.asarray(year_idx, dtype=jnp.int32),
            **statics, **operands,
        )
        with self._override_lock:
            self._warm.add(int(rows.shape[0]))
        host = jax.device_get(out)
        res = {
            f: np.asarray(getattr(host, f))[:n] for f in QUERY_FIELDS
        }
        if cache_key is not None:
            self._result_cache.put(cache_key, res)
        return res

    def query(
        self,
        agent_ids: Sequence[int],
        year: Optional[int] = None,
        overrides: Optional[dict] = None,
        bucket: Optional[int] = None,
    ) -> Dict[str, np.ndarray]:
        """Convenience single-shot query by stable agent id (the
        microbatcher is the production path; this is the direct one).
        Bypasses the surface/cache layers: this is the parity oracle
        the engine-free paths are proven bit-exact against."""
        return self.query_rows(
            self.rows_for(agent_ids),
            self.year_index(year),
            inputs=self.inputs_for(overrides),
            bucket=bucket,
        )

    def warmup(self, buckets: Sequence[int], year_idx: int = 0) -> None:
        """Compile (and execute once) every bucket program so no live
        request pays a compile. Row content is irrelevant to the
        compiled shape; row 0 repeated is enough — except under a
        clustered sim, where each cluster owns a specialized program
        (warm one representative bucket per cluster) and mixed buckets
        compile the full-bank fallback (warm one of those too)."""
        reps = [0]
        mixed = None
        if self._cluster is not None:
            cid = self._cluster["cid"]
            reps = [int(np.flatnonzero(cid == ci)[0])
                    for ci in range(len(self._cluster["banks"]))]
            if len(reps) > 1:
                mixed = reps[:2]
        for b in buckets:
            for r in reps:
                self.query_rows(
                    np.full(b, r, dtype=np.int32), year_idx, bucket=None
                )
            if mixed is not None and b > 1:
                self.query_rows(
                    np.asarray(mixed * (b // 2) + [mixed[0]] * (b % 2),
                               dtype=np.int32),
                    year_idx, bucket=None,
                )
            logger.info("serve warmup: bucket %d compiled", b)
