"""dgen_tpu.serve: online what-if query engine.

The first request/response layer of the codebase — the bridge from
"reproduce the paper's batch runs" to the north star's "serve heavy
traffic": a long-lived process loads a placed agent table + profile
banks once and answers ad-hoc per-agent queries (optimal PV+storage
size, bill savings, NPV/payback, scenario-override deltas) through
fixed-shape, microbatched device programs.

    from dgen_tpu.serve import ServeEngine, Microbatcher
    engine = ServeEngine(sim)                # reuse a built Simulation
    bat = Microbatcher(engine)               # pow2 buckets, deadline flush
    bat.query([17, 203], year=2026,
              overrides={"scale": {"itc_fraction": 0.0}})

HTTP front-end: ``python -m dgen_tpu.serve`` (see docs/serve.md).
Fleet: ``python -m dgen_tpu.serve --fleet N`` — a
:class:`~dgen_tpu.serve.fleet.ReplicaSupervisor` keeps N replica
processes alive and readiness-gated behind a
:class:`~dgen_tpu.serve.front.FleetFront` that round-robins, breaks
circuits, sheds load, and drains gracefully (docs/serve.md "Fleet
operations").

Production throughput (docs/serve.md "Production throughput"): the
zero-override default question serves engine-free from a precomputed,
provenance-gated, memory-mapped :class:`~dgen_tpu.serve.surface.
AnswerSurface` (``--build-surface`` / ``--surface``); repeated
what-ifs hit the cross-replica exact
:class:`~dgen_tpu.serve.resultcache.ResultCache` (``--cache-dir``);
and the :class:`~dgen_tpu.serve.autoscale.Autoscaler`
(``--autoscale``) grows/drains the fleet from the aggregated
occupancy signal.
"""

from dgen_tpu.serve.autoscale import Autoscaler  # noqa: F401
from dgen_tpu.serve.batcher import Microbatcher, QueueFullError  # noqa: F401
from dgen_tpu.serve.engine import (  # noqa: F401
    QUERY_FIELDS,
    OverrideError,
    QueryOutputs,
    ServeEngine,
    apply_overrides,
    override_key,
    query_program,
)
from dgen_tpu.serve.fleet import (  # noqa: F401
    HTTPPool,
    ReplicaSupervisor,
    default_replica_cmd,
)
from dgen_tpu.serve.front import CircuitBreaker, FleetFront  # noqa: F401
from dgen_tpu.serve.resultcache import ResultCache  # noqa: F401
from dgen_tpu.serve.surface import (  # noqa: F401
    AnswerSurface,
    StaleSurfaceError,
    SurfaceError,
    build_surface,
)
