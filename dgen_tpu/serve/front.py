"""The fleet's routing front: round-robin over READY replicas, with
per-replica circuit breakers, bounded failover retry, load shedding,
and graceful drain.

One tiny HTTP process sits in front of the
:class:`~dgen_tpu.serve.fleet.ReplicaSupervisor`'s N replicas and owns
the client-facing contract:

* **Routing** — ``POST /query`` round-robins over replicas that are
  READY *and* whose breaker admits traffic.  A forward failure
  (connect refused/reset, forward timeout, replica 5xx) is retried
  exactly once on a *different* replica — safe because every query is
  idempotent (a pure function of banks/inputs/agent/year; see
  docs/serve.md) — then surfaces as 503 + Retry-After.  The front
  never answers 502/504: every terminal failure is a retryable 503,
  so a well-behaved client's only failure mode is a bounded retry
  loop.
* **Circuit breakers** — ``FleetConfig.breaker_failures`` consecutive
  failures OPEN a replica's breaker (no traffic); after
  ``breaker_cooldown_s`` one HALF_OPEN probe request is admitted —
  success closes the breaker, failure re-opens it.  This takes a hung
  replica out of rotation after a handful of timeouts instead of
  paying the timeout on every request.
* **Load shedding** — a scrape thread aggregates replica ``/metricz``
  every ``metricz_interval_s``; when summed queue depth exceeds
  ``shed_queue_frac`` of summed queue capacity, new queries are shed
  with 503 + Retry-After *at the front*, before they cost a forward.
  Shedding beats collapse: the fleet's queues stay bounded, p99 stays
  a queue wait instead of a timeout.
* **Drain** — SIGTERM (or :func:`drain_front`) stops admitting
  queries (503 + Retry-After, ``/readyz`` red), waits for in-flight
  forwards, then SIGTERMs the replicas (each drains its own batches)
  and exits.

The ``front_route`` fault site fires on every forward attempt, so the
fleet drill can inject routing-layer failures and assert the breaker +
retry machinery heals them.
"""

from __future__ import annotations

import itertools
import json
import signal
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Dict, Optional

from dgen_tpu.config import FleetConfig
from dgen_tpu.resilience.faults import FaultError, fault_point
from dgen_tpu.serve.fleet import (
    HTTP_ERRORS,
    HTTPPool,
    ReplicaSupervisor,
    http_json,
)
from dgen_tpu.serve.server import InflightTracker, _JsonHandler
from dgen_tpu.utils import timing
from dgen_tpu.utils.logging import get_logger

logger = get_logger()

# breaker states
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """Per-replica admission state machine (thread-safe).

    CLOSED: traffic flows; ``failures_to_open`` *consecutive* failures
    trip it OPEN.  OPEN: no traffic until ``cooldown_s`` elapses, then
    exactly ONE probe request is admitted (HALF_OPEN).  Probe success
    → CLOSED (counter reset); probe failure → OPEN again with a fresh
    cooldown.  ``clock`` is injectable so tests drive time."""

    def __init__(self, failures_to_open: int = 3, cooldown_s: float = 1.0,
                 clock=time.monotonic) -> None:
        self.failures_to_open = failures_to_open
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at: Optional[float] = None
        self.n_opened = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May a request be routed here now?  Mutating: an OPEN breaker
        past its cooldown transitions to HALF_OPEN and admits exactly
        one probe — call it only on the replica actually being picked."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if (self._clock() - self._opened_at) >= self.cooldown_s:
                    self._state = HALF_OPEN
                    return True   # the one probe
                return False
            return False          # HALF_OPEN: probe already in flight

    def record_success(self) -> None:
        with self._lock:
            self._state = CLOSED
            self._consecutive = 0
            self._opened_at = None

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            if (self._state == HALF_OPEN
                    or self._consecutive >= self.failures_to_open):
                if self._state != OPEN:
                    self.n_opened += 1
                self._state = OPEN
                self._opened_at = self._clock()

    def to_json(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive,
                "times_opened": self.n_opened,
            }


class FleetFront:
    """Routing + shedding + drain over a supervisor's replicas (module
    docstring).  Transport-independent: :meth:`route_query` takes and
    returns bytes, so it is unit-testable without sockets and the
    handler stays a thin shell."""

    def __init__(self, supervisor: ReplicaSupervisor,
                 config: Optional[FleetConfig] = None) -> None:
        self.sup = supervisor
        self.config = config or supervisor.config
        self.t_start = time.time()
        self._drain = InflightTracker()
        self._breakers: Dict[int, CircuitBreaker] = {}
        self._rr = itertools.count()
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self._scrape_thread: Optional[threading.Thread] = None
        #: pooled keep-alive connections for forwards + scrapes: the
        #: steady-state front->replica hop pays no TCP handshake
        self._pool = HTTPPool()
        #: replica index -> (monotonic scrape time, /metricz payload)
        self._metricz: Dict[int, tuple] = {}
        #: replica index -> (scrape time, batches, occupancy-sum) at
        #: the previous pressure() call — the windowed-occupancy
        #: baseline (pruned with the other per-replica maps); held
        #: value covers ticks between scrapes
        self._occ_prev: Dict[int, tuple] = {}
        self._held_occupancy = 0.0
        self._lat = timing.LogHistogram()
        # counters (under _lock)
        self.n_requests = 0
        self.n_shed = 0
        self.n_drained = 0
        self.n_retries = 0
        self.n_forward_failures = 0
        self.n_unrouted = 0

    # -- breakers ------------------------------------------------------

    def breaker(self, index: int) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(index)
            if br is None:
                br = CircuitBreaker(
                    failures_to_open=self.config.breaker_failures,
                    cooldown_s=self.config.breaker_cooldown_s,
                )
                self._breakers[index] = br
            return br

    # -- scrape / shed -------------------------------------------------

    def start(self) -> "FleetFront":
        self._scrape_thread = threading.Thread(
            target=self._scrape_loop, name="dgen-front-scrape",
            daemon=True,
        )
        self._scrape_thread.start()
        return self

    def _scrape_loop(self) -> None:
        while not self._closed.is_set():
            ready = self.sup.ready_handles()
            for h in ready:
                payload = self._scrape_one(h.port)
                if payload is not None:
                    self._metricz[h.index] = (time.monotonic(), payload)
            self._prune_replica_state(ready)
            time.sleep(self.config.metricz_interval_s)

    def _scrape_one(self, port: int) -> Optional[dict]:
        try:
            status, blob, _ = http_json(
                port, "/metricz", timeout=2.0, pool=self._pool)
            if status != 200:
                return None
            return json.loads(blob)
        except HTTP_ERRORS:
            return None

    def _prune_replica_state(self, ready) -> None:
        """Autoscale hygiene: per-replica state keyed by index must
        not accumulate forever as replicas are added and retired over
        a long-lived fleet — drop scrapes, breakers, and pooled
        sockets of slots that no longer exist or were STOPPED."""
        gone_ports = self.sup.stopped_ports()
        live = self.sup.live_indices()
        for i in [i for i in list(self._metricz) if i not in live]:
            self._metricz.pop(i, None)
        with self._lock:
            # _occ_prev is also written by pressure() on the control
            # thread — pruning races with the window update otherwise
            for i in [i for i in list(self._occ_prev) if i not in live]:
                self._occ_prev.pop(i, None)
            for i in [i for i in self._breakers if i not in live]:
                del self._breakers[i]
        for port in gone_ports:
            self._pool.drop(port)

    def _fresh_metricz(self) -> Dict[int, dict]:
        """Scrapes younger than 3 intervals, restricted to replicas
        that are READY right now."""
        now = time.monotonic()
        horizon = 3.0 * self.config.metricz_interval_s
        ready = {h.index for h in self.sup.ready_handles()}
        # dict() is one atomic C-level copy under the GIL: the scrape
        # thread may insert concurrently, and iterating the live dict
        # here would raise "changed size during iteration" mid-request
        snap = dict(self._metricz)
        return {
            i: p for i, (t, p) in snap.items()
            if i in ready and (now - t) <= horizon
        }

    def shed_now(self) -> bool:
        """Occupancy-driven load shedding: aggregate queue depth over
        READY replicas vs aggregate capacity.  No fresh signal = no
        shedding (never shed blind)."""
        fresh = self._fresh_metricz()
        if not fresh:
            return False
        depth = sum(int(p.get("queue_depth", 0)) for p in fresh.values())
        cap = sum(int(p.get("max_queue", 0)) for p in fresh.values())
        return cap > 0 and depth >= self.config.shed_queue_frac * cap

    def pressure(self) -> Optional[dict]:
        """The autoscaler's aggregated signal: instantaneous queue
        fraction plus WINDOWED batch occupancy (batches dispatched
        since the previous ``pressure()`` call, weighted by their
        occupancy) over fresh READY-replica scrapes.  Windowing
        matters: the replicas report lifetime occupancy means, and a
        lifetime mean never decays — an idle fleet would look busy
        forever.  Zero new batches in the window = zero occupancy
        (no device work IS idle).  None when no fresh signal exists
        (the autoscaler then holds — never scale blind, the same rule
        as shedding)."""
        now = time.monotonic()
        horizon = 3.0 * self.config.metricz_interval_s
        ready = {h.index for h in self.sup.ready_handles()}
        snap = dict(self._metricz)
        fresh = {
            i: (t, p) for i, (t, p) in snap.items()
            if i in ready and (now - t) <= horizon
        }
        if not fresh:
            return None
        depth = sum(
            int(p.get("queue_depth", 0)) for _t, p in fresh.values())
        cap = sum(
            int(p.get("max_queue", 0)) for _t, p in fresh.values())
        # occupancy over batches dispatched since the last NEW scrape;
        # ticks between scrapes HOLD the previous value instead of
        # reading "no new data yet" as idleness (the controller may
        # tick faster than the scrape cadence)
        d_batches = 0
        d_occ_sum = 0.0
        saw_new_scrape = False
        with self._lock:   # vs the scrape thread's _prune_replica_state
            for i, (t, p) in fresh.items():
                prev_t, pb, po = self._occ_prev.get(i, (None, 0, 0.0))
                if prev_t is not None and t == prev_t:
                    continue   # same scrape as last pressure() call
                saw_new_scrape = True
                batches = int(p.get("batches", 0) or 0)
                occ_sum = float(p.get("batch_occupancy") or 0.0) * batches
                if batches >= pb:   # a restarted replica resets counters
                    d_batches += batches - pb
                    d_occ_sum += occ_sum - po
                self._occ_prev[i] = (t, batches, occ_sum)
            if saw_new_scrape:
                occ = (d_occ_sum / d_batches) if d_batches > 0 else 0.0
                self._held_occupancy = max(occ, 0.0)
            held = self._held_occupancy
        return {
            "queue_frac": (depth / cap) if cap else 0.0,
            "occupancy": held,
            "window_batches": d_batches,
            "ready_replicas": len(fresh),
        }

    # -- routing -------------------------------------------------------

    def _pick(self, exclude: set):
        """Next routable replica in round-robin order, honoring
        breakers.  ``allow()`` is only called on the candidate actually
        being picked (a HALF_OPEN probe slot must not be consumed by
        mere consideration)."""
        handles = sorted(
            (h for h in self.sup.ready_handles()
             if h.index not in exclude),
            key=lambda h: h.index,
        )
        if not handles:
            return None
        start = next(self._rr)
        for k in range(len(handles)):
            h = handles[(start + k) % len(handles)]
            if self.breaker(h.index).allow():
                return h
        return None

    def _forward(self, h, raw: bytes) -> tuple:
        status, blob, _ = http_json(
            h.port, "/query", method="POST", body=raw,
            timeout=self.config.request_timeout_s, pool=self._pool,
        )
        return status, blob

    @staticmethod
    def _blob(payload: dict) -> bytes:
        return json.dumps(payload).encode("utf-8")

    def _retry_after(self) -> Dict[str, str]:
        return {"Retry-After": str(
            int(self.config.retry_after_s)
            if float(self.config.retry_after_s).is_integer()
            else self.config.retry_after_s
        )}

    def route_query(self, raw: bytes) -> tuple:
        """(status, body bytes, extra headers) for one client /query.
        Replica answers (200 and 4xx alike) pass through byte-for-byte;
        front-generated failures are always retryable 503s."""
        t0 = time.monotonic()
        with self._lock:
            self.n_requests += 1
        if self.draining:
            with self._lock:
                self.n_drained += 1
            return 503, self._blob(
                {"error": "fleet is draining", "retry": True,
                 "draining": True}
            ), self._retry_after()
        if self.shed_now():
            with self._lock:
                self.n_shed += 1
            return 503, self._blob(
                {"error": "fleet overloaded; shedding load",
                 "retry": True, "shed": True}
            ), self._retry_after()
        self._drain.enter()
        try:
            tried: set = set()
            last_err = None
            for attempt in range(2):   # initial + ONE other-replica retry
                h = self._pick(tried)
                if h is None:
                    break
                tried.add(h.index)
                if attempt > 0:
                    with self._lock:
                        self.n_retries += 1
                br = self.breaker(h.index)
                try:
                    # drill hook: a routing-layer forward failure
                    # (connect refused/reset before the replica saw
                    # anything) — must count against THIS replica's
                    # breaker and fail over like any transport error
                    fault_point("front_route")
                    code, blob = self._forward(h, raw)
                except (FaultError, *HTTP_ERRORS) as e:
                    br.record_failure()
                    with self._lock:
                        self.n_forward_failures += 1
                    last_err = f"{type(e).__name__}: {e}"
                    continue
                if code == 503:
                    # replica alive but shedding/draining: not a breaker
                    # failure; prefer another replica, else surface it
                    br.record_success()
                    last_err = "replica 503"
                    continue
                if code >= 500:
                    br.record_failure()
                    with self._lock:
                        self.n_forward_failures += 1
                    last_err = f"replica {code}"
                    continue
                br.record_success()
                self._lat.record(time.monotonic() - t0)
                return code, blob, {}
            with self._lock:
                self.n_unrouted += 1
            self._lat.record(time.monotonic() - t0)
            return 503, self._blob(
                {"error": "no replica available", "retry": True,
                 "detail": last_err}
            ), self._retry_after()
        finally:
            self._drain.exit()

    # -- probe endpoints -----------------------------------------------

    def healthz(self) -> dict:
        return {
            "status": "ok",
            "live": True,
            "role": "fleet-front",
            "ready": bool(self.sup.ready_handles()) and not self.draining,
            "draining": self.draining,
            "uptime_s": round(time.time() - self.t_start, 1),
            "replicas": [
                {**h.summary(), "breaker": self.breaker(h.index).to_json()}
                for h in self.sup.replicas
            ],
            "events_tail": list(self.sup.events)[-20:],
        }

    def readyz(self) -> tuple:
        ok = bool(self.sup.ready_handles()) and not self.draining
        return (200 if ok else 503), {
            "ready": ok,
            "ready_replicas": len(self.sup.ready_handles()),
            "draining": self.draining,
        }

    def metricz(self) -> dict:
        """Fleet-aggregated metrics: summed queue depths, weighted
        occupancy, per-replica breaker state + last /metricz scrape."""
        fresh = self._fresh_metricz()
        depth = sum(int(p.get("queue_depth", 0)) for p in fresh.values())
        cap = sum(int(p.get("max_queue", 0)) for p in fresh.values())
        w_occ = None
        batches = sum(
            int(p.get("batches", 0) or 0) for p in fresh.values())
        if batches:
            w_occ = sum(
                float(p.get("batch_occupancy") or 0.0)
                * int(p.get("batches", 0) or 0)
                for p in fresh.values()
            ) / batches
        with self._lock:
            counters = {
                "requests": self.n_requests,
                "shed": self.n_shed,
                "drained": self.n_drained,
                "retries": self.n_retries,
                "forward_failures": self.n_forward_failures,
                "unrouted": self.n_unrouted,
            }
        # engine-free-path counters, aggregated: the bench's surface
        # hit-rate / cache hit-rate stamps read these
        surface_hits = sum(
            int(p.get("surface_hits", 0) or 0) for p in fresh.values())
        cache = {"hits": 0, "misses": 0, "stores": 0, "evictions": 0}
        for p in fresh.values():
            rc = p.get("result_cache") or {}
            for k in cache:
                cache[k] += int(rc.get(k, 0) or 0)
        snap = self._lat.snapshot()
        return {
            "role": "fleet-front",
            "ready_replicas": len(self.sup.ready_handles()),
            "n_replicas": self.sup.live_count(),
            "queue_depth": depth,
            "queue_capacity": cap,
            "surface_hits": surface_hits,
            "result_cache": cache,
            "http_pool": self._pool.stats(),
            "occupancy_weighted": (
                round(w_occ, 4) if w_occ is not None else None),
            "draining": self.draining,
            "shedding": self.shed_now(),
            **counters,
            "latency_ms": {
                "p50": round(snap["p50"] * 1e3, 3),
                "p90": round(snap["p90"] * 1e3, 3),
                "p99": round(snap["p99"] * 1e3, 3),
                "count": snap["count"],
            },
            "replicas": {
                str(h.index): {
                    "state": h.state,
                    "breaker": self.breaker(h.index).to_json(),
                    "metricz": fresh.get(h.index),
                }
                for h in self.sup.replicas
            },
        }

    # -- drain / shutdown ----------------------------------------------

    @property
    def draining(self) -> bool:
        return self._drain.draining

    @property
    def inflight(self) -> int:
        return self._drain.inflight

    def begin_drain(self) -> None:
        self._drain.begin_drain()

    def wait_idle(self, timeout: float = 30.0) -> bool:
        return self._drain.wait_idle(timeout)

    def close(self) -> None:
        self._closed.set()
        self._pool.close()


class _FrontHandler(_JsonHandler):
    """Thin HTTP shell over :class:`FleetFront`."""

    @property
    def front(self) -> FleetFront:
        return self.server.front  # type: ignore[attr-defined]

    def _socket_timeout_s(self) -> float:
        # a front request spans up to two forward attempts
        return 2.0 * self.front.config.request_timeout_s + 5.0

    def do_GET(self) -> None:  # noqa: N802 — http.server contract
        if self.path == "/healthz":
            self._send(200, self.front.healthz())
        elif self.path == "/readyz":
            code, payload = self.front.readyz()
            self._send(code, payload)
        elif self.path == "/metricz":
            self._send(200, self.front.metricz())
        else:
            self._send(404, {"error": f"no route {self.path}"})

    def do_POST(self) -> None:  # noqa: N802 — http.server contract
        raw = self._read_body()
        if raw is None:
            return
        if self.path != "/query":
            self._send(404, {"error": f"no route {self.path}"})
            return
        code, blob, headers = self.front.route_query(raw)
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(blob)))
        for k, v in headers.items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(blob)


def make_front_server(front: FleetFront) -> ThreadingHTTPServer:
    """Bind the front's HTTP server (port 0 = ephemeral)."""
    srv = ThreadingHTTPServer(
        (front.config.host, front.config.port), _FrontHandler
    )
    srv.front = front  # type: ignore[attr-defined]
    return srv


def start_front_in_thread(front: FleetFront) -> ThreadingHTTPServer:
    srv = make_front_server(front)
    t = threading.Thread(
        target=srv.serve_forever, name="dgen-front-http", daemon=True
    )
    t.start()
    return srv


def drain_front(front: FleetFront, srv: ThreadingHTTPServer,
                stop_fleet: bool = True,
                timeout: Optional[float] = None) -> bool:
    """Fleet-wide graceful drain: stop admitting at the front, wait for
    in-flight forwards, SIGTERM the replicas (each drains its own
    batches), stop the accept loop."""
    timeout = timeout if timeout is not None else (
        front.config.drain_timeout_s)
    front.begin_drain()
    idle = front.wait_idle(timeout)
    if stop_fleet:
        front.sup.stop(drain=True, timeout=timeout)
    front.close()
    srv.shutdown()
    return idle


def install_sigterm_drain_front(front: FleetFront,
                                srv: ThreadingHTTPServer) -> None:
    """SIGTERM = drain the whole fleet.  Main-thread only (CPython
    signal contract); the drain runs on a helper thread."""

    def _on_term(signum, frame) -> None:
        logger.info("fleet front: SIGTERM — draining fleet")
        threading.Thread(
            target=drain_front, args=(front, srv),
            name="dgen-front-drain", daemon=True,
        ).start()

    signal.signal(signal.SIGTERM, _on_term)
