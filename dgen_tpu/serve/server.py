"""Threaded JSON-over-HTTP front-end for the serving engine.

Stdlib-only (http.server) by design: the repo's hard dependency set
stays jax+numpy, and the endpoint shape — one POST route, three GET
probes — does not need a framework. One process serves:

  * ``POST /query``   {"agent_ids": [...], "year": 2026,
                       "overrides": {"scale": {"itc_fraction": 0.5}},
                       "cash_flow": false}
                      -> {"year": ..., "results": [{...} per agent]}
  * ``GET  /healthz`` LIVENESS: the process is up and answering, plus
                      the shared provenance stamp
                      (io.export.provenance_stamp: git sha, config
                      hash, backend), replica identity, warm bucket
                      shapes, and the boot report (warmup wall,
                      compile-cache hit/miss counts)
  * ``GET  /readyz``  READINESS: 200 only once warmup completed and
                      warm_buckets is non-empty (and the process is
                      not draining) — the signal the fleet front and
                      any external LB route on.  Liveness != readiness:
                      a booting replica is alive but unroutable.
  * ``GET  /metricz`` lifetime serving stats: p50/p99 request latency,
                      queue depth, batch occupancy (utils.timing
                      histograms + Microbatcher counters), replica
                      identity, steady-state compile counts.

Handlers never build programs (dgenlint L10): every device program was
compiled at engine warmup; a handler only validates, enqueues, and
formats.

Timeout discipline (the first satellite of the fleet PR): every way a
request can wedge a handler thread is bounded —

  * a client that never finishes sending (or never reads) trips the
    per-connection socket timeout (``ServeConfig.socket_timeout_s``);
  * a hung engine call trips the per-request deadline
    (``ServeConfig.request_timeout_s``) and answers **504**, with the
    still-queued future cancelled so the stalled work is dropped, not
    executed after the stall clears.

Graceful drain (reused by the fleet): :func:`drain` flips the app to
draining (new queries answer 503 + Retry-After and ``/readyz`` goes
red so routers stop sending), waits for in-flight requests, flushes
the batcher's queued batches, then stops the accept loop.
:func:`install_sigterm_drain` wires that to SIGTERM for the CLI.
"""

from __future__ import annotations

import json
import math
import os
import signal
import threading
import time
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

import numpy as np

from dgen_tpu.config import ServeConfig
from dgen_tpu.io.export import provenance_stamp
from dgen_tpu.resilience.quarantine import QuarantinedAgentError
from dgen_tpu.serve.batcher import Microbatcher, QueueFullError
from dgen_tpu.serve.engine import QUERY_FIELDS, OverrideError, ServeEngine
from dgen_tpu.utils import compilecache, timing
from dgen_tpu.utils.logging import get_logger

logger = get_logger()

#: request-body cap: a /query of max_batch agents with overrides is a
#: few KB; anything near this is malformed or hostile
_MAX_BODY_BYTES = 1 << 20

#: Retry-After stamped on a single replica's 503s (queue full, drain);
#: the fleet front has its own knob (FleetConfig.retry_after_s)
_RETRY_AFTER_S = 1

#: env var carrying the replica index into a fleet-spawned process
#: (set by serve.fleet; surfaces in /healthz and /metricz identity)
REPLICA_ENV = "DGEN_TPU_SERVE_REPLICA"


class DrainingError(RuntimeError):
    """The process is draining: no new queries are admitted; clients
    should retry against another replica (HTTP 503 + Retry-After)."""


class InflightTracker:
    """Drain bookkeeping shared by the replica app and the fleet
    front: count in-flight requests, flip a draining flag, and wait
    (bounded) for the count to reach zero."""

    def __init__(self) -> None:
        self.draining = False
        self._inflight = 0
        self._cv = threading.Condition()

    def begin_drain(self) -> None:
        self.draining = True

    def enter(self) -> None:
        with self._cv:
            self._inflight += 1

    def exit(self) -> None:
        with self._cv:
            self._inflight -= 1
            self._cv.notify_all()

    @property
    def inflight(self) -> int:
        with self._cv:
            return self._inflight

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until nothing is in flight (True) or the timeout
        lapses (False — the caller exits anyway; drain is bounded)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(remaining)
        return True


def _num(v) -> "float | None":
    """JSON-safe float: non-finite values become null (json.dumps
    would otherwise emit bare NaN/Infinity tokens, which strict JSON
    parsers reject)."""
    f = float(v)
    return f if math.isfinite(f) else None


def _rows_to_json(out: Dict[str, np.ndarray], cash_flow: bool) -> list:
    """Columnar engine results -> per-agent JSON rows."""
    n = out["agent_id"].shape[0]
    rows = []
    for i in range(n):
        row = {}
        for f in QUERY_FIELDS:
            if f == "cash_flow":
                if cash_flow:
                    row[f] = [_num(x) for x in out[f][i]]
                continue
            v = out[f][i]
            row[f] = int(v) if f == "agent_id" else _num(v)
        rows.append(row)
    return rows


def _env_replica_index() -> Optional[int]:
    raw = os.environ.get(REPLICA_ENV, "").strip()
    try:
        return int(raw) if raw else None
    except ValueError:
        return None


class ServeApp:
    """The server's state: engine + batcher + provenance, shared by
    every handler thread.

    ``defer_warmup=True`` skips warmup at construction so the HTTP
    socket can bind (and /healthz answer) while bucket programs are
    still compiling — the caller then runs :meth:`warmup_now` (usually
    on a thread; the replica CLI does).  ``/readyz`` stays 503 until
    warmup completes: liveness != readiness.
    """

    def __init__(
        self,
        engine: ServeEngine,
        config: Optional[ServeConfig] = None,
        provenance: Optional[dict] = None,
        replica_index: Optional[int] = None,
        defer_warmup: bool = False,
    ) -> None:
        self.engine = engine
        self.config = config or ServeConfig()
        self.batcher = Microbatcher(engine, self.config)
        self.t_start = time.time()
        self.replica_index = (
            replica_index if replica_index is not None
            else _env_replica_index()
        )
        self._drain = InflightTracker()
        self.boot_report: dict = {}
        self._warmup_done = not self.config.warmup
        self._steady_guard = None
        self._closed = False
        # one stamp at construction: /healthz must stay allocation-free
        # and subprocess-free per probe
        self.provenance = provenance if provenance is not None else (
            provenance_stamp(
                engine.sim.run_config, engine.sim.scenario, self.config,
            )
        )
        if self.config.warmup and not defer_warmup:
            self.warmup_now()
        elif self._warmup_done:
            # warmup disabled (debug): steady-state compiles are then
            # an honest >0 — count them from the start
            self._arm_steady_guard()

    # -- boot ----------------------------------------------------------

    def warmup_now(self) -> None:
        """Compile/load every bucket program, recording the boot report
        (warmup wall + compile-cache hit/miss counts — ``hits ==
        requests`` proves a shared-cache fast boot: nothing was
        compiled, every program deserialized from the cache a sibling
        replica or previous incarnation populated).  Idempotent."""
        if self._warmup_done:
            return
        t0 = time.time()
        with compilecache.HitCounter() as hc:
            self.engine.warmup(self.config.buckets)
        wall = time.time() - t0
        self.boot_report = {
            "warmup_s": round(wall, 3),
            "buckets": list(self.config.buckets),
            "compile_cache": {
                **hc.to_json(),
                "dir": (compilecache.stats() or {}).get("dir"),
            },
        }
        self._warmup_done = True
        self._arm_steady_guard()
        logger.info(
            "serve warmup: %d bucket programs in %.1fs "
            "(cache hits %d / misses %d)",
            len(self.config.buckets), wall, hc.hits, hc.misses,
        )

    def _arm_steady_guard(self) -> None:
        """Count (never fail on) post-warmup compiles/traces; /metricz
        reports them so the fleet drill can assert the zero-steady-
        state-compile invariant on every replica from outside."""
        from dgen_tpu.lint.guard import RetraceGuard

        self._steady_guard = RetraceGuard(
            max_compiles=1 << 30, max_traces=None,
            context="serve steady state",
        ).start()

    @property
    def ready(self) -> bool:
        """Routable: warmup complete, at least one warm bucket program,
        and not draining.  (Liveness is 'the process answers /healthz';
        this is the stricter signal the front routes on.)"""
        return (
            self._warmup_done
            and bool(self.engine.warm_buckets)
            and not self.draining
        )

    # -- endpoint bodies (transport-independent, unit-testable) --------

    def identity(self) -> dict:
        """Who is answering: stamped into /healthz and /metricz so a
        fleet operator can tell replicas apart."""
        return {
            "pid": os.getpid(),
            "replica_index": self.replica_index,
            "boot_time_unix": round(self.t_start, 3),
            "uptime_s": round(time.time() - self.t_start, 1),
        }

    def healthz(self) -> dict:
        return {
            "status": "ok",
            "live": True,
            "ready": self.ready,
            "draining": self.draining,
            "n_agents": self.engine.n_agents,
            "years": self.engine.years,
            "buckets": list(self.config.buckets),
            "warm_buckets": sorted(self.engine.warm_buckets),
            "boot": self.boot_report,
            **self.identity(),
            **self.provenance,
        }

    def readyz(self) -> tuple:
        """(status_code, payload): 200 only when routable."""
        ok = self.ready
        return (200 if ok else 503), {
            "ready": ok,
            "draining": self.draining,
            "warmup_done": self._warmup_done,
            "warm_buckets": sorted(self.engine.warm_buckets),
        }

    def metricz(self) -> dict:
        rec = self.batcher.stats()
        batch = timing.histogram("serve_batch")
        if batch is not None:
            snap = batch.snapshot()
            rec["batch_wall_ms"] = {
                "p50": round(snap["p50"] * 1e3, 3),
                "p99": round(snap["p99"] * 1e3, 3),
                "count": snap["count"],
            }
        rec.update(self.identity())
        rec["draining"] = self.draining
        if self._steady_guard is not None:
            rec["steady_state_compiles"] = self._steady_guard.n_compiles
            rec["steady_state_traces"] = self._steady_guard.n_traces
        # an armed fault registry (drills) reports what actually fired,
        # so the fleet drill can confirm its injection from outside
        from dgen_tpu.resilience import faults as faults_mod

        reg = faults_mod.active()
        if reg is not None:
            rec["faults_fired"] = {
                s: reg.fired(s) for s in faults_mod.SITES
                if reg.fired(s)
            }
        return rec

    @property
    def draining(self) -> bool:
        return self._drain.draining

    def run_query(self, body: dict) -> dict:
        if self.draining:
            raise DrainingError(
                "replica is draining; retry against another replica"
            )
        self._drain.enter()
        try:
            agent_ids = body.get("agent_ids")
            if not isinstance(agent_ids, list) or not agent_ids:
                raise ValueError("'agent_ids' must be a non-empty list")
            year = body.get("year")
            overrides = body.get("overrides")
            fut = self.batcher.submit(agent_ids, year, overrides)
            try:
                out = fut.result(self.config.request_timeout_s)
            except FutureTimeout:
                # the client gets a 504 either way; cancel so a request
                # still QUEUED is dropped instead of executed after the
                # stall clears (double work exactly at the overload
                # point)
                fut.cancel()
                raise
            return {
                "year": self.engine.years[self.engine.year_index(year)],
                "results": _rows_to_json(out, bool(body.get("cash_flow"))),
            }
        finally:
            self._drain.exit()

    # -- drain / shutdown ----------------------------------------------

    def begin_drain(self) -> None:
        """Stop admitting new queries (503 + Retry-After; /readyz goes
        red so routers stop sending).  In-flight requests keep running;
        :meth:`wait_idle` + :meth:`close` finish the job."""
        self._drain.begin_drain()

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until no request is in flight (True) or the timeout
        lapses (False — the caller exits anyway; drain is bounded)."""
        return self._drain.wait_idle(timeout)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.batcher.close()
        if self._steady_guard is not None:
            self._steady_guard.stop()

    @property
    def inflight(self) -> int:
        return self._drain.inflight


class _JsonHandler(BaseHTTPRequestHandler):
    """Shared JSON plumbing for the replica handler and the fleet
    front's handler: per-connection socket timeout, JSON responses
    with optional extra headers, quiet logging."""

    protocol_version = "HTTP/1.1"

    #: Nagle + delayed ACK costs ~40 ms per hop on loopback keep-alive
    #: POSTs (http.client writes headers and body separately); the
    #: client side sets TCP_NODELAY too (fleet._NoDelayHTTPConnection)
    disable_nagle_algorithm = True

    #: overridden per-app in setup(); BaseHTTPRequestHandler applies it
    #: as the connection's socket timeout
    timeout = 30.0

    def _socket_timeout_s(self) -> float:
        return self.timeout

    def setup(self) -> None:
        # a client that stops sending mid-body (or never reads its
        # response) must release this handler thread: the socket
        # timeout bounds every rfile.read/wfile.write
        self.timeout = self._socket_timeout_s()
        super().setup()

    def _send(self, code: int, payload: dict, close: bool = False,
              headers: Optional[Dict[str, str]] = None) -> None:
        blob = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(blob)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        if close:
            # advertises the close AND sets self.close_connection
            self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(blob)

    def _read_body(self, route_check=None) -> Optional[bytes]:
        """Read (or refuse) a POST body BEFORE routing: any response
        sent with unread body bytes on a keep-alive connection desyncs
        the stream (the leftover bytes parse as the next request line)
        — refusal paths therefore close the connection explicitly.
        Returns None when a refusal was already sent."""
        if self.headers.get("Transfer-Encoding"):
            # chunked bodies are not length-delimited; refuse + close
            # rather than leave chunk framing in the stream
            self._send(411, {"error": "Content-Length required"},
                       close=True)
            return None
        try:
            length = int(self.headers.get("Content-Length", 0))
        except (TypeError, ValueError):
            self._send(400, {"error": "bad Content-Length"}, close=True)
            return None
        if length > _MAX_BODY_BYTES:
            self._send(413, {"error": "request body too large"},
                       close=True)
            return None
        return self.rfile.read(length)

    def log_message(self, fmt: str, *args) -> None:  # quiet by default
        logger.debug("serve http: " + fmt, *args)


class _Handler(_JsonHandler):
    """Routes to the :class:`ServeApp` attached to the server."""

    @property
    def app(self) -> ServeApp:
        return self.server.app  # type: ignore[attr-defined]

    def _socket_timeout_s(self) -> float:
        return self.app.config.socket_timeout_s

    def do_GET(self) -> None:  # noqa: N802 — http.server contract
        if self.path == "/healthz":
            self._send(200, self.app.healthz())
        elif self.path == "/readyz":
            code, payload = self.app.readyz()
            self._send(code, payload)
        elif self.path == "/metricz":
            self._send(200, self.app.metricz())
        else:
            self._send(404, {"error": f"no route {self.path}"})

    def do_POST(self) -> None:  # noqa: N802 — http.server contract
        raw = self._read_body()
        if raw is None:
            return
        if self.path != "/query":
            self._send(404, {"error": f"no route {self.path}"})
            return
        try:
            body = json.loads(raw or b"{}")
            self._send(200, self.app.run_query(body))
        except QueueFullError as e:
            # admission control: tell the client to back off — and for
            # how long (load-shed 503s always carry Retry-After)
            self._send(503, {"error": str(e), "retry": True},
                       headers={"Retry-After": str(_RETRY_AFTER_S)})
        except DrainingError as e:
            self._send(503, {"error": str(e), "retry": True,
                             "draining": True},
                       headers={"Retry-After": str(_RETRY_AFTER_S)})
        except QuarantinedAgentError as e:
            # the agent exists but its data was contained at load
            # (resilience.quarantine): 422 with the reasons, so a
            # client can distinguish bad-data containment from a typo'd
            # id (400) and stop retrying
            self._send(422, {
                "error": str(e),
                "quarantine": {
                    "agent_id": e.agent_id,
                    "reasons": e.reasons,
                },
            })
        except (KeyError, ValueError, OverrideError) as e:
            # KeyError's str() re-quotes its message; unwrap it
            msg = e.args[0] if isinstance(e, KeyError) and e.args else str(e)
            self._send(400, {"error": str(msg)})
        except FutureTimeout:
            self._send(504, {"error": "query timed out"})
        except Exception as e:  # noqa: BLE001 — handler must answer
            logger.exception("serve /query failed")
            self._send(500, {"error": str(e)})


def make_server(app: ServeApp) -> ThreadingHTTPServer:
    """Bind a threaded HTTP server (port 0 = ephemeral, for tests)."""
    srv = ThreadingHTTPServer(
        (app.config.host, app.config.port), _Handler
    )
    srv.app = app  # type: ignore[attr-defined]
    return srv


def drain(app: ServeApp, srv: ThreadingHTTPServer,
          timeout: float = 30.0) -> bool:
    """Graceful drain, reused by the fleet front's replica shutdown:
    stop admitting queries (503 + Retry-After, /readyz red), wait for
    in-flight requests (bounded by ``timeout``), flush the batcher's
    queued batches, then stop the accept loop.  Returns True when
    everything in flight finished inside the bound."""
    app.begin_drain()
    idle = app.wait_idle(timeout)
    app.close()          # flushes queued batches, stops the worker
    srv.shutdown()       # serve_forever returns; listeners stop
    return idle


def install_sigterm_drain(app: ServeApp, srv: ThreadingHTTPServer,
                          timeout: float = 30.0) -> None:
    """SIGTERM = graceful drain (the fleet supervisor's stop signal and
    every container runtime's).  Must be called from the main thread
    (CPython signal contract); the drain itself runs on a helper thread
    so the handler returns immediately."""

    def _on_term(signum, frame) -> None:
        logger.info("serve: SIGTERM — draining (timeout %.1fs)", timeout)
        threading.Thread(
            target=drain, args=(app, srv, timeout),
            name="dgen-serve-drain", daemon=True,
        ).start()

    signal.signal(signal.SIGTERM, _on_term)


def serve_forever(app: ServeApp, srv: Optional[ThreadingHTTPServer] = None,
                  drain_timeout_s: float = 30.0) -> None:
    """Run until SIGINT (immediate) or SIGTERM (graceful drain);
    closes the batcher on the way out.  Pass a pre-bound ``srv`` when
    the caller needed the port before blocking (the replica CLI binds
    first, writes its portfile, then serves)."""
    if srv is None:
        srv = make_server(app)
    host, port = srv.server_address[:2]
    install_sigterm_drain(app, srv, timeout=drain_timeout_s)
    logger.info(
        "dgen-tpu serve: %d agents, years %s-%s, buckets %s on "
        "http://%s:%d (/query /healthz /readyz /metricz)",
        app.engine.n_agents, app.engine.years[0], app.engine.years[-1],
        list(app.config.buckets), host, port,
    )
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        logger.info("serve: shutting down")
    finally:
        srv.server_close()
        app.close()


def start_in_thread(app: ServeApp) -> ThreadingHTTPServer:
    """Test/embedding helper: serve on a daemon thread; returns the
    bound server (``server_address`` carries the ephemeral port)."""
    srv = make_server(app)
    t = threading.Thread(
        target=srv.serve_forever, name="dgen-serve-http", daemon=True
    )
    t.start()
    return srv
