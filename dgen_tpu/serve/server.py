"""Threaded JSON-over-HTTP front-end for the serving engine.

Stdlib-only (http.server) by design: the repo's hard dependency set
stays jax+numpy, and the endpoint shape — one POST route, two GET
probes — does not need a framework. One process serves:

  * ``POST /query``   {"agent_ids": [...], "year": 2026,
                       "overrides": {"scale": {"itc_fraction": 0.5}},
                       "cash_flow": false}
                      -> {"year": ..., "results": [{...} per agent]}
  * ``GET  /healthz`` liveness + the shared provenance stamp
                      (io.export.provenance_stamp: git sha, config
                      hash, backend) + warm bucket shapes
  * ``GET  /metricz`` lifetime serving stats: p50/p99 request latency,
                      queue depth, batch occupancy (utils.timing
                      histograms + Microbatcher counters)

Handlers never build programs (dgenlint L10): every device program was
compiled at engine warmup; a handler only validates, enqueues, and
formats.
"""

from __future__ import annotations

import json
import math
import threading
import time
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

import numpy as np

from dgen_tpu.config import ServeConfig
from dgen_tpu.io.export import provenance_stamp
from dgen_tpu.serve.batcher import Microbatcher, QueueFullError
from dgen_tpu.serve.engine import QUERY_FIELDS, OverrideError, ServeEngine
from dgen_tpu.utils import timing
from dgen_tpu.utils.logging import get_logger

logger = get_logger()

#: request-body cap: a /query of max_batch agents with overrides is a
#: few KB; anything near this is malformed or hostile
_MAX_BODY_BYTES = 1 << 20

#: per-request wait bound on the batcher future — covers a device hang
#: without wedging every handler thread forever
_QUERY_TIMEOUT_S = 60.0


def _num(v) -> "float | None":
    """JSON-safe float: non-finite values become null (json.dumps
    would otherwise emit bare NaN/Infinity tokens, which strict JSON
    parsers reject)."""
    f = float(v)
    return f if math.isfinite(f) else None


def _rows_to_json(out: Dict[str, np.ndarray], cash_flow: bool) -> list:
    """Columnar engine results -> per-agent JSON rows."""
    n = out["agent_id"].shape[0]
    rows = []
    for i in range(n):
        row = {}
        for f in QUERY_FIELDS:
            if f == "cash_flow":
                if cash_flow:
                    row[f] = [_num(x) for x in out[f][i]]
                continue
            v = out[f][i]
            row[f] = int(v) if f == "agent_id" else _num(v)
        rows.append(row)
    return rows


class ServeApp:
    """The server's state: engine + batcher + provenance, shared by
    every handler thread."""

    def __init__(
        self,
        engine: ServeEngine,
        config: Optional[ServeConfig] = None,
        provenance: Optional[dict] = None,
    ) -> None:
        self.engine = engine
        self.config = config or ServeConfig()
        self.batcher = Microbatcher(engine, self.config)
        self.t_start = time.time()
        # one stamp at construction: /healthz must stay allocation-free
        # and subprocess-free per probe
        self.provenance = provenance if provenance is not None else (
            provenance_stamp(
                engine.sim.run_config, engine.sim.scenario, self.config,
            )
        )
        if self.config.warmup:
            t0 = time.time()
            engine.warmup(self.config.buckets)
            logger.info(
                "serve warmup: %d bucket programs in %.1fs",
                len(self.config.buckets), time.time() - t0,
            )

    # -- endpoint bodies (transport-independent, unit-testable) --------

    def healthz(self) -> dict:
        return {
            "status": "ok",
            "uptime_s": round(time.time() - self.t_start, 1),
            "n_agents": self.engine.n_agents,
            "years": self.engine.years,
            "buckets": list(self.config.buckets),
            "warm_buckets": sorted(self.engine.warm_buckets),
            **self.provenance,
        }

    def metricz(self) -> dict:
        rec = self.batcher.stats()
        batch = timing.histogram("serve_batch")
        if batch is not None:
            snap = batch.snapshot()
            rec["batch_wall_ms"] = {
                "p50": round(snap["p50"] * 1e3, 3),
                "p99": round(snap["p99"] * 1e3, 3),
                "count": snap["count"],
            }
        rec["uptime_s"] = round(time.time() - self.t_start, 1)
        return rec

    def run_query(self, body: dict) -> dict:
        agent_ids = body.get("agent_ids")
        if not isinstance(agent_ids, list) or not agent_ids:
            raise ValueError("'agent_ids' must be a non-empty list")
        year = body.get("year")
        overrides = body.get("overrides")
        fut = self.batcher.submit(agent_ids, year, overrides)
        try:
            out = fut.result(_QUERY_TIMEOUT_S)
        except FutureTimeout:
            # the client gets a 504 either way; cancel so a request
            # still QUEUED is dropped instead of executed after the
            # stall clears (double work exactly at the overload point)
            fut.cancel()
            raise
        return {
            "year": self.engine.years[self.engine.year_index(year)],
            "results": _rows_to_json(out, bool(body.get("cash_flow"))),
        }

    def close(self) -> None:
        self.batcher.close()


class _Handler(BaseHTTPRequestHandler):
    """Routes to the :class:`ServeApp` attached to the server."""

    protocol_version = "HTTP/1.1"

    @property
    def app(self) -> ServeApp:
        return self.server.app  # type: ignore[attr-defined]

    def _send(self, code: int, payload: dict, close: bool = False) -> None:
        blob = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(blob)))
        if close:
            # advertises the close AND sets self.close_connection
            self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(blob)

    def log_message(self, fmt: str, *args) -> None:  # quiet by default
        logger.debug("serve http: " + fmt, *args)

    def do_GET(self) -> None:  # noqa: N802 — http.server contract
        if self.path == "/healthz":
            self._send(200, self.app.healthz())
        elif self.path == "/metricz":
            self._send(200, self.app.metricz())
        else:
            self._send(404, {"error": f"no route {self.path}"})

    def do_POST(self) -> None:  # noqa: N802 — http.server contract
        # read (or refuse) the body BEFORE routing: any response sent
        # with unread body bytes on a keep-alive connection desyncs the
        # stream (the leftover bytes parse as the next request line) —
        # refusal paths therefore close the connection explicitly
        if self.headers.get("Transfer-Encoding"):
            # chunked bodies are not length-delimited; refuse + close
            # rather than leave chunk framing in the stream
            self._send(411, {"error": "Content-Length required"},
                       close=True)
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
        except (TypeError, ValueError):
            self._send(400, {"error": "bad Content-Length"}, close=True)
            return
        if length > _MAX_BODY_BYTES:
            self._send(413, {"error": "request body too large"},
                       close=True)
            return
        raw = self.rfile.read(length)
        if self.path != "/query":
            self._send(404, {"error": f"no route {self.path}"})
            return
        try:
            body = json.loads(raw or b"{}")
            self._send(200, self.app.run_query(body))
        except QueueFullError as e:
            # admission control: tell the client to back off
            self._send(503, {"error": str(e), "retry": True})
        except (KeyError, ValueError, OverrideError) as e:
            # KeyError's str() re-quotes its message; unwrap it
            msg = e.args[0] if isinstance(e, KeyError) and e.args else str(e)
            self._send(400, {"error": str(msg)})
        except FutureTimeout:
            self._send(504, {"error": "query timed out"})
        except Exception as e:  # noqa: BLE001 — handler must answer
            logger.exception("serve /query failed")
            self._send(500, {"error": str(e)})


def make_server(app: ServeApp) -> ThreadingHTTPServer:
    """Bind a threaded HTTP server (port 0 = ephemeral, for tests)."""
    srv = ThreadingHTTPServer(
        (app.config.host, app.config.port), _Handler
    )
    srv.app = app  # type: ignore[attr-defined]
    return srv


def serve_forever(app: ServeApp) -> None:
    """Run until SIGINT; closes the batcher on the way out."""
    srv = make_server(app)
    host, port = srv.server_address[:2]
    logger.info(
        "dgen-tpu serve: %d agents, years %s-%s, buckets %s on "
        "http://%s:%d (/query /healthz /metricz)",
        app.engine.n_agents, app.engine.years[0], app.engine.years[-1],
        list(app.config.buckets), host, port,
    )
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        logger.info("serve: shutting down")
    finally:
        srv.server_close()
        app.close()


def start_in_thread(app: ServeApp) -> ThreadingHTTPServer:
    """Test/embedding helper: serve on a daemon thread; returns the
    bound server (``server_address`` carries the ephemeral port)."""
    srv = make_server(app)
    t = threading.Thread(
        target=srv.serve_forever, name="dgen-serve-http", daemon=True
    )
    t.start()
    return srv
