"""Bounded-queue microbatcher: coalesce concurrent what-if queries into
padded power-of-two buckets.

The serving latency problem is the inverse of the batch engines': a
single-agent query under-fills the device by orders of magnitude, but
an unbounded dynamic batch would give every distinct request count its
own XLA compile (the retrace storm dgenlint L10 / RetraceGuard exist to
kill). The resolution is fixed compile shapes: requests queue, a worker
coalesces same-scenario requests in FIFO order, and the batch pads up
to the next power-of-two bucket (``ServeConfig.buckets``) — so the set
of programs a serving process can ever run is known at warmup, and
occupancy (real rows / bucket) is the measured price of shape
stability. A ``max_wait_ms`` deadline bounds how long a lone request
waits for co-batching, and admission control rejects submissions
beyond ``max_queue`` with :class:`QueueFullError` instead of letting
queue delay grow without bound (load shedding beats collapse).

Coalescing key: (year_idx, scenario-override key) — requests batch
together only when they share the traced inputs a bucket binds once.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Dict, Optional, Sequence

import numpy as np

from dgen_tpu.config import ServeConfig
from dgen_tpu.serve.engine import ServeEngine, override_key
from dgen_tpu.utils import timing
from dgen_tpu.utils.logging import get_logger

logger = get_logger()

#: timing-histogram names (utils.timing.observe; /metricz and the bench
#: serve section read percentiles back via timing_report)
REQUEST_LATENCY = "serve_request"
BATCH_WALL = "serve_batch"


class QueueFullError(RuntimeError):
    """Admission control: the serve queue is at ``max_queue`` requests;
    the client should back off and retry (HTTP 503)."""


class _Request:
    __slots__ = ("rows", "year_idx", "key", "inputs", "future", "t_submit")

    def __init__(self, rows, year_idx, key, inputs):
        self.rows = rows
        self.year_idx = year_idx
        self.key = key
        self.inputs = inputs
        self.future: Future = Future()
        self.t_submit = time.monotonic()


class Microbatcher:
    """The request-coalescing front of a :class:`ServeEngine`.

    ``start=False`` leaves the worker thread unstarted (deterministic
    queue-state tests; production always starts it).
    """

    def __init__(
        self,
        engine: ServeEngine,
        config: Optional[ServeConfig] = None,
        start: bool = True,
    ) -> None:
        self.engine = engine
        self.config = config or ServeConfig()
        self._q: "deque[_Request]" = deque()
        self._cv = threading.Condition()
        self._closed = False
        # counters (under _cv): lifetime totals for /metricz
        self.n_requests = 0
        self.n_rejected = 0
        self.n_batches = 0
        self.n_rows = 0
        #: requests answered straight from the answer surface's mmap
        #: (engine-free; they never entered the queue)
        self.n_surface_hits = 0
        self._occupancy_sum = 0.0
        self._thread = threading.Thread(
            target=self._worker, name="dgen-serve-batcher", daemon=True
        )
        if start:
            self._thread.start()

    # -- client side ----------------------------------------------------

    def submit(
        self,
        agent_ids: Sequence[int],
        year: Optional[int] = None,
        overrides: Optional[dict] = None,
    ) -> Future:
        """Enqueue one query; resolves to the host result dict (engine
        row order = request order). Raises :class:`QueueFullError` when
        the queue is at capacity and KeyError/OverrideError for bad
        ids/years/overrides (validated HERE, on the caller's thread, so
        the worker never poisons a whole batch on one bad request)."""
        if not agent_ids:
            raise ValueError("empty agent_ids")
        if len(agent_ids) > self.config.max_batch:
            raise ValueError(
                f"{len(agent_ids)} agents in one request exceeds "
                f"max_batch {self.config.max_batch}; split the request"
            )
        rows = self.engine.rows_for(agent_ids)
        year_idx = self.engine.year_index(year)
        okey = override_key(overrides)
        # engine-free fast path: the zero-override question for a
        # surface-covered year is a mmap read — it never queues, never
        # pads, never touches the device, and does not count against
        # admission control (it consumes no engine capacity).
        # getattr: test stubs implement only the query surface
        surf = getattr(self.engine, "surface", None)
        if not okey and surf is not None and surf.covers(year_idx):
            req = _Request(rows, year_idx, (year_idx, okey), None)
            out = surf.lookup(rows, year_idx)
            with self._cv:
                if self._closed:
                    raise RuntimeError("batcher is closed")
                self.n_requests += 1
                self.n_surface_hits += 1
            timing.observe(
                REQUEST_LATENCY, time.monotonic() - req.t_submit
            )
            req.future.set_result(out)
            return req.future
        inputs = self.engine.inputs_for(overrides)
        req = _Request(rows, year_idx, (year_idx, okey), inputs)
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if len(self._q) >= self.config.max_queue:
                self.n_rejected += 1
                raise QueueFullError(
                    f"serve queue full ({self.config.max_queue} requests "
                    "queued); back off and retry"
                )
            self.n_requests += 1
            self._q.append(req)
            self._cv.notify_all()
        return req.future

    def query(
        self,
        agent_ids: Sequence[int],
        year: Optional[int] = None,
        overrides: Optional[dict] = None,
        timeout: Optional[float] = 30.0,
    ) -> Dict[str, np.ndarray]:
        """Blocking submit-and-wait convenience."""
        return self.submit(agent_ids, year, overrides).result(timeout)

    # -- worker side ----------------------------------------------------

    def _take_batch(self) -> Optional[list]:
        """Under _cv: pop the next dispatchable batch, or None to keep
        waiting. FIFO head defines the coalescing key; same-key
        requests join (in order) until the bucket is full; the batch
        dispatches when full, past the head's deadline, or on close."""
        # drop requests whose caller already gave up (a 504'd future is
        # cancelled): executing them after a stall clears is pure
        # double work
        for r in [r for r in self._q if r.future.cancelled()]:
            self._q.remove(r)
        if not self._q:
            return None
        head = self._q[0]
        batch, rows = [], 0
        for r in self._q:
            if r.key != head.key:
                continue
            if rows + len(r.rows) > self.config.max_batch:
                break
            batch.append(r)
            rows += len(r.rows)
        full = rows >= self.config.max_batch
        expired = (
            time.monotonic() - head.t_submit
            >= self.config.max_wait_ms / 1e3
        )
        if not (full or expired or self._closed):
            return None
        for r in batch:
            self._q.remove(r)
        return batch

    def _worker(self) -> None:
        while True:
            with self._cv:
                batch = self._take_batch()
                if batch is None:
                    if self._closed and not self._q:
                        return
                    if self._q:
                        head_deadline = (
                            self._q[0].t_submit
                            + self.config.max_wait_ms / 1e3
                        )
                        self._cv.wait(
                            timeout=max(head_deadline - time.monotonic(), 0.0)
                            + 1e-4
                        )
                    else:
                        self._cv.wait()
                    continue
            self._run_batch(batch)

    def _bucket_for(self, rows: int) -> int:
        for b in self.config.buckets:
            if b >= rows:
                return b
        return self.config.max_batch

    def _run_batch(self, batch: list) -> None:
        rows = np.concatenate([r.rows for r in batch])
        bucket = self._bucket_for(rows.shape[0])
        t0 = time.monotonic()
        try:
            out = self.engine.query_rows(
                rows, batch[0].year_idx, inputs=batch[0].inputs,
                bucket=bucket, key=batch[0].key[1],
            )
        except BaseException as e:  # noqa: BLE001 — fail the futures,
            for r in batch:         # never the worker thread
                if not r.future.cancelled():
                    r.future.set_exception(e)
            return
        wall = time.monotonic() - t0
        timing.observe(BATCH_WALL, wall)
        with self._cv:
            self.n_batches += 1
            self.n_rows += int(rows.shape[0])
            self._occupancy_sum += rows.shape[0] / bucket
        lo = 0
        done = time.monotonic()
        for r in batch:
            hi = lo + len(r.rows)
            res = {k: v[lo:hi] for k, v in out.items()}
            lo = hi
            timing.observe(REQUEST_LATENCY, done - r.t_submit)
            if not r.future.cancelled():
                r.future.set_result(res)

    # -- ops ------------------------------------------------------------

    def stats(self) -> Dict[str, object]:
        """Lifetime serving stats (the /metricz payload core)."""
        with self._cv:
            depth = len(self._q)
            rec = {
                "queue_depth": depth,
                "max_queue": self.config.max_queue,
                "requests": self.n_requests,
                "rejected": self.n_rejected,
                "batches": self.n_batches,
                "rows": self.n_rows,
                "surface_hits": self.n_surface_hits,
                "batch_occupancy": (
                    round(self._occupancy_sum / self.n_batches, 4)
                    if self.n_batches else None
                ),
            }
        rec["buckets"] = list(self.config.buckets)
        rec["warm_buckets"] = sorted(self.engine.warm_buckets)
        # surface/result-cache counters (empty when neither layer is
        # attached) — the fleet front aggregates these across replicas
        serve_stats = getattr(self.engine, "serve_stats", None)
        if serve_stats is not None:
            rec.update(serve_stats())
        lat = timing.histogram(REQUEST_LATENCY)
        if lat is not None:
            snap = lat.snapshot()
            rec["latency_ms"] = {
                "p50": round(snap["p50"] * 1e3, 3),
                "p90": round(snap["p90"] * 1e3, 3),
                "p99": round(snap["p99"] * 1e3, 3),
                "mean": round(snap["mean"] * 1e3, 3),
                "max": round(snap["max"] * 1e3, 3),
                "count": snap["count"],
            }
        return rec

    def close(self, timeout: float = 10.0) -> None:
        """Drain the queue, stop the worker. Idempotent."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._thread.is_alive():
            self._thread.join(timeout)
        # a never-started worker (start=False tests) leaves queued
        # futures unresolved; fail them explicitly
        with self._cv:
            pending = list(self._q)
            self._q.clear()
        for r in pending:
            if not r.future.done():
                r.future.set_exception(RuntimeError("batcher closed"))
