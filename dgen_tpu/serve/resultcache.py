"""Cross-replica exact result cache for served what-if answers.

Answers are **bit-exact within a bucket shape** (the microbatcher's
coalescing contract), so a cache hit is an EXACT answer, not an
approximation: the same (year, canonical override key, bucket shape,
requested rows) through the same configuration produces the same bytes
every time.  This module caches those answers in a shared directory —
the same cross-process pattern as ``utils/compilecache.py`` — so a hot
what-if (a promoted scenario, a widely shared link) is computed once
per fleet and then served from disk by EVERY replica, including a
replica that just rebooted after a kill.

Entry contract:

* **key** — sha256 over (provenance key, year index, override key,
  bucket, the row-index bytes): everything the answer bytes depend on.
  The provenance key (the serving config hash + git sha) partitions
  the directory across code/config versions, so a stale entry can
  never be served after a deploy — it simply stops being addressed.
* **value** — one ``.npz`` file holding the host result arrays, landed
  via temp + ``os.replace`` (crash-consistent; a killed writer leaves
  at most a temp sibling, cleaned opportunistically).
* **bounded** — at most ``max_entries`` files; insertion evicts the
  least-recently-USED entries (mtime is touched on every hit).  The
  eviction scan is on the writer, never the read path.

Concurrent replicas race benignly: a double store writes identical
bytes; a read racing an eviction counts as a miss.  Counters (hits,
misses, stores, evictions) surface in ``/metricz`` per replica and
aggregated at the fleet front.
"""

from __future__ import annotations

import hashlib
import io
import os
import threading
import time
from typing import Dict, Optional

import numpy as np

from dgen_tpu.utils.logging import get_logger

logger = get_logger()

_SUFFIX = ".npz"


class ResultCache:
    """Bounded, file-backed, cross-process answer cache.

    Parameters
    ----------
    dir_path : shared directory (created if absent).  Replicas of one
        fleet point at the same directory.
    provenance_key : partitions keys across code/config versions —
        pass the serving provenance (config hash + git sha); answers
        from different versions never alias.
    max_entries : eviction bound (files), enforced on store.
    """

    def __init__(
        self,
        dir_path: str,
        provenance_key: str = "",
        max_entries: int = 512,
    ) -> None:
        if max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        self.dir = dir_path
        self.provenance_key = provenance_key
        self.max_entries = int(max_entries)
        os.makedirs(dir_path, exist_ok=True)
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.evictions = 0

    # -- keys ----------------------------------------------------------

    def key(
        self,
        year_idx: int,
        override_key: str,
        bucket: int,
        rows: np.ndarray,
    ) -> str:
        """Canonical entry key: everything the answer bytes depend on
        given one provenance partition."""
        h = hashlib.sha256()
        h.update(self.provenance_key.encode())
        h.update(f"|{int(year_idx)}|{override_key}|{int(bucket)}|".encode())
        h.update(np.ascontiguousarray(rows, dtype=np.int32).tobytes())
        return h.hexdigest()

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, key + _SUFFIX)

    # -- read/write ----------------------------------------------------

    def get(self, key: str) -> Optional[Dict[str, np.ndarray]]:
        """The cached answer dict, or None (counted as a miss).  A
        file vanishing mid-read (concurrent eviction) or failing to
        parse (torn write from a pre-atomic writer — cannot happen via
        :meth:`put`, but the cache must never crash serving) is a
        miss."""
        path = self._path(key)
        try:
            with np.load(path, allow_pickle=False) as z:
                out = {f: np.array(z[f]) for f in z.files}
            os.utime(path)   # LRU touch; eviction orders by mtime
        except (OSError, ValueError, KeyError, EOFError):
            with self._lock:
                self.misses += 1
            return None
        with self._lock:
            self.hits += 1
        return out

    def put(self, key: str, out: Dict[str, np.ndarray]) -> None:
        """Store an answer (temp + rename), then enforce the entry
        bound by evicting least-recently-used files.  Failures are
        logged, never raised — the cache is an accelerator, not a
        dependency."""
        path = self._path(key)
        tmp = f"{path}.{os.getpid()}.tmp"
        try:
            buf = io.BytesIO()
            np.savez(buf, **out)
            with open(tmp, "wb") as f:   # dgenlint: disable=L11
                f.write(buf.getvalue())
            os.replace(tmp, path)
        except OSError as e:
            logger.warning("result cache store failed: %s", e)
            try:
                if os.path.exists(tmp):
                    os.remove(tmp)
            except OSError:
                pass
            return
        with self._lock:
            self.stores += 1
        self._evict()

    def _evict(self) -> None:
        """Drop oldest-used entries beyond ``max_entries``; stale temp
        siblings from killed writers are garbage-collected too."""
        try:
            entries = []
            for n in os.listdir(self.dir):
                p = os.path.join(self.dir, n)
                try:
                    if n.endswith(".tmp"):
                        # a killed writer's leftover; stale after 60 s
                        if time.time() - os.path.getmtime(p) > 60.0:
                            os.remove(p)
                        continue
                    if n.endswith(_SUFFIX):
                        entries.append((os.path.getmtime(p), p))
                except OSError:
                    continue   # vanished under a concurrent evictor
            excess = len(entries) - self.max_entries
            if excess <= 0:
                return
            entries.sort()
            dropped = 0
            for _mt, p in entries[:excess]:
                try:
                    os.remove(p)
                    dropped += 1
                except OSError:
                    continue
            if dropped:
                with self._lock:
                    self.evictions += dropped
        except OSError as e:
            logger.warning("result cache eviction scan failed: %s", e)

    # -- ops -----------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            rec = {
                "hits": self.hits,
                "misses": self.misses,
                "stores": self.stores,
                "evictions": self.evictions,
                "max_entries": self.max_entries,
            }
        rec["dir"] = self.dir
        return rec
