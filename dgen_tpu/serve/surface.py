"""The precomputed answer surface: the zero-override question served
engine-free.

Millions of users mostly ask the DEFAULT question — "this agent, this
year, no what-if overrides" — and until now every one of those queries
walked the full jitted engine path.  But the zero-override answer is a
pure function of (population, scenario inputs, year): a finite table.
This module sweeps it ONCE offline through the very same
:func:`~dgen_tpu.serve.engine.query_program` the live engine runs — at
full bucket width, so the precomputed rows are **bit-exact by
construction** against what the engine would compute at that bucket
shape — and persists it as a content-hashed, provenance-stamped,
memory-mapped columnar table (:mod:`dgen_tpu.io.mmaptable`).  A
replica then answers surface-covered queries straight out of the mmap
(microseconds, no device, no queue) and falls through to the compiled
engine for everything else.  N replicas on one machine mmap the same
file: one physical copy in the page cache, the same sharing argument
as the compile cache.

Staleness is the failure mode that matters: a surface is only exact
for the exact configuration that built it.  The builder stamps
``git_sha``, a ``config_hash`` over (RunConfig, ScenarioConfig), a
sha256 of the population identity (agent ids + mask), the year grid,
and the sizing statics; :meth:`AnswerSurface.load` refuses — with the
mismatching field NAMED — when any of them differ from the engine it
is being attached to.  A refused or damaged surface degrades to the
engine path; it never serves stale answers.

Build workflow (docs/serve.md "Production throughput")::

    python -m dgen_tpu.serve --build-surface runs/surface --agents 8192
    python -m dgen_tpu.serve --fleet 3 --surface runs/surface ...
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from dgen_tpu.io.export import config_hash, git_sha
from dgen_tpu.io.mmaptable import MmapTable, MmapTableError, write_table
from dgen_tpu.resilience.faults import fault_point
from dgen_tpu.serve.engine import QUERY_FIELDS, ServeEngine
from dgen_tpu.utils.logging import get_logger

logger = get_logger()

#: header meta tag (bumped if the column contract changes)
SURFACE_VERSION = 1


class SurfaceError(RuntimeError):
    """The surface directory is missing/corrupt/unreadable (the mmap
    layer's verdict, re-raised with serving context)."""


class StaleSurfaceError(SurfaceError):
    """The surface was built under a different configuration than the
    engine it is being attached to; ``reason`` names the mismatching
    field.  A stale surface is REFUSED, never served."""

    def __init__(self, reason: str) -> None:
        super().__init__(f"answer surface refused: {reason}")
        self.reason = reason


def surface_provenance(engine: ServeEngine) -> dict:
    """What the surface's exactness depends on: the code (git sha),
    the configuration (RunConfig + ScenarioConfig hash), the exact
    population (agent ids + mask bytes), the year grid, and the
    sizing statics.  ServeConfig is deliberately EXCLUDED — queue and
    timeout knobs cannot change an answer."""
    sim = engine.sim
    pop = hashlib.sha256()
    pop.update(np.ascontiguousarray(sim.host_agent_id).tobytes())
    pop.update(np.ascontiguousarray(sim.host_mask).tobytes())
    return {
        "git_sha": git_sha(),
        "config_hash": config_hash(sim.run_config, sim.scenario),
        "population_sha": pop.hexdigest()[:16],
        "years": [int(y) for y in engine.years],
        "n_rows": int(np.asarray(sim.host_mask).shape[0]),
        "econ_years": int(sim.econ_years),
        "sizing_iters": int(sim.run_config.sizing_iters),
    }


def provenance_key(engine: ServeEngine) -> str:
    """Compact provenance partition key (config hash + git sha +
    population) — the result cache's version namespace, so answers
    computed by different code/config/population can never alias."""
    p = surface_provenance(engine)
    return f"{p['config_hash']}|{p['git_sha']}|{p['population_sha']}"


def load_and_attach(engine: ServeEngine, dir_path: str) -> Optional[str]:
    """Load + attach a surface to ``engine``; on refusal (stale,
    corrupt, missing) log the named reason, record it on the engine
    for /metricz, and serve engine-only.  Returns the refusal reason,
    or None on success.  A refused surface degrades availability of
    the fast path — it NEVER degrades correctness."""
    try:
        surf = AnswerSurface.load(dir_path, engine)
    except Exception as e:  # noqa: BLE001 — refusal must not kill boot
        reason = str(e)
        engine.surface_refused = reason
        logger.error(
            "%s — serving WITHOUT the answer surface (every query "
            "takes the compiled engine path)", reason,
        )
        return reason
    engine.attach_surface(surf)
    logger.info(
        "answer surface attached: %d years x %d rows (bucket %d, "
        "content %s)", surf.stats()["years"], surf.stats()["rows"],
        surf.bucket, surf.stats()["content_hash"],
    )
    return None


def build_surface(
    engine: ServeEngine,
    out_dir: str,
    bucket: int,
    year_indices: Optional[Sequence[int]] = None,
) -> dict:
    """Sweep the zero-override answer for every (year, table row)
    through the live engine at ``bucket`` width and persist it as a
    mmap table at ``out_dir``; returns the written header.

    Every row of the padded table is swept (padding rows are inert
    per-row math, same as in a live bucket), so lookups index by table
    row directly.  ``year_indices`` restricts the sweep (tests,
    incremental rollouts); an unbuilt year simply falls through to the
    engine at serve time.
    """
    n_rows = int(np.asarray(engine.sim.host_mask).shape[0])
    yis = (
        list(range(len(engine.years)))
        if year_indices is None else [int(y) for y in year_indices]
    )
    t0 = time.time()
    per_field: Dict[str, List[np.ndarray]] = {f: [] for f in QUERY_FIELDS}
    for yi in yis:
        chunks: Dict[str, List[np.ndarray]] = {f: [] for f in QUERY_FIELDS}
        for start in range(0, n_rows, bucket):
            rows = np.arange(
                start, min(start + bucket, n_rows), dtype=np.int32
            )
            out = engine.query_rows(rows, yi, bucket=bucket)
            for f in QUERY_FIELDS:
                chunks[f].append(out[f])
        for f in QUERY_FIELDS:
            per_field[f].append(np.concatenate(chunks[f], axis=0))
    columns = {
        f: np.stack(per_field[f], axis=0) for f in QUERY_FIELDS
    }
    meta = {
        "surface_version": SURFACE_VERSION,
        "bucket": int(bucket),
        "year_indices": yis,
        "provenance": surface_provenance(engine),
        "build_wall_s": round(time.time() - t0, 3),
    }
    header = write_table(out_dir, columns, meta=meta)
    logger.info(
        "answer surface built: %d years x %d rows at bucket %d in "
        "%.1fs -> %s (content %s)",
        len(yis), n_rows, bucket, meta["build_wall_s"], out_dir,
        header["content_hash"][:12],
    )
    return header


class AnswerSurface:
    """A loaded, provenance-verified surface bound to one engine.

    ``lookup`` is pure host-side numpy fancy-indexing into the mmap —
    no device program, no queue, no compile.  Hit counting is
    thread-safe (handler threads share one instance)."""

    def __init__(self, table: MmapTable, meta: dict) -> None:
        self._table = table
        self.meta = meta
        self.bucket = int(meta["bucket"])
        self._slot = {
            int(yi): i for i, yi in enumerate(meta["year_indices"])
        }
        self._cols = table.columns
        self._lock = threading.Lock()
        self.hits = 0

    # -- loading -------------------------------------------------------

    @classmethod
    def load(cls, dir_path: str, engine: ServeEngine) -> "AnswerSurface":
        """Open + provenance-gate a surface for ``engine``.  Raises
        :class:`SurfaceError` (unreadable/damaged) or
        :class:`StaleSurfaceError` (built under a different
        config_hash/git_sha/population/grid, reason named)."""
        # drill hook: torn storage / unreadable mmap at load — the
        # caller must refuse and fall through, never serve garbage
        fault_point(
            "surface_load", path=os.path.join(dir_path, "table.bin")
        )
        try:
            table = MmapTable(dir_path)
            table.verify()
        except MmapTableError as e:
            raise SurfaceError(f"answer surface unusable: {e}") from e
        meta = table.meta
        if meta.get("surface_version") != SURFACE_VERSION:
            raise StaleSurfaceError(
                f"surface_version {meta.get('surface_version')!r} != "
                f"{SURFACE_VERSION}"
            )
        want = surface_provenance(engine)
        got = meta.get("provenance") or {}
        for field in (
            "config_hash", "git_sha", "population_sha", "years",
            "n_rows", "econ_years", "sizing_iters",
        ):
            if got.get(field) != want[field]:
                raise StaleSurfaceError(
                    f"{field} mismatch (surface {got.get(field)!r} != "
                    f"engine {want[field]!r})"
                )
        missing = [f for f in QUERY_FIELDS if f not in table.columns]
        if missing:
            raise StaleSurfaceError(
                f"missing answer column(s) {missing}"
            )
        return cls(table, meta)

    # -- serving -------------------------------------------------------

    def covers(self, year_idx: int) -> bool:
        return int(year_idx) in self._slot

    def lookup(
        self, rows: np.ndarray, year_idx: int
    ) -> Dict[str, np.ndarray]:
        """Answers for ``rows`` at ``year_idx`` — same dict-of-arrays
        shape :meth:`ServeEngine.query_rows` returns, copied out of
        the mmap (callers may mutate)."""
        slot = self._slot[int(year_idx)]
        rows = np.asarray(rows, dtype=np.int32)
        out = {
            f: np.array(self._cols[f][slot][rows]) for f in QUERY_FIELDS
        }
        with self._lock:
            self.hits += 1
        return out

    def stats(self) -> dict:
        with self._lock:
            hits = self.hits
        return {
            "years": len(self._slot),
            "rows": int(self._cols["agent_id"].shape[1]),
            "bucket": self.bucket,
            "hits": hits,
            "content_hash": self._table.content_hash[:12],
        }
