"""Multi-device execution: mesh construction, state-balanced agent
partitioning, and shard_map-ped kernels with ICI collectives — the
TPU-native replacement for the reference's one-GCP-Batch-task-per-state
scale-out (SURVEY.md §2.6)."""

from dgen_tpu.parallel import mesh, partition  # noqa: F401
