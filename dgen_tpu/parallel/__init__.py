"""Multi-device execution: mesh construction, state-balanced agent
partitioning, shard_map-ped kernels with ICI collectives, and the
elastic P->P' resharded checkpoint restore — the TPU-native replacement
for the reference's one-GCP-Batch-task-per-state scale-out
(SURVEY.md §2.6)."""

from dgen_tpu.parallel import elastic, mesh, partition  # noqa: F401
