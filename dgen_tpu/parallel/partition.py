"""State-balanced agent partitioning across devices.

The reference load-balances its national run by binning states into
four size classes and submitting each as a separate GCP Batch job
(state_input_csvs/{small,mid,mid_large,large}_states.csv +
submit_all.sh:8-46). The TPU equivalent: order agents so that each
device shard holds (nearly) whole states, via greedy
largest-first bin packing of states onto devices, then pad each shard
to equal length. Keeping states shard-local makes the state x sector
segment reductions mostly local, with a single psum combining the few
states that straddle a boundary.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

import jax
import numpy as np


@dataclasses.dataclass(frozen=True)
class Partition:
    """Host-side description of an agent->device assignment."""

    order: np.ndarray          # [N] permutation: new position -> old index
    shard_sizes: np.ndarray    # [D] real agents per shard
    shard_len: int             # padded per-shard length
    device_of_state: np.ndarray  # [n_states] -> device (primary shard)
    #: [D*shard_len] ORIGINAL table row behind each padded position
    #: (-1 for per-shard padding rows) — set by :func:`partition_table`
    #: so per-row side arrays (e.g. the ensemble's cohort entry years)
    #: can ride the same permutation without ambiguity; None when the
    #: partition was built directly from :func:`partition_by_state`
    gather_rows: np.ndarray | None = None

    @property
    def n_devices(self) -> int:
        return len(self.shard_sizes)

    @property
    def total_padded(self) -> int:
        return self.n_devices * self.shard_len


def partition_by_state(
    state_idx: np.ndarray,
    n_states: int,
    n_devices: int,
    pad_multiple: int = 8,
    mesh_shape: Tuple[int, int] | None = None,
) -> Partition:
    """Greedy largest-first packing of states onto devices.

    Returns a permutation placing each device's agents contiguously.
    Agents of one state always land on one device (states bigger than a
    balanced share still go to the currently-lightest device — matching
    the reference's whole-state-per-task granularity).

    ``mesh_shape=(H, D)`` with H > 1 (the 2-D hosts x devices grid,
    parallel.mesh) packs hierarchically: states go to the lightest HOST
    row first, then to the lightest device within that host — so whole
    states stay host-local and the straddle psums the flat packing
    would route over DCN become intra-host ICI traffic. The global
    device index is ``host * D + device`` (row-major, matching
    make_mesh's device order).
    """
    state_idx = np.asarray(state_idx)
    counts = np.bincount(state_idx, minlength=n_states)
    device_of_state = np.zeros(n_states, dtype=np.int32)
    if mesh_shape is not None and mesh_shape[0] > 1:
        h, d = int(mesh_shape[0]), int(mesh_shape[1])
        if h * d != n_devices:
            raise ValueError(
                f"mesh shape {h}x{d} does not cover {n_devices} devices")
        host_load = np.zeros(h, dtype=np.int64)
        dev_load = np.zeros((h, d), dtype=np.int64)
        for s in np.argsort(-counts):
            if counts[s] == 0:
                device_of_state[s] = 0
                continue
            hh = int(np.argmin(host_load))
            dd = int(np.argmin(dev_load[hh]))
            device_of_state[s] = hh * d + dd
            host_load[hh] += counts[s]
            dev_load[hh, dd] += counts[s]
    else:
        device_load = np.zeros(n_devices, dtype=np.int64)
        for s in np.argsort(-counts):
            if counts[s] == 0:
                device_of_state[s] = 0
                continue
            dd = int(np.argmin(device_load))
            device_of_state[s] = dd
            device_load[dd] += counts[s]

    agent_device = device_of_state[state_idx]
    order = np.argsort(agent_device, kind="stable")
    shard_sizes = np.bincount(agent_device, minlength=n_devices)

    shard_len = int(shard_sizes.max()) if len(state_idx) else 0
    shard_len = ((shard_len + pad_multiple - 1) // pad_multiple) * pad_multiple
    shard_len = max(shard_len, pad_multiple)
    return Partition(
        order=order,
        shard_sizes=shard_sizes,
        shard_len=shard_len,
        device_of_state=device_of_state,
    )


def apply_partition_indices(part: Partition, n_agents: int) -> Tuple[np.ndarray, np.ndarray]:
    """(gather_index [D*shard_len], valid_mask [D*shard_len]) mapping the
    padded, device-ordered layout back to original agent rows (index 0
    used for padding rows, masked out)."""
    gather = np.zeros(part.total_padded, dtype=np.int64)
    mask = np.zeros(part.total_padded, dtype=np.float32)
    starts = np.concatenate([[0], np.cumsum(part.shard_sizes)[:-1]])
    for d in range(part.n_devices):
        seg = part.order[starts[d]: starts[d] + part.shard_sizes[d]]
        off = d * part.shard_len
        gather[off: off + len(seg)] = seg
        mask[off: off + len(seg)] = 1.0
    return gather, mask


def partition_table(table, n_devices: int, pad_multiple: int = 128,
                    mesh_shape: Tuple[int, int] | None = None):
    """(reordered AgentTable, Partition): lay agents out so each device
    shard holds whole states, the TPU analogue of the reference's
    per-state task binning (state_input_csvs/ + submit_all.sh).

    The partition is computed over REAL agents only (padding rows are
    re-created per shard); every [N]-leading leaf is gathered into the
    new order and the mask re-derived, so results keyed by ``agent_id``
    are invariant under the permutation. ``mesh_shape`` makes the
    packing host-hierarchical on a 2-D grid (:func:`partition_by_state`).
    """
    old_mask = np.asarray(table.mask) > 0
    real_rows = np.nonzero(old_mask)[0]
    state_real = np.asarray(table.state_idx)[real_rows]
    part = partition_by_state(
        state_real, table.n_states, n_devices, pad_multiple,
        mesh_shape=mesh_shape,
    )
    gather_sub, valid = apply_partition_indices(part, len(real_rows))
    gather = real_rows[gather_sub]
    n_old = table.n_agents

    def g(x):
        if hasattr(x, "ndim") and x.ndim >= 1 and x.shape[0] == n_old:
            return x[gather]
        return x

    out = jax.tree.map(g, table)
    import jax.numpy as jnp

    part = dataclasses.replace(
        part, gather_rows=np.where(valid > 0, gather, -1)
    )
    return dataclasses.replace(out, mask=jnp.asarray(valid)), part
