"""Scale-out launch harness: the TPU-native analogue of the reference's
cluster orchestration layer (SURVEY.md §2.6 L7).

The reference shards national runs by STATE across GCP Batch tasks:
``submit_all.sh`` submits four size-binned jobs
(state_input_csvs/{small,mid,mid_large,large}_states.csv on
c2d-highcpu-8/16/32), each task picks its state via ``BATCH_TASK_INDEX``
(batch_job_yamls/dgen-batch-job-small-states.yaml:47-56) and the tasks
never talk to each other — Postgres is the only shared surface.

The TPU equivalents here:

  * ``bin_states`` — the same size-binned grouping, used either to
    launch one process per bin (:func:`shard_commands`) or to feed the
    in-process state-local partitioner (parallel.partition).
  * ``initialize_multihost`` — jax.distributed bring-up for multi-host
    / multi-slice meshes: every host calls it, gets the global device
    set, and the SAME single-axis agent mesh (parallel.mesh) spans ICI
    within a slice and DCN across slices; XLA routes the (tiny)
    state-aggregation psums accordingly. This replaces the reference's
    no-comms design with real collectives, and is exercised on
    single-host by the 8-device virtual mesh tests.
  * ``shard_commands`` — emits the per-task env/command lines (the
    ``BATCH_TASK_INDEX`` analogue ``DGEN_SHARD_INDEX``) for operators
    who prefer the reference's share-nothing process-per-bin model,
    e.g. one v5e-8 slice per size bin.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np


@dataclass(frozen=True)
class StateBins:
    """Size-binned state groups (largest states in the last bin)."""

    bins: List[List[str]]

    def flat(self) -> List[str]:
        return [s for b in self.bins for s in b]


def bin_states(
    state_sizes: Dict[str, float],
    n_bins: int = 4,
) -> StateBins:
    """Greedy size-binning of states, the reference's
    small/mid/mid_large/large split (state_input_csvs/) generalized:
    states sorted by size are dealt into ``n_bins`` quantile groups so
    each bin's tasks have comparable runtimes on one machine shape."""
    if not state_sizes:
        return StateBins(bins=[[] for _ in range(n_bins)])
    names = sorted(state_sizes, key=lambda s: state_sizes[s])
    splits = np.array_split(np.asarray(names, dtype=object), n_bins)
    return StateBins(bins=[list(map(str, s)) for s in splits])


def shard_commands(
    bins: StateBins,
    entry: str = "python -m dgen_tpu.parallel.launch",
) -> List[str]:
    """Per-bin launch lines (the ``submit_all.sh`` analogue): each
    carries its shard index and comma-joined state list via env."""
    out = []
    for i, states in enumerate(bins.bins):
        if not states:
            continue
        out.append(
            f"DGEN_SHARD_INDEX={i} DGEN_SHARD_STATES={','.join(states)} "
            f"{entry}"
        )
    return out


def initialize_multihost(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Bring up jax.distributed for a multi-host / multi-slice run.

    Reads ``DGEN_COORDINATOR`` (host:port), ``DGEN_NUM_PROCESSES`` and
    ``DGEN_PROCESS_ID`` when args are omitted — the operator-supplied
    analogue of GCP Batch's injected task env
    (batch_job_yamls/...:11-25). Returns True when distributed mode was
    initialized; False (single-process) when no coordinator is
    configured. After initialization ``jax.devices()`` is the GLOBAL
    device set, so ``parallel.mesh.make_mesh()`` spans every slice —
    collectives ride ICI within a slice and DCN across.
    """
    coordinator = coordinator or os.environ.get("DGEN_COORDINATOR")
    if not coordinator:
        return False

    def from_env(value: Optional[int], var: str) -> int:
        if value is not None:
            return int(value)
        raw = os.environ.get(var)
        if raw is None or not raw.strip():
            # a bare KeyError here would read as a bug in THIS code;
            # it is an operator error in the launch env, so say exactly
            # which variable is missing and what the contract is
            raise ValueError(
                f"DGEN_COORDINATOR is set ({coordinator!r}) but {var} "
                "is missing: a multi-host launch needs DGEN_COORDINATOR, "
                "DGEN_NUM_PROCESSES and DGEN_PROCESS_ID set on every "
                "process (docs/userguide.md 'Multi-host runs')"
            )
        try:
            return int(raw)
        except ValueError:
            raise ValueError(
                f"{var}={raw!r} is not an integer (multi-host launch "
                "env, docs/userguide.md 'Multi-host runs')"
            ) from None

    num_processes = from_env(num_processes, "DGEN_NUM_PROCESSES")
    process_id = from_env(process_id, "DGEN_PROCESS_ID")
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    # an import-time compilecache.enable() (bench, conftest, graft
    # entry) could not see the backend yet; re-check the gloo refusal
    # now that process_count/backend are known
    from dgen_tpu.utils import compilecache

    compilecache.ensure_safe_for_backend()
    return True


def shard_states_from_env() -> Optional[List[str]]:
    """The per-task state list, if launched via :func:`shard_commands`."""
    raw = os.environ.get("DGEN_SHARD_STATES")
    return [s for s in raw.split(",") if s] if raw else None


def pin_platform_from_env() -> None:
    """Apply ``DGEN_PLATFORM`` / ``DGEN_CPU_DEVICES`` /
    ``JAX_CPU_COLLECTIVES_IMPLEMENTATION`` in-process BEFORE backend
    bring-up.  Needed on hosts whose site hooks import jax at
    interpreter startup, where the plain env vars are silently baked
    into an already-chosen backend — shared by :func:`main` and the
    gang worker (:mod:`dgen_tpu.resilience.gangworker`)."""
    plat = os.environ.get("DGEN_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    if os.environ.get("DGEN_CPU_DEVICES"):
        from dgen_tpu.utils import compat

        compat.set_cpu_device_count(int(os.environ["DGEN_CPU_DEVICES"]))
    impl = os.environ.get("JAX_CPU_COLLECTIVES_IMPLEMENTATION")
    if impl:
        # multi-process CPU gangs need gloo collectives selected before
        # the first backend client is created; the env var alone does
        # not survive a site hook's early jax import
        import jax

        jax.config.update("jax_cpu_collectives_implementation", impl)


def main() -> None:
    """Per-shard entrypoint (``python -m dgen_tpu.parallel.launch``):
    runs a reference-input scenario for this shard's states.

    Env contract (the batch_job_yamls analogue): ``DGEN_SHARD_STATES``
    (comma list, from :func:`shard_commands`), optional
    ``DGEN_INPUT_ROOT`` (default the reference mount),
    ``DGEN_RUN_DIR`` (default ./runs/shard_<i>), ``DGEN_AGENTS``
    (synthetic population size until a converted package is supplied
    via ``DGEN_PACKAGE``), plus the multi-host vars read by
    :func:`initialize_multihost`.

    ``DGEN_PLATFORM`` / ``DGEN_CPU_DEVICES`` force the jax platform
    in-process BEFORE backend bring-up — needed on hosts whose site
    hooks pin a platform at interpreter startup, where the plain
    ``JAX_PLATFORMS`` env var is silently overridden (CI runs the
    launch entrypoint on virtual CPU devices this way).
    """
    pin_platform_from_env()
    distributed = initialize_multihost()

    from dgen_tpu.utils import compilecache

    # no-op on multi-process CPU (gloo) backends — enable() itself
    # refuses there; see its docstring for the rendezvous-timeout story
    compilecache.enable()

    import jax
    import jax.numpy as jnp

    from dgen_tpu.config import RunConfig, ScenarioConfig
    from dgen_tpu.io import package as pkg
    from dgen_tpu.io import synth
    from dgen_tpu.io.export import RunExporter
    from dgen_tpu.io.reference_inputs import (
        scenario_inputs_from_reference,
        wholesale_profile_bank,
    )
    from dgen_tpu.models.agents import ProfileBank
    from dgen_tpu.models.simulation import Simulation
    from dgen_tpu.parallel.mesh import default_mesh

    shard = int(os.environ.get("DGEN_SHARD_INDEX", "0"))
    states = shard_states_from_env() or ["DE"]
    root = os.environ.get(
        "DGEN_INPUT_ROOT", "/root/reference/dgen_os/input_data")
    run_dir = os.environ.get("DGEN_RUN_DIR", f"./runs/shard_{shard}")

    cfg = ScenarioConfig(name=f"shard{shard}", start_year=2014,
                         end_year=int(os.environ.get("DGEN_END_YEAR", 2040)))

    if os.environ.get("DGEN_PACKAGE"):
        pop = pkg.load_population(os.environ["DGEN_PACKAGE"])
        input_states = pop.states
        inputs, meta = scenario_inputs_from_reference(
            root, cfg, input_states)
        profiles = pop.profiles
    else:
        # synthetic populations index the full state list even when only
        # the shard's states are populated, so inputs must cover it too
        input_states = list(synth.STATES)
        inputs, meta = scenario_inputs_from_reference(
            root, cfg, input_states)
        pop = synth.generate_population(
            int(os.environ.get("DGEN_AGENTS", "4096")), states=states,
            seed=shard, n_regions=len(meta["regions"]),
        )
        profiles = ProfileBank(
            load=pop.profiles.load, solar_cf=pop.profiles.solar_cf,
            wholesale=jnp.asarray(wholesale_profile_bank(meta, root)),
        )

    # production placement: the 2-D hosts x devices grid under
    # jax.distributed, the flat agent mesh single-host, DGEN_TPU_MESH
    # to force a shape (parallel.mesh.default_mesh)
    mesh = default_mesh()
    sim = Simulation(pop.table, profiles, pop.tariffs, inputs, cfg,
                     RunConfig.from_env(), mesh=mesh)
    # one persistence path for single- AND multi-host runs: orbax saves
    # sharded carries collectively, and the exporter writes each
    # process's local shard rows (io.export) — the distributed-run
    # analogue of the reference's always-persisted per-task outputs
    # (dgen_model.py:459-462)
    from dgen_tpu.io.export import static_frame_from_table

    exporter = RunExporter(
        run_dir, agent_id=sim.host_agent_id, mask=sim.host_mask,
        state_names=list(input_states),
        static_frame=(
            static_frame_from_table(pop.table, states=list(input_states))
            if jax.process_count() == 1 else None
        ),
        meta={
            "scenario": cfg.name, "shard": shard,
            "states": list(states),
            "distributed": bool(distributed),
            "n_processes": jax.process_count(),
            "market_curves": meta["market_curves"],
            "data_sources": meta.get("data_sources", {}),
        },
    )
    res = run_with_recovery(
        sim, os.path.join(run_dir, "ckpt"), callback=exporter,
        collect=False,
    )
    ran = pop.states if os.environ.get("DGEN_PACKAGE") else states
    print(f"shard {shard} ({','.join(ran)}): "
          f"{len(res.years)} years -> {run_dir}")


def run_with_recovery(sim, checkpoint_dir: str, max_retries: int = 3,
                      **run_kwargs):
    """Run a Simulation with crash recovery: the analogue of the
    reference's GCP Batch ``maxRetryCount: 3`` + SPOT re-runs
    (batch_job_yamls/...:10, submit_all.sh:15) — except a re-run here
    resumes from the last per-year orbax checkpoint instead of
    restarting the whole state task from scratch (the reference re-runs
    the entire task and relies on a fresh output schema for
    idempotency, data_functions.py:158).
    """
    from dgen_tpu.io import checkpoint as ckpt

    user_resume = run_kwargs.pop("resume", None)

    def should_resume(attempt: int) -> bool:
        if attempt > 0:
            return True
        if user_resume is not None:
            return bool(user_resume)
        # fresh process after a preemption: resume iff checkpoints exist
        try:
            return ckpt.latest_year(checkpoint_dir) is not None
        except (FileNotFoundError, OSError):
            return False

    import jax

    # In-process retries are only sound single-controller: after a
    # failed collective in a multi-process (jax.distributed) run the
    # runtime is degraded and a lone process re-entering sim.run would
    # hang at the next collective/orbax barrier. Re-raise instead so
    # the cluster scheduler's task-level restart (the reference's
    # maxRetryCount, batch_job_yamls/...:10) relaunches EVERY process;
    # the fresh run resumes from the last checkpoint via should_resume.
    retries = max_retries if jax.process_count() == 1 else 0

    last_err = None
    for attempt in range(retries + 1):
        try:
            return sim.run(
                checkpoint_dir=checkpoint_dir,
                resume=should_resume(attempt),
                **run_kwargs,
            )
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:  # noqa: BLE001 - recovery boundary
            last_err = e
            import logging

            logging.getLogger("dgen_tpu").warning(
                "run attempt %d/%d failed: %s", attempt + 1,
                retries + 1, e,
            )
    raise last_err


if __name__ == "__main__":
    main()
