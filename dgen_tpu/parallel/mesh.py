"""Device mesh construction.

The default mesh is 1-D: one "agents" axis carries all data
parallelism — the agent population is embarrassingly parallel within a
year (SURVEY.md §2.6) and the only cross-agent communication is small
state x sector reductions, so a single axis with psum collectives over
ICI is the whole comms design.

Pod-scale national runs use a true 2-D **hosts x devices** grid (the
SNIPPETS.md [1]/[3] NamedSharding placement pattern): the agent axis
then spans BOTH mesh axes — row-major, so a (1, D) grid is placement-
identical to the 1-D mesh — and DCN carries the host-axis slice of the
(tiny) reductions while ICI carries the device-axis slice. Everything
that builds an agent-axis PartitionSpec goes through
:func:`agent_spec`/:func:`agent_axes` so a 2-D mesh shards over both
axes instead of silently replicating across host rows.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec

AGENT_AXIS = "agents"
HOST_AXIS = "hosts"


def default_mesh_shape(n_devices: Optional[int] = None) -> Tuple[int, int]:
    """The production (hosts, devices) grid for this topology.

    ``DGEN_TPU_MESH=HxD`` forces a shape (:func:`parse_mesh_shape`);
    otherwise a jax.distributed run gets the true 2-D
    ``process_count x local-devices`` grid — the placement every
    run mesh should default to at pod scale, so the host axis of the
    (tiny) cross-shard reductions rides DCN-grouped collectives — and
    a single-process run gets the flat 1-D agent mesh ``(1, D)``.
    """
    raw = os.environ.get("DGEN_TPU_MESH", "").strip()
    if raw:
        return parse_mesh_shape(raw)
    total = len(jax.devices()) if n_devices is None else int(n_devices)
    procs = jax.process_count()
    if procs > 1 and total % procs == 0:
        return (procs, total // procs)
    return (1, total)


def default_mesh(devices: Optional[Sequence] = None) -> Optional[Mesh]:
    """The production run mesh (or None on a single device).

    One constructor for every production entry point (parallel.launch,
    the gang worker, the sweep CLI, the scale bench), so national runs
    land on the 2-D hosts x devices grid by default instead of each
    caller hand-rolling ``make_mesh()`` flat.
    """
    devs = list(devices if devices is not None else jax.devices())
    h, d = default_mesh_shape(len(devs))
    if h * d <= 1:
        return None
    return make_mesh(devices=devs, shape=(h, d))


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None,
              shape: Optional[Tuple[int, int]] = None) -> Mesh:
    """Build the run mesh.

    ``shape``: optional (hosts, devices) grid. ``(1, D)`` (or None)
    builds the 1-D agent mesh over D devices; ``(H, D)`` with H > 1
    builds the 2-D hosts x devices mesh whose axes are
    ``(HOST_AXIS, AGENT_AXIS)`` and whose device order is row-major —
    so the agent-axis placement (which spans both axes, see
    :func:`agent_spec`) assigns devices identically to the flat 1-D
    mesh and only the collective GROUPING is topology-aware.
    """
    devs = list(devices if devices is not None else jax.devices())
    if shape is not None:
        h, d = int(shape[0]), int(shape[1])
        need = h * d
        if len(devs) < need:
            raise ValueError(
                f"mesh shape {h}x{d} needs {need} devices, "
                f"{len(devs)} available"
            )
        devs = devs[:need]
        if h > 1:
            return Mesh(
                np.asarray(devs).reshape(h, d), (HOST_AXIS, AGENT_AXIS)
            )
        return Mesh(np.asarray(devs), (AGENT_AXIS,))
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (AGENT_AXIS,))


def agent_axes(mesh: Mesh) -> Tuple[str, ...]:
    """The mesh axis names the agent dimension shards over — ALL of
    them: every axis of a dgen mesh carries agents (a hosts x devices
    grid shards the table over both; nothing else is ever mesh-sharded
    — banks/inputs ride replicated)."""
    return tuple(mesh.axis_names)


def agent_spec(mesh: Mesh, ndim: int = 1, axis: int = 0) -> PartitionSpec:
    """PartitionSpec sharding dimension ``axis`` of an ``ndim``-rank
    array over the mesh's agent axes, everything else replicated.

    One constructor for every agent-axis placement in the tree
    (Simulation placement, the chunked-scan constraint, the shard_map
    kernel specs, elastic restore) so a 2-D mesh cannot be half-adopted:
    P("agents") on a hosts x devices grid would shard 4-ways and
    REPLICATE across host rows — exactly the regression the mesh
    auditor (docs/lint.md J8) exists to catch.
    """
    names = agent_axes(mesh)
    entry: Union[str, Tuple[str, ...]] = (
        names[0] if len(names) == 1 else names
    )
    dims = [None] * ndim
    dims[axis] = entry
    return PartitionSpec(*dims)


def mesh_shape_of(mesh: Mesh) -> Tuple[int, int]:
    """(hosts, devices) shape of a run mesh (1-D meshes report
    hosts=1)."""
    ax = dict(mesh.shape)
    return (int(ax.get(HOST_AXIS, 1)), int(ax[AGENT_AXIS]))


def parse_mesh_shape(label: str) -> Tuple[int, int]:
    """'HxD' -> (H, D), e.g. '1x8' or '2x4' (the mesh-audit grid
    vocabulary, docs/lint.md)."""
    parts = label.lower().split("x")
    if len(parts) != 2:
        raise ValueError(
            f"bad mesh shape '{label}' (expected HxD, e.g. 2x4)"
        )
    try:
        h, d = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(
            f"bad mesh shape '{label}' (expected HxD, e.g. 2x4)"
        ) from None
    if h < 1 or d < 1:
        raise ValueError(f"bad mesh shape '{label}' (axes must be >= 1)")
    return h, d
