"""Device mesh construction.

One 1-D mesh axis ("agents") carries all data parallelism: the agent
population is embarrassingly parallel within a year (SURVEY.md §2.6) and
the only cross-agent communication is small state x sector reductions,
so a single axis with psum collectives over ICI is the whole comms
design. Multi-slice (DCN) national runs reuse the same axis — XLA routes
the (tiny) psums appropriately.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AGENT_AXIS = "agents"


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (AGENT_AXIS,))
