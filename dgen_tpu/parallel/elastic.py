"""Elastic resharded restore: resume a checkpoint written at P
processes under a P′-process topology.

A jax.distributed gang is not elastic mid-run — one lost host kills
every collective, and the correct recovery is tear-down-and-relaunch
(:mod:`dgen_tpu.resilience.gang`).  What CAN be elastic is the
*restore*: orbax persists the cross-year :class:`~dgen_tpu.models.
simulation.SimCarry` as a global array (each process wrote its
addressable shards), so a relaunched gang of a DIFFERENT size re-places
the same global carry under its OWN mesh's NamedSharding (the
SNIPPETS.md [1]/[3] pattern: a sharded ShapeDtypeStruct template hands
orbax the target layout, and each process reads exactly the shards it
now owns).  A run that lost a host permanently resumes on fewer
workers instead of dying.

Two invariants make this sound:

* the checkpoint is keyed by the PADDED global agent count, which is a
  property of the population (``pad_table``), not of the topology that
  wrote it — so the global shape matches across P -> P′;
* the restored carry feeds the same ``year_step`` executable path; only
  the placement changed, so no program is re-derived here (the new
  topology compiles its own program exactly as a fresh run would).
"""

from __future__ import annotations

from typing import Optional, Tuple

from jax.sharding import Mesh, NamedSharding

from dgen_tpu.parallel.mesh import agent_spec
from dgen_tpu.utils.logging import get_logger

logger = get_logger()


def carry_sharding(mesh: Optional[Mesh]) -> Optional[NamedSharding]:
    """The agent-axis NamedSharding a SimCarry restores onto under
    ``mesh`` (None = single-device host restore)."""
    if mesh is None:
        return None
    return NamedSharding(mesh, agent_spec(mesh))


def validate_topology(n_agents: int, mesh: Optional[Mesh]) -> None:
    """Fail fast (with the fix named) when the padded agent table does
    not divide over the new topology's device count — the one way an
    elastic restore can be impossible."""
    if mesh is None:
        return
    d = int(mesh.devices.size)
    if n_agents % d:
        raise ValueError(
            f"elastic restore: padded agent count {n_agents} does not "
            f"divide over {d} devices; pad the population to a multiple "
            "of the largest device count the run may shrink through "
            "(models.agents.pad_table / RunConfig.agent_pad_multiple)"
        )


def restore_resharded(
    checkpoint_dir: str,
    n_agents: int,
    year: Optional[int] = None,
    mesh: Optional[Mesh] = None,
    scenario: Optional[str] = None,
) -> Tuple[int, object]:
    """(year, carry): restore a checkpointed SimCarry under the CURRENT
    topology, regardless of the process/device topology that wrote it.

    Passing the new mesh's sharding makes orbax read each process's
    now-addressable shards straight to their devices — no full-array
    host copy, no dependence on the writing gang's shard layout."""
    from dgen_tpu.io import checkpoint as ckpt

    validate_topology(n_agents, mesh)
    return ckpt.restore_year(
        checkpoint_dir, n_agents, year,
        sharding=carry_sharding(mesh), scenario=scenario,
    )


def resume_year_for(
    checkpoint_dir: str,
    n_agents: int,
    frontier: Optional[int],
    mesh: Optional[Mesh] = None,
    scenario: Optional[str] = None,
) -> Optional[int]:
    """The year a relaunched gang re-enters at: the newest checkpoint
    that actually RESTORES under the CURRENT topology, capped at the
    manifest frontier (never resume past a year whose exports are not
    durably on disk), walking back past corrupt/torn steps
    (:func:`dgen_tpu.io.checkpoint.latest_valid_year`).  ``None`` (no
    frontier, or nothing restorable at or below it) means restart from
    scratch.

    Every worker of a gang evaluates this against the same shared
    directory in the same order, so all P′ processes independently
    agree on the resume year — and the validation restores are
    themselves collective, issued in lockstep."""
    if frontier is None:
        return None
    from dgen_tpu.io import checkpoint as ckpt

    validate_topology(n_agents, mesh)
    return ckpt.latest_valid_year(
        checkpoint_dir, n_agents, max_year=frontier,
        sharding=carry_sharding(mesh), scenario=scenario,
    )
